"""Driver-entry guards: bench.py's host-only mode must stay runnable
(the TPU modes need the tunnel, but argument parsing, RecordIO synthesis,
the native pipeline, and the JSON contract are all exercisable on CPU —
if this breaks, the driver's end-of-round capture breaks with it)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_comm_smoke_json_contract():
    """--comm-bench --smoke is the CI guard on the comm bench entry (tiny
    shapes, CPU mesh, no file written): one JSON line with the contract
    keys, all four modes measured, and the int8 plan ratio sane."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--comm-bench",
         "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "modes"):
        assert key in blob, blob
    assert blob["value"] > 1.0  # int8 moves fewer bytes than fp32
    assert set(blob["modes"]) == {"none", "bf16", "int8", "twobit"}
    for mode, row in blob["modes"].items():
        assert row["hlo_wire_bytes_per_step"] > 0, mode
        assert row["step_ms"] > 0, mode
    # int8 is integer-typed on the wire, so CPU HLO shows it faithfully:
    # compiled reality must agree with the closed-form plan
    assert blob["modes"]["int8"]["hlo_wire_bytes_per_step"] == pytest.approx(
        blob["modes"]["int8"]["plan_wire_bytes_per_step"], rel=0.02)
    assert blob["smoke"] is True  # smoke runs never write BENCH_COMM_*.json


def test_bench_telemetry_smoke_json_contract():
    """--telemetry-bench --smoke is the CI guard on the telemetry bench
    entry: one JSON line with the contract keys, hub op costs measured,
    and the acceptance bound — hub overhead under 2% of the baseline step
    on the 8-virtual-device smoke run."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--telemetry-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "emit_ns",
                "observe_ns", "counter_ns", "step_ms_baseline",
                "step_ms_telemetry", "timeline_overhead_pct"):
        assert key in blob, blob
    assert blob["metric"] == "telemetry_hub_overhead_pct_of_step"
    assert blob["emit_ns"] > 0 and blob["step_ms_baseline"] > 0
    # the acceptance bound: hub instrumentation costs <2% of a step
    assert 0 < blob["value"] < 2.0, blob
    assert blob["smoke"] is True  # smoke runs never write BENCH_TELEMETRY_*


def test_bench_trace_smoke_json_contract():
    """--trace-bench --smoke is the CI guard on the distributed-tracing
    bench entry: one JSON line with the contract keys, per-op tracing
    costs measured, and the ISSUE 6 acceptance bound — flight recorder +
    trace propagation under 2% of the dp-8 baseline step."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--trace-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "note_ns",
                "sink_ns", "ctx_ns", "mint_ns", "step_ms_baseline",
                "step_ms_traced", "traced_overhead_pct",
                "flight_steps_recorded"):
        assert key in blob, blob
    assert blob["metric"] == "trace_flight_overhead_pct_of_step"
    assert blob["note_ns"] > 0 and blob["step_ms_baseline"] > 0
    # the acceptance bound: always-on tracing costs <2% of a step
    assert 0 < blob["value"] < 2.0, blob
    assert blob["flight_steps_recorded"] > 0  # the black box was live
    assert blob["smoke"] is True  # smoke runs never write BENCH_TRACE_*


def test_bench_mem_smoke_json_contract():
    """--mem-bench --smoke is the CI guard on the memory-observability
    bench entry: one JSON line with the contract keys, ledger/sampler op
    costs measured, a live watermark recorded, at least one program plan
    registered, and the ISSUE 9 acceptance bound — ledger + sampler
    under 2% of the dp-8 baseline step."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mem-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "add_ns",
                "sample_ns", "step_ms_baseline", "step_ms_tracked",
                "tracked_overhead_pct", "watermark_mb",
                "memory_plans_registered"):
        assert key in blob, blob
    assert blob["metric"] == "memory_ledger_overhead_pct_of_step"
    assert blob["add_ns"] > 0 and blob["step_ms_baseline"] > 0
    # the acceptance bound: memory accounting costs <2% of a step
    assert 0 < blob["value"] < 2.0, blob
    assert blob["watermark_mb"] > 0  # the ledger saw the tracked run
    assert blob["memory_plans_registered"] >= 1  # AOT plan registered
    assert blob["smoke"] is True  # smoke runs never write BENCH_MEM_*


def test_bench_health_smoke_json_contract():
    """--health-bench --smoke is the CI guard on the training-health
    bench entry (ISSUE 14): one JSON line with the contract keys, the
    ISSUE 14 acceptance bound — on-device stats overhead < 2% of the
    dp-8 step's FLOPs — a per-layer table from the instrumented run, and
    the injected-anomaly detection latencies (nonfinite in 0 extra
    steps, explosion/spike within 1)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--health-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "flops_per_step_baseline", "flops_per_step_health",
                "step_ms_baseline", "step_ms_health", "wall_overhead_pct",
                "health_events", "layers", "detect_latency_steps"):
        assert key in blob, blob
    assert blob["metric"] == "health_stats_overhead_pct_of_step"
    # ACCEPTANCE: the in-graph stats cost < 2% of the step's FLOPs
    assert 0 < blob["value"] < 2.0, blob
    assert blob["flops_per_step_health"] > blob["flops_per_step_baseline"]
    # the instrumented run streamed per-layer stats
    assert blob["health_events"] > 0
    assert {row["layer"] for row in blob["layers"]} == {"fc1", "fc2"}
    for row in blob["layers"]:
        assert row["max_grad_norm"] > 0, row
    # ACCEPTANCE: detectors catch the injected anomalies promptly
    lat = blob["detect_latency_steps"]
    assert lat["nonfinite"] == 0
    assert lat["grad_explosion"] is not None and lat["grad_explosion"] <= 1
    assert lat["loss_spike"] is not None and lat["loss_spike"] <= 1
    assert blob["smoke"] is True  # smoke runs never write BENCH_HEALTH_*


def test_bench_overlap_smoke_json_contract():
    """--overlap-bench --smoke is the CI guard on the comm/compute
    overlap bench entry: one JSON line with the contract keys, the
    per-bucket schedule proven structurally (>= 2 independent HLO
    collective pairs, per-bucket plans summing exactly to the fused
    plan), the stale-sync pipeline strictly beating the serial
    schedule, a positive overlap-efficiency gauge, and the telemetry
    tax under the 2% invariant."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--overlap-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mesh",
                "stale_sync", "overlap_efficiency",
                "telemetry_overhead_pct"):
        assert key in blob, blob
    assert blob["metric"] == "overlap_bench_stale_sync_speedup"
    # ACCEPTANCE: the overlapped schedule strictly beats the serial one
    assert blob["value"] > 1.0, blob
    assert blob["stale_sync"]["step_ms_pipelined"] < \
        blob["stale_sync"]["step_ms_serial"]
    # ACCEPTANCE: >= 2 independent per-bucket collective pair groups in
    # the compiled HLO, and the plan arithmetic is exact vs fused
    assert blob["mesh"]["hlo_independent_pairs"] >= 2, blob["mesh"]
    assert blob["mesh"]["num_buckets"] >= 2
    assert blob["mesh"]["plan_matches_fused"] is True
    assert blob["mesh"]["loss_parity"] is True
    # ACCEPTANCE: efficiency gauge exported and positive, telemetry tax
    # within the <2% invariant
    assert blob["overlap_efficiency"] > 0, blob
    assert 0 <= blob["telemetry_overhead_pct"] < 2.0, blob
    assert blob["smoke"] is True  # smoke runs never write BENCH_OVERLAP_*


def test_bench_elastic_smoke_json_contract():
    """--elastic-bench --smoke is the CI guard on the elastic-training
    bench entry (ISSUE 10): one JSON line with the contract keys, both
    resizes (8->6 shrink, 6->8 regrow) executed with measured downtime,
    per-world step times, and the resize badput priced into goodput."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--elastic-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "shrink_downtime_s", "grow_downtime_s", "resizes",
                "worlds", "step_ms_by_world", "goodput_pct_by_epoch",
                "resize_badput_s"):
        assert key in blob, blob
    assert blob["metric"] == "elastic_resize_downtime_seconds"
    # both resizes happened and were priced
    assert blob["resizes"] == 2
    assert blob["worlds"] == [6, 8]
    assert blob["shrink_downtime_s"] > 0
    assert blob["grow_downtime_s"] > 0
    assert blob["resize_badput_s"] > 0
    # training ran at every world size
    for world in ("8_pre", "6", "8_post"):
        assert blob["step_ms_by_world"].get(world, 0) > 0, blob
    assert blob["smoke"] is True  # smoke runs never write BENCH_ELASTIC_*


def test_bench_ckpt_smoke_json_contract():
    """--ckpt-bench --smoke is the CI guard on the async-checkpoint bench
    entry (ISSUE 17): one JSON line with the contract keys, the async
    step stall under the 10%-of-sync acceptance bound, both recovery
    tiers exercised (peer RAM restore + chaos-forced disk fallback), and
    checkpoint badput priced at all three cadences."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--ckpt-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "async_stall_ms", "sync_save_ms", "peer_recovery_s",
                "disk_recovery_s", "badput_by_cadence"):
        assert key in blob, blob
    assert blob["metric"] == "ckpt_async_stall_pct_of_sync"
    # ACCEPTANCE: the async save stalls the step loop <10% of a sync save
    assert 0 < blob["value"] < 10.0, blob
    assert blob["async_stall_ms"] < blob["sync_save_ms"]
    # both recovery paths ran: T1 with replication live, T2 under chaos
    assert blob["peer_recovery_tier"] == "t1"
    assert blob["disk_recovery_tier"] == "t2"
    assert blob["peer_recovery_s"] > 0 and blob["disk_recovery_s"] > 0
    # badput priced at every cadence, monotone non-increasing with cadence
    rows = blob["badput_by_cadence"]
    assert set(rows) == {"1", "4", "16"}
    assert all(r["badput_s_per_epoch"] >= 0 for r in rows.values())
    assert rows["16"]["badput_s_per_epoch"] <= rows["1"]["badput_s_per_epoch"]
    assert blob["smoke"] is True  # smoke runs never write BENCH_CKPT_*


def test_bench_controller_smoke_json_contract():
    """--controller-bench --smoke is the CI guard on the fleet-controller
    bench entry (ISSUE 12): one JSON line with the contract keys, the
    blamed straggler really evicted by the armed run, a compression tier
    auto-picked, the breaker never tripped, and the armed fleet's
    steady-state per-chip throughput recovering a positive fraction of
    what the straggler cost the static fleet."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--controller-bench", "--smoke"],
        capture_output=True, text=True, timeout=560, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "tpc_clean",
                "tpc_static", "tpc_controller", "final_step_ms",
                "evicted", "backfilled", "tier_chosen", "retier_actions",
                "worlds", "breaker_state", "decisions_total"):
        assert key in blob, blob
    assert blob["metric"] == "controller_goodput_recovered_frac"
    # the closed loop actually closed: blame -> evict -> recover
    assert blob["evicted"] == [7]
    assert blob["tier_chosen"] in ("bf16", "int8", "twobit")
    assert blob["breaker_state"] == "closed"
    # the straggler really cost the static fleet, and the armed fleet
    # bought a solid share back (generous margin: shared-box timing)
    assert blob["tpc_static"] < blob["tpc_clean"]
    assert blob["value"] is not None and blob["value"] > 0.2, blob
    assert blob["smoke"] is True  # smoke runs never write BENCH_CONTROLLER_*


def test_bench_lockwatch_smoke_json_contract():
    """--lockwatch-bench --smoke is the CI guard on the lock-order
    watchdog bench (ISSUE 11): one JSON line with the contract keys,
    ZERO lock-order cycles across both soaks (group-kvstore membership
    churn + elastic-resize fit), the kvstore soak finishing without a
    hang, and the acceptance bound — watchdog overhead under 2% of a
    dp-4 step (priced per-pair x acquisitions/step, robust to
    shared-box noise)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--lockwatch-bench", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "pair_ns_off",
                "pair_ns_on", "pair_delta_ns", "acquires_per_step",
                "step_ms", "cycles", "max_hold_ms", "kv_soak",
                "resizes", "worlds"):
        assert key in blob, blob
    assert blob["metric"] == "lockwatch_overhead_pct_of_step"
    # ACCEPTANCE: zero lock-order cycles in both soaks, no kv hang
    assert blob["cycles"] == 0, blob
    assert blob["kv_soak"]["cycles"] == 0, blob
    assert blob["kv_soak"]["hung"] is False, blob
    # ACCEPTANCE: the armed watchdog costs <2% of a step
    assert 0 <= blob["value"] < 2.0, blob
    assert blob["pair_ns_on"] > blob["pair_ns_off"] > 0
    assert blob["acquires_per_step"] > 0 and blob["step_ms"] > 0
    # both elastic resizes committed under the watchdog
    assert blob["resizes"] == 2 and blob["worlds"] == [3, 4], blob
    assert blob["smoke"] is True  # smoke runs never write BENCH_LOCKWATCH_*


def test_bench_kernel_smoke_json_contract():
    """--kernel-bench --smoke is the CI guard on the Pallas kernel-layer
    bench (ISSUE 13): one JSON line with the contract keys, a roofline
    row per kernel (registry FLOP/byte model + measured interpret-mode
    time), the fused-vs-unfused HLO acceptance — the kernel path removes
    EVERY full-slab quantize pass while moving byte-identical
    collectives — and fused-Adam bitwise parity."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--kernel-bench",
         "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "kernels",
                "hlo_fused_vs_unfused", "wire_bytes_identical",
                "fused_adam", "int8_matmul_rel_error", "catalog"):
        assert key in blob, blob
    assert blob["metric"] == "kernel_bench_full_slab_quantize_passes_removed"
    # ACCEPTANCE: the codec path runs full-slab quantize passes, the
    # kernel path runs none, and the wire bytes are identical
    hlo = blob["hlo_fused_vs_unfused"]
    assert hlo["codec"]["full_slab_quantize_passes"] > 0, blob
    assert hlo["kernels"]["full_slab_quantize_passes"] == 0, blob
    assert blob["value"] == hlo["codec"]["full_slab_quantize_passes"]
    assert blob["wire_bytes_identical"] is True, blob
    # a roofline row per kernel family, each priced by the registry
    row_names = {k["kernel"] for k in blob["kernels"]}
    assert {"flash_attention_fwd", "flash_attention_fwd_bwd", "quant_int8",
            "quant_twobit", "dequant_sum_int8", "fused_adam",
            "int8_matmul"} <= row_names
    for row in blob["kernels"]:
        assert row["model_flops"] > 0 and row["model_bytes"] > 0, row
        assert row["ms"] > 0 and row["achieved_gflops_s"] > 0, row
        assert row["kernels_in_program"], row
    # ACCEPTANCE: fused sharded-Adam step-time row + exact parity
    assert blob["fused_adam"]["bitwise_parity"] is True, blob
    assert blob["fused_adam"]["fused_ms"] > 0
    assert blob["fused_adam"]["per_leaf_ms"] > 0
    assert 0 < blob["int8_matmul_rel_error"] < 0.02, blob
    # the catalog covers every registered kernel
    assert {c["kernel"] for c in blob["catalog"]} >= {
        "flash_fwd", "fused_adam", "quant_int8", "int8_matmul"}
    assert blob["smoke"] is True  # smoke runs never write BENCH_KERNELS_*


def test_bench_profile_smoke_json_contract():
    """--profile-bench --smoke is the CI guard on the device-time
    profiler bench (ISSUE 15): one JSON line with the contract keys, the
    acceptance bounds — >= 80% of in-window device time attributed to
    named layers/kernels, out-of-window overhead < 0.5% of a step — a
    top-K hotspot table, measured roofline rows stamped
    source="measured", a measured-vs-modeled MFU delta, and the capture
    window priced as profile badput."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--profile-bench",
         "--smoke"],
        capture_output=True, text=True, timeout=560, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "window_steps",
                "device_ms", "unattributed_ms", "layers_ms", "top",
                "roofline", "measured_mfu_pct", "mfu_delta_pct",
                "profile_badput_s", "out_of_window_poll_ns",
                "out_of_window_overhead_pct", "step_ms"):
        assert key in blob, blob
    assert blob["metric"] == "profile_attribution_coverage_pct"
    # ACCEPTANCE: >= 80% of in-window device time named, remainder
    # reported explicitly
    assert blob["value"] >= 80.0, blob
    assert blob["unattributed_ms"] >= 0.0
    # model layers really attributed (not just the pseudo-categories)
    assert {"fc1", "fc2"} <= set(blob["layers_ms"]), blob["layers_ms"]
    assert blob["top"] and blob["top"][0]["ms"] > 0
    # measured roofline rows: source=measured, joined FLOP models, a
    # bound classification per row
    assert blob["roofline"], blob
    for row in blob["roofline"]:
        assert row["source"] == "measured", row
        assert row["model_flops"] > 0 and row["measured_ms_per_step"] > 0
        assert row.get("bound") in ("compute", "bandwidth"), row
    # the measured-vs-modeled reconciliation resolved
    assert blob["measured_mfu_pct"] is not None
    assert blob["mfu_delta_pct"] is not None
    # ACCEPTANCE: out-of-window overhead < 0.5% of a step; the window
    # itself priced as profile badput
    assert 0 <= blob["out_of_window_overhead_pct"] < 0.5, blob
    assert blob["profile_badput_s"] > 0
    assert blob["smoke"] is True  # smoke runs never write BENCH_PROFILE_*


def test_kernel_bench_roofline_rows_carry_source():
    """ISSUE 15 satellite: every --kernel-bench roofline row is stamped
    with its provenance (interpret on the CPU rig) so an interpret-mode
    estimate can never be read as a device measurement. Asserted on the
    committed artifact so the full-run schema is pinned without re-running
    the bench."""
    path = os.path.join(REPO, "BENCH_KERNELS_r16.json")
    with open(path) as f:
        blob = json.load(f)
    assert blob["kernels"], blob
    for row in blob["kernels"]:
        assert row.get("source") in ("interpret", "measured"), row
        # the CPU artifact ran under the Pallas interpreter
        if blob.get("interpret_mode"):
            assert row["source"] == "interpret", row


@pytest.mark.slow
def test_bench_pipeline_mode_json_contract(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode",
         "pipeline", "--recordio", str(tmp_path / "b.rec"),
         "--num-images", "64"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    # the contract: ONE JSON line on stdout with the required keys
    lines = [l for l in r.stdout.strip().splitlines()
             if l.startswith("{")]
    assert len(lines) == 1, r.stdout
    blob = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in blob, blob
    assert blob["value"] > 0
