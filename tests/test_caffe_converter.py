"""Caffe converter tests (reference: tools/caffe_converter/ — prototxt ->
Symbol + weight conversion, here dependency-free)."""

import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from caffe_converter import convert_weights, load_npz_blobs, proto_to_symbol  # noqa: E402
from caffe_converter.prototxt import first, parse  # noqa: E402

LENET_PROTOTXT = """
name: "LeNet"
input: "data"
input_dim: 2
input_dim: 1
input_dim: 28
input_dim: 28
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 1 }
}
layer {
  name: "relu1"
  type: "ReLU"
  bottom: "conv1"
  top: "conv1"
}
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "ip1"
  type: "InnerProduct"
  bottom: "pool1"
  top: "ip1"
  inner_product_param { num_output: 10 }
}
layer {
  name: "prob"
  type: "Softmax"
  bottom: "ip1"
  top: "prob"
}
"""


def test_prototxt_parser():
    msg = parse(LENET_PROTOTXT)
    assert first(msg, "name") == "LeNet"
    assert msg["input_dim"] == [2, 1, 28, 28]
    layers = msg["layer"]
    assert len(layers) == 5
    conv = first(layers[0], "convolution_param")
    assert first(conv, "num_output") == 8
    assert first(first(layers[2], "pooling_param"), "pool") == "MAX"


def test_proto_to_symbol_shapes():
    symbol, input_shapes = proto_to_symbol(LENET_PROTOTXT)
    assert input_shapes["data"] == (2, 1, 28, 28)
    args = symbol.list_arguments()
    for name in ("conv1_weight", "conv1_bias", "ip1_weight", "ip1_bias"):
        assert name in args, args
    arg_shapes, out_shapes, _ = symbol.infer_shape(data=(2, 1, 28, 28))
    shape_of = dict(zip(args, arg_shapes))
    assert shape_of["conv1_weight"] == (8, 1, 5, 5)
    assert shape_of["ip1_weight"] == (10, 8 * 12 * 12)
    assert out_shapes[0] == (2, 10)


def test_weight_conversion_and_forward(tmp_path):
    symbol, _ = proto_to_symbol(LENET_PROTOTXT)
    rng = np.random.RandomState(0)
    blobs = {
        "conv1": [rng.randn(8, 1, 5, 5).astype(np.float32),
                  rng.randn(8).astype(np.float32)],
        "ip1": [rng.randn(10, 8 * 12 * 12).astype(np.float32),
                rng.randn(10).astype(np.float32)],
    }
    npz = tmp_path / "blobs.npz"
    np.savez(npz, **{f"{l}/{i}": a for l, arrs in blobs.items()
                     for i, a in enumerate(arrs)})
    arg_params = convert_weights(load_npz_blobs(str(npz)), symbol)
    assert set(arg_params) == {"conv1_weight", "conv1_bias",
                               "ip1_weight", "ip1_bias"}

    exe = symbol.simple_bind(mx.cpu(), data=(2, 1, 28, 28))
    for k, v in arg_params.items():
        exe.arg_dict[k][:] = v.asnumpy()
    x = rng.rand(2, 1, 28, 28).astype(np.float32)
    out = exe.forward(data=mx.nd.array(x))[0].asnumpy()
    # numpy replica of the conv->relu->pool->fc->softmax pipeline
    from numpy.lib.stride_tricks import sliding_window_view

    conv = np.zeros((2, 8, 24, 24), np.float32)
    win = sliding_window_view(x, (5, 5), axis=(2, 3))  # (2,1,24,24,5,5)
    for o in range(8):
        conv[:, o] = np.einsum("nchwkl,ckl->nhw", win, blobs["conv1"][0][o]) \
            + blobs["conv1"][1][o]
    relu = np.maximum(conv, 0)
    pooled = relu.reshape(2, 8, 12, 2, 12, 2).max(axis=(3, 5))
    logits = pooled.reshape(2, -1) @ blobs["ip1"][0].T + blobs["ip1"][1]
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs = e / e.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(out, probs, rtol=1e-4, atol=1e-5)


def test_convert_weights_missing_layer_raises():
    symbol, _ = proto_to_symbol(LENET_PROTOTXT)
    with pytest.raises(ValueError, match="ip1"):
        convert_weights({"conv1": [np.zeros((8, 1, 5, 5), np.float32),
                                   np.zeros(8, np.float32)]}, symbol)


def test_v1_layer_types_and_split_concat():
    proto = """
    input: "data"
    input_shape { dim: 1 dim: 4 dim: 8 dim: 8 }
    layers { name: "sp" type: 22 bottom: "data" top: "a" top: "b" }
    layers { name: "c1" type: 4 bottom: "a" top: "c1"
             convolution_param { num_output: 4 kernel_size: 1 } }
    layers { name: "cat" type: 3 bottom: "c1" bottom: "b" top: "cat" }
    layers { name: "loss" type: 21 bottom: "cat" top: "loss" }
    """
    symbol, shapes = proto_to_symbol(proto)
    assert shapes["data"] == (1, 4, 8, 8)
    _, out_shapes, _ = symbol.infer_shape(data=(1, 4, 8, 8))
    assert out_shapes[0] == (1, 8, 8, 8)  # concat of 4+4 channels
