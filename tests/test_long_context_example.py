"""Long-context training-through-ring-attention tier: the example must
LEARN (loss 4.16 uniform -> <1.0) on a dp x sp mesh — proving gradients
flow backward through the ring's collective-permute rotations, not just
that the forward matches dense (tests/test_parallel.py covers that)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_long_context_lm_learns_through_ring_attention():
    script = os.path.join(REPO, "examples", "long_context",
                          "train_long_lm.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, script, "--dp", "2", "--sp", "4"],
        capture_output=True, text=True, timeout=900, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "ring attention sp=4" in r.stdout
