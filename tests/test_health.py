"""Training-health observability acceptance (ISSUE 14).

Covers: layer grouping + config resolution, per-step in-jit stats emitted
as ``health`` events with golden keys and per-layer gauges, the armed
zero-recompile epoch with health stacked on compression + overlap +
fused-Adam + guards, bitwise stat parity between the compressed and
uncompressed step paths, every streaming detector (loss spike, grad
explosion, dead layer, divergence drift, nonfinite), the e2e contract —
an injected exploding layer and an injected NaN step produce a
``health_anomaly`` incident naming the correct layer inside a CRC-valid
flight dump BEFORE the guard-skip event, and the ``telemetry health``
CLI renders it — the on-device overhead bound (<2%% of the step's
FLOPs), and the fleet controller's recommend-only health lever.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import health as health_mod
from mxnet_tpu.utils import compile as cm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_hub():
    telemetry.reset()
    yield


def _ctx8():
    return [mx.cpu(i) for i in range(8)]


def _mlp(hidden=32, classes=4):
    data = mx.sym.Variable("data")
    h1 = mx.sym.Activation(mx.sym.FullyConnected(
        data, name="fc1", num_hidden=hidden), name="a1", act_type="relu")
    return mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        h1, name="fc2", num_hidden=classes), name="softmax")


def _blobs(n=128, dim=8, classes=4, scale=1.0):
    rng = np.random.RandomState(0)
    X = (rng.randn(n, dim) * scale).astype(np.float32)
    y = rng.randint(0, classes, (n,)).astype(np.float32)
    return X, y


def _health_event(step, stats, loss=1.0, finite=True, epoch=0):
    return {"kind": "health", "epoch": epoch, "step": step, "loss": loss,
            "finite": finite, "stats": stats}


def _row(grad=1.0, weight=1.0, ratio=1e-3, nonfinite=0):
    return {"grad_norm": grad, "weight_norm": weight,
            "update_ratio": ratio, "nonfinite": nonfinite}


# -- grouping + config ---------------------------------------------------------

def test_layer_groups_strip_role_suffixes():
    groups = health_mod.layer_groups(
        ["fc1_weight", "fc1_bias", "bn1_gamma", "bn1_beta", "embed"])
    assert groups == {"bn1": ("bn1_beta", "bn1_gamma"),
                      "embed": ("embed",),
                      "fc1": ("fc1_bias", "fc1_weight")}


def test_config_resolution(monkeypatch):
    assert telemetry.HealthConfig.resolve(False) is None
    monkeypatch.delenv("MXNET_TPU_HEALTH", raising=False)
    assert telemetry.HealthConfig.resolve(None) is None
    monkeypatch.setenv("MXNET_TPU_HEALTH", "1")
    cfg = telemetry.HealthConfig.resolve(None)
    assert cfg is not None and cfg.every == 1
    assert telemetry.HealthConfig.resolve(cfg) is cfg
    # the program cache key is config-independent: precompile(health=True)
    # must serve any thresholds/cadence without orphaning warmed programs
    assert telemetry.HealthConfig.resolve(True).key() == \
        telemetry.HealthConfig(every=4, grad_z=2.0).key()


# -- per-step stats stream -----------------------------------------------------

def test_fit_health_emits_per_step_events_and_gauges():
    X, y = _blobs(128)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                           optimizer="sgd", learning_rate=0.1)
    model.fit(X, y, batch_size=32, health=True)
    events = telemetry.hub().events("health")
    assert len(events) == 2 * (128 // 32)
    for e in events:
        for key in telemetry.EVENT_GOLDEN_KEYS["health"]:
            assert key in e, (key, e)
        assert set(e["stats"]) == {"fc1", "fc2"}
        for row in e["stats"].values():
            assert row["grad_norm"] > 0 and row["weight_norm"] > 0
            assert row["update_ratio"] > 0 and row["nonfinite"] == 0
        assert np.isfinite(e["loss"]) and e["finite"] is True
    # steps advance within each epoch
    assert [e["step"] for e in events[:4]] == [0, 1, 2, 3]
    # the loss stream is the TRUE training loss (cross-entropy here), not
    # the constant gradient-seed scalar (sum of softmax outputs == batch)
    losses = [e["loss"] for e in events]
    assert len(set(losses)) > 1, losses
    assert all(abs(l - 32.0) > 1e-3 for l in losses), losses
    assert losses[-1] < losses[0]  # lr 0.1 on blobs: converging
    # per-layer gauges reach the exporters
    dump = telemetry.prom_dump()
    assert 'mxtpu_health_grad_norm{layer="fc2"' in dump
    assert 'mxtpu_health_update_ratio{layer="fc1"' in dump
    # the monitor is exposed for post-fit inspection
    rep = model.health_monitor.report()
    assert rep["steps"] == len(events)
    assert set(rep["layers"]) == {"fc1", "fc2"}


def test_fit_health_off_emits_nothing():
    X, y = _blobs(64)
    model = mx.FeedForward(_mlp(hidden=16), ctx=mx.cpu(), num_epoch=1,
                           optimizer="sgd", learning_rate=0.1)
    model.fit(X, y, batch_size=32)
    assert telemetry.hub().events("health") == []
    assert getattr(model, "health_monitor", None) is None


def test_fit_health_every_n_steps():
    X, y = _blobs(128)
    model = mx.FeedForward(_mlp(hidden=16), ctx=mx.cpu(), num_epoch=2,
                           optimizer="sgd", learning_rate=0.1)
    model.fit(X, y, batch_size=32,
              health=telemetry.HealthConfig(every=2))
    # 4 steps/epoch, observed at steps 0 and 2 -> 2 events per epoch
    assert len(telemetry.hub().events("health")) == 4


# -- the zero-recompile acceptance criterion -----------------------------------

def test_fit_health_full_stack_zero_recompiles():
    """ACCEPTANCE: an armed RecompileTracker epoch stays green with
    health=True stacked on compression + overlap + fused-Adam + guards —
    the stats pytree threads through the donated carry without perturbing
    the program signature."""
    X, y = _blobs(160, dim=10)
    model = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=3,
                           optimizer="adam", fused=True, learning_rate=0.01)
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    cm.reset_compile_stats()
    try:
        model.fit(X, y, batch_size=32, compression="int8", overlap=True,
                  guards=True, health=True,
                  epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    assert tracker.recompiles == []
    per = cm.compile_stats()["per_function"]
    train = [c for lbl, c in per.items() if lbl.startswith("train_step:")]
    assert train and train[0]["misses"] == 1  # compiled exactly once
    assert len(telemetry.hub().events("health")) == 3 * 5


def test_precompile_with_health_then_fit_no_compiles():
    X, y = _blobs(120, dim=10)
    model = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=2,
                           learning_rate=0.5)
    out = model.precompile(data_shapes={"data": (40, 10)},
                           label_shapes={"softmax_label": (40,)},
                           guards=True, health=True)
    assert out["programs"] == 1
    with cm.RecompileTracker(raise_on_recompile=True):
        model.fit(X, y, batch_size=40, guards=True, health=True)


# -- path parity ---------------------------------------------------------------

def test_health_stats_bitwise_compressed_vs_uncompressed():
    """ACCEPTANCE: per-layer stats are bitwise-equal between the
    compressed (shard_map, explicit allreduce) and uncompressed (SPMD
    psum) step paths over a full training trajectory — the stats engine
    reads the same synced gradients on both."""
    from mxnet_tpu.comm import CompressionSpec

    X, y = _blobs(64)

    def stats_of(compression):
        mx.random.seed(0)
        np.random.seed(0)
        telemetry.reset()
        model = mx.FeedForward(_mlp(), ctx=_ctx8(), num_epoch=3,
                               optimizer="sgd", learning_rate=0.5)
        model.fit(X, y, batch_size=32, health=True,
                  compression=compression)
        return telemetry.hub().events("health")

    spmd = stats_of(None)
    # mode "none": the lossless wire — the shard_map path structure with
    # the SPMD path's exact arithmetic (fit()'s public resolve collapses
    # "none" to off, so drive the spec in directly)
    sharded = stats_of(CompressionSpec("none"))
    assert len(spmd) == len(sharded) == 6
    for a, b in zip(spmd, sharded):
        assert a["loss"] == b["loss"]
        assert a["stats"] == b["stats"]  # dict equality on floats: bitwise


# -- streaming detectors -------------------------------------------------------

def test_detector_nonfinite_names_layer():
    mon = telemetry.HealthMonitor()
    found = mon.observe(_health_event(
        0, {"fc1": _row(), "fc2": _row(nonfinite=12)}, finite=False))
    assert [(f[0], f[1]) for f in found] == [("nonfinite", "fc2")]
    evs = telemetry.hub().events("health_anomaly")
    assert len(evs) == 1 and evs[0]["layer"] == "fc2"
    for key in telemetry.EVENT_GOLDEN_KEYS["health_anomaly"]:
        assert key in evs[0]
    counters = telemetry.hub().snapshot()["counters"]
    assert counters['health_anomalies_total{reason=nonfinite}'] == 1


def test_detector_grad_explosion_zscore_and_absolute():
    cfg = telemetry.HealthConfig(min_steps=4, grad_z=8.0, grad_limit=1e5)
    mon = telemetry.HealthMonitor(cfg)
    rng = np.random.RandomState(3)
    for i in range(20):
        mon.observe(_health_event(
            i, {"fc1": _row(grad=1.0 + 0.05 * rng.randn()),
                "fc2": _row(grad=2.0 + 0.1 * rng.randn())}))
    assert mon.anomalies == []
    found = mon.observe(_health_event(
        20, {"fc1": _row(grad=1.0), "fc2": _row(grad=60.0)}))
    assert [(f[0], f[1]) for f in found] == [("grad_explosion", "fc2")]
    # absolute limit fires with no warmup at all
    mon2 = telemetry.HealthMonitor(telemetry.HealthConfig(grad_limit=100.0))
    found = mon2.observe(_health_event(0, {"fc1": _row(grad=5e3)}))
    assert [(f[0], f[1]) for f in found] == [("grad_explosion", "fc1")]
    assert mon2.blamed_layer() == ("fc1", "grad_explosion")


def test_detector_loss_spike():
    mon = telemetry.HealthMonitor(telemetry.HealthConfig(min_steps=4))
    rng = np.random.RandomState(5)
    for i in range(16):
        mon.observe(_health_event(i, {"fc1": _row()},
                                  loss=1.0 + 0.02 * rng.randn()))
    found = mon.observe(_health_event(16, {"fc1": _row()}, loss=30.0))
    assert [f[0] for f in found] == ["loss_spike"]


def test_detector_dead_layer_and_guard_skips_excluded():
    cfg = telemetry.HealthConfig(dead_ratio=1e-9, dead_steps=5)
    mon = telemetry.HealthMonitor(cfg)
    # 4 dead steps, then a guard-skipped step (ratio 0 by construction,
    # finite=False) which must NOT advance the death counter
    for i in range(4):
        mon.observe(_health_event(i, {"fc1": _row(ratio=1e-12)}))
    mon.observe(_health_event(4, {"fc1": _row(ratio=0.0, nonfinite=1)},
                              finite=False))
    assert not any(r["reason"] == "dead_layer" for r in mon.anomalies)
    found = mon.observe(_health_event(5, {"fc1": _row(ratio=1e-12)}))
    assert [(f[0], f[1]) for f in found] == [("dead_layer", "fc1")]


def test_detector_divergence_drift():
    cfg = telemetry.HealthConfig(min_steps=4, drift_tol=0.1, drift_steps=10,
                                 loss_z=1e9)  # isolate the drift detector
    mon = telemetry.HealthMonitor(cfg)
    loss = 1.0
    found_at = None
    for i in range(120):
        loss *= 1.05  # slow exponential divergence
        found = mon.observe(_health_event(i, {"fc1": _row()}, loss=loss))
        if any(f[0] == "divergence_drift" for f in found):
            found_at = i
            break
    assert found_at is not None, "drift never detected"


def test_read_events_fills_health_defaults(tmp_path):
    """Satellite: hand-rolled/early health rows read back with the
    additive fields defaulted, so the CLI and detectors consume old and
    new streams uniformly."""
    import json

    path = str(tmp_path / "old.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "kind": "health", "ts": 1.0,
                            "epoch": 0, "step": 0, "loss": 1.0}) + "\n")
        f.write(json.dumps({"v": 1, "kind": "health_anomaly", "ts": 2.0,
                            "epoch": 0, "step": 0,
                            "reason": "loss_spike"}) + "\n")
    rows = telemetry.read_events(path)
    assert rows[0]["stats"] == {} and rows[0]["finite"] is True
    assert rows[0]["rank"] == 0  # v1 identity fill still applies
    assert rows[1]["layer"] is None


def test_no_guard_skip_event_when_guard_does_not_skip():
    """GuardConfig(skip_nonfinite=False) applies the poisoned update —
    the flight record must not claim a skip that never ran (the
    nonfinite health_anomaly still fires)."""
    from mxnet_tpu.resilience import chaos as chaos_mod
    from mxnet_tpu.resilience.guards import GuardConfig

    X, y = _blobs(64)
    model = mx.FeedForward(_mlp(hidden=16), ctx=mx.cpu(), num_epoch=1,
                           optimizer="sgd", learning_rate=0.1)
    with chaos_mod.chaos_scope(seed=3, rules={"step.nan": 1.0}):
        model.fit(X, y, batch_size=32,
                  guards=GuardConfig(skip_nonfinite=False), health=True)
    anomalies = telemetry.hub().events("health_anomaly")
    assert any(e["reason"] == "nonfinite" for e in anomalies)
    skips = [e for e in telemetry.hub().events("step_event")
             if e.get("name") == "guard_skip"]
    assert skips == []


def test_blamed_layer_ages_out_across_epochs():
    """A blame must expire after `within` OBSERVED healthy steps even
    when the epoch rolls over (event step numbers reset per epoch and
    cannot express age)."""
    mon = telemetry.HealthMonitor(telemetry.HealthConfig(grad_limit=10.0,
                                                         window=8))
    mon.observe(_health_event(5, {"fc1": _row(grad=1e4)}, epoch=0))
    assert mon.blamed_layer() == ("fc1", "grad_explosion")
    for i in range(20):  # > 2 * window healthy steps, in a LATER epoch
        mon.observe(_health_event(i, {"fc1": _row()}, epoch=7))
    assert mon.blamed_layer() is None


def test_nonfinite_count_shapes():
    """Device arrays count on device (one scalar pull); the historical
    array-like contract (lists, scalars, numpy) still holds."""
    import jax.numpy as jnp

    from mxnet_tpu.monitor import nonfinite_count

    assert nonfinite_count([float("nan"), 1.0, float("inf")]) == 2
    assert nonfinite_count(np.array([1, 2, 3])) == 0  # int dtype
    assert nonfinite_count(jnp.array([np.nan, 1.0])) == 1
    assert nonfinite_count(mx.nd.array(np.array([np.nan, np.inf]))) == 2


# -- e2e: exploding layer + NaN step -> named incident before the skip ---------

class _BoomInit(mx.initializer.Uniform):
    """Normal init except fc1's weights are huge — its activations (and
    therefore fc2's gradients) explode from step 1, deterministically."""

    def __call__(self, name, arr):
        super().__call__(name, arr)
        if name == "fc1_weight":
            arr[:] = arr.asnumpy() * 1e5


def test_exploding_layer_and_nan_step_e2e(tmp_path, monkeypatch):
    """ACCEPTANCE e2e: a dp fit with an injected exploding layer and an
    injected NaN step -> the detector names the correct layer in a
    ``health_anomaly`` incident inside a CRC-valid flight dump BEFORE the
    guard-skip event, and the ``telemetry health`` CLI renders it."""
    from mxnet_tpu.resilience import chaos as chaos_mod

    X, y = _blobs(128, dim=8)
    jsonl = str(tmp_path / "run.jsonl")
    telemetry.flight.reset()
    model = mx.FeedForward(_mlp(hidden=16), ctx=_ctx8(), num_epoch=2,
                           optimizer="sgd", learning_rate=0.01,
                           initializer=_BoomInit(0.07))
    cfg = telemetry.HealthConfig(grad_limit=1e3, min_steps=3)
    with chaos_mod.chaos_scope(seed=3, rules={"step.nan": 0.4}):
        model.fit(X, y, batch_size=32, guards=True, health=cfg,
                  telemetry=telemetry.TelemetryConfig(jsonl=jsonl,
                                                      memory=False))

    anomalies = telemetry.hub().events("health_anomaly")
    # the exploding layer is named FIRST: fc1's huge weights blow up
    # fc2's gradients (fc2's grad is the fc1-activation outer product);
    # later steps may legitimately flag other layers as the blast radius
    # spreads through the updates
    explosions = [e for e in anomalies if e["reason"] == "grad_explosion"]
    assert explosions and explosions[0]["layer"] == "fc2"
    # the chaos-poisoned steps were flagged nonfinite
    nans = [e for e in anomalies if e["reason"] == "nonfinite"]
    assert nans, "no nonfinite anomaly despite chaos step.nan"
    assert model.guard_stats["skipped_steps"] > 0

    # flight dump: CRC-valid, and the first nonfinite health_anomaly
    # precedes the first guard_skip step event — the black box reads
    # cause before effect
    dump_path = str(tmp_path / "flight.json")
    telemetry.flight.dump(dump_path, reason="test")
    ok, payload = telemetry.validate_flight(dump_path)
    assert ok, payload
    incidents = payload["incidents"]
    kinds = [(e.get("kind"), e.get("reason"), e.get("name"))
             for e in incidents]
    i_anom = next(i for i, k in enumerate(kinds)
                  if k[0] == "health_anomaly" and k[1] == "nonfinite")
    i_skip = next(i for i, k in enumerate(kinds)
                  if k[0] == "step_event" and k[2] == "guard_skip")
    assert i_anom < i_skip, kinds

    # the CLI renders the per-layer table + anomaly timeline
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, "-m", "mxnet_tpu.telemetry",
                        "health", jsonl], capture_output=True, text=True,
                       cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fc2" in r.stdout and "grad_explosion" in r.stdout
    assert "nonfinite" in r.stdout


# -- overhead ------------------------------------------------------------------

def test_health_stats_overhead_under_two_percent():
    """ACCEPTANCE: the on-device stats cost < 2% of a dp-8 step, priced
    by the jaxpr-audit FLOP table (the stats run inside the fused step,
    so the MFU numerator includes them exactly)."""
    X, y = _blobs(512, dim=128, classes=8)

    def flops_of(health):
        telemetry.reset()
        model = mx.FeedForward(_mlp(hidden=256, classes=8), ctx=_ctx8(),
                               num_epoch=1, optimizer="sgd",
                               learning_rate=0.1)
        model.fit(X, y, batch_size=256, health=health, telemetry=True)
        return telemetry.hub().snapshot()["gauges"]["model_flops_per_step"]

    base = flops_of(False)
    with_health = flops_of(True)
    overhead = (with_health - base) / base * 100.0
    assert 0 < overhead < 2.0, overhead


# -- fleet controller sensor ---------------------------------------------------

def test_controller_health_lever_recommend_only():
    from mxnet_tpu.resilience.controller import FleetController

    mon = telemetry.HealthMonitor(telemetry.HealthConfig(grad_limit=10.0))
    ctl = FleetController(interval=0.0)
    ctl.bind(model_key="m", world_size=8, health=mon)
    try:
        mon.observe(_health_event(0, {"fc2": _row(grad=1e4)}))
        ctl.tick(now=1.0)
        recs = [d for d in ctl.decisions if d["lever"] == "health"]
        assert recs and recs[-1]["outcome"] == "recommended"
        assert "fc2" in recs[-1]["action"]
        # recommend-only: nothing was actuated, the breaker never moved
        assert not any(d["outcome"] == "actuated" for d in ctl.decisions)
        assert ctl.state == ctl.ARMED
    finally:
        ctl.unbind()
