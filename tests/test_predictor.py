"""Predictor tests (reference: c_predict_api surface — create from
checkpoint, set_input/forward/get_output, single-file export bundle)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.predictor import Predictor


def _trained_model(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(200, 8).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=8)
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=2)
    net = sym.SoftmaxOutput(data=net, name="softmax")
    model = mx.FeedForward(net, ctx=mx.cpu(), num_epoch=5,
                           initializer=mx.init.Xavier())
    model.kwargs = {"lr": 0.5}
    model.fit(X, y, batch_size=50)
    prefix = str(tmp_path / "m")
    model.save(prefix, 5)
    return model, prefix, X, y


def test_predictor_from_checkpoint(tmp_path):
    model, prefix, X, y = _trained_model(tmp_path)
    pred = Predictor.create(prefix, 5, ctx=mx.cpu())
    pred.forward(data=X[:32])
    out = pred.get_output(0)
    assert out.shape == (32, 2)
    expect = model.predict(X[:32], batch_size=32)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_predictor_export_bundle(tmp_path):
    model, prefix, X, y = _trained_model(tmp_path)
    pred = Predictor.create(prefix, 5, ctx=mx.cpu())
    bundle = str(tmp_path / "model.mxtpu")
    pred.export(bundle)
    loaded = Predictor.load(bundle, ctx=mx.cpu())
    loaded.forward(data=X[:16])
    pred.forward(data=X[:16])
    np.testing.assert_allclose(loaded.get_output(0), pred.get_output(0),
                               rtol=1e-5)


def test_predictor_requires_forward(tmp_path):
    _, prefix, X, _ = _trained_model(tmp_path)
    pred = Predictor.create(prefix, 5)
    with pytest.raises(mx.MXNetError):
        pred.get_output(0)
