"""Native C++ predictor vs the JAX Predictor on exported bundles.

Reference test pattern: tests/python/predict/ (the c_predict_api path) —
export a trained graph, reload through the dependency-free runtime, and
check outputs agree with the framework's own forward.
"""

import os

import numpy as np
import pytest

import mxnet_tpu.symbol as S
from mxnet_tpu import random as mx_random
from mxnet_tpu import ndarray as nd
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.native import predict as native_predict

pytestmark = pytest.mark.skipif(
    native_predict.get_predict_lib() is None,
    reason="native predict library unavailable")


def _random_params(sym, input_shapes):
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    rng = np.random.RandomState(7)
    params, aux = {}, {}
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name in input_shapes or name.endswith("_label"):
            continue
        params[name] = nd.array(rng.uniform(-0.5, 0.5, shape).astype(np.float32))
    for name, shape in zip(sym.list_auxiliary_states(), aux_shapes):
        if name.endswith("moving_var"):
            aux[name] = nd.array(rng.uniform(0.5, 1.5, shape).astype(np.float32))
        else:
            aux[name] = nd.array(rng.uniform(-0.1, 0.1, shape).astype(np.float32))
    return params, aux


def _roundtrip(sym, input_shapes, tmp_path, atol=2e-4):
    params, aux = _random_params(sym, input_shapes)
    py_pred = Predictor(sym, params, aux, input_names=list(input_shapes))
    rng = np.random.RandomState(3)
    inputs = {k: rng.randn(*shape).astype(np.float32)
              for k, shape in input_shapes.items()}
    py_pred.forward(**inputs)
    expected = [py_pred.get_output(i) for i in range(len(sym.list_outputs()))]

    bundle = str(tmp_path / "model.mxtpu")
    py_pred.export(bundle)
    npred = native_predict.NativePredictor(bundle)
    npred.forward(**inputs)
    assert npred.num_outputs == len(expected)
    for i, exp in enumerate(expected):
        got = npred.get_output(i)
        assert got.shape == exp.shape, (got.shape, exp.shape)
        np.testing.assert_allclose(got, exp, atol=atol, rtol=1e-3)


def test_mlp_bundle(tmp_path):
    x = S.Variable("data")
    h = S.FullyConnected(data=x, num_hidden=32, name="fc1")
    h = S.Activation(data=h, act_type="relu", name="relu1")
    h = S.FullyConnected(data=h, num_hidden=10, name="fc2")
    net = S.SoftmaxOutput(data=h, name="softmax")
    _roundtrip(net, {"data": (4, 20)}, tmp_path)


def test_lenet_bundle(tmp_path):
    from mxnet_tpu.models import lenet
    _roundtrip(lenet(), {"data": (2, 1, 28, 28)}, tmp_path)


def test_conv_bn_concat_slice_bundle(tmp_path):
    x = S.Variable("data")
    c1 = S.Convolution(data=x, kernel=(3, 3), pad=(1, 1), num_filter=8,
                       name="c1")
    b1 = S.BatchNorm(data=c1, name="bn1")
    a1 = S.Activation(data=b1, act_type="tanh", name="t1")
    c2 = S.Convolution(data=x, kernel=(1, 1), num_filter=8, num_group=2,
                       name="c2")
    cat = S.Concat(a1, c2, name="cat")
    parts = S.SliceChannel(data=cat, num_outputs=2, name="slice")
    merged = parts[0] + parts[1]
    pool = S.Pooling(data=merged, kernel=(2, 2), stride=(2, 2),
                     pool_type="avg", name="pool")
    lrn = S.LRN(data=pool, nsize=3, name="lrn")
    flat = S.Flatten(data=lrn, name="flat")
    net = S.LogisticRegressionOutput(data=flat, name="out")
    _roundtrip(net, {"data": (2, 4, 8, 8)}, tmp_path)


def test_leakyrelu_elementwise_bundle(tmp_path):
    x = S.Variable("data")
    l1 = S.LeakyReLU(data=x, act_type="leaky", slope=0.1, name="lk")
    l2 = S.LeakyReLU(data=x, act_type="elu", slope=0.3, name="elu")
    net = S.LinearRegressionOutput(data=l1 * l2 - x, name="out")
    _roundtrip(net, {"data": (3, 6)}, tmp_path)


def test_resnet_block_bundle(tmp_path):
    """Residual unit: conv-bn-relu + identity shortcut (resnet building block)."""
    x = S.Variable("data")
    c = S.Convolution(data=x, kernel=(3, 3), pad=(1, 1), num_filter=4,
                      no_bias=True, name="conv1")
    b = S.BatchNorm(data=c, name="bn1")
    r = S.Activation(data=b, act_type="relu", name="relu1")
    s = r + x
    pool = S.Pooling(data=s, kernel=(4, 4), global_pool=True,
                     pool_type="avg", name="gap")
    flat = S.Flatten(data=pool, name="flat")
    fc = S.FullyConnected(data=flat, num_hidden=5, name="fc")
    net = S.SoftmaxOutput(data=fc, name="softmax")
    _roundtrip(net, {"data": (2, 4, 8, 8)}, tmp_path)


def test_unary_reshape_transpose_bundle(tmp_path):
    x = S.Variable("data")
    u = S.Sqrt(data=S.Square(data=x))
    u = S.Log(data=S.Exp(data=u))
    r = S.Reshape(data=u, target_shape=(0, 2, -1))
    t = S.Transpose(data=r, axes=(0, 2, 1))
    net = S.LinearRegressionOutput(data=S.Flatten(data=t), name="out")
    _roundtrip(net, {"data": (3, 8)}, tmp_path)


def test_fix_gamma_batchnorm_bundle(tmp_path):
    x = S.Variable("data")
    b = S.BatchNorm(data=x, fix_gamma=True, name="bn")
    net = S.LinearRegressionOutput(data=S.Flatten(data=b), name="out")
    # gamma != 1 in the stored params must be ignored when fix_gamma=True
    sym = net
    params, aux = _random_params(sym, {"data": (2, 3, 4, 4)})
    params["bn_gamma"] = nd.array(np.full((3,), 2.0, np.float32))
    py_pred = Predictor(sym, params, aux, input_names=["data"])
    rng = np.random.RandomState(5)
    inp = rng.randn(2, 3, 4, 4).astype(np.float32)
    py_pred.forward(data=inp)
    expected = py_pred.get_output(0)
    bundle = str(tmp_path / "bn.mxtpu")
    py_pred.export(bundle)
    npred = native_predict.NativePredictor(bundle)
    npred.forward(data=inp)
    np.testing.assert_allclose(npred.get_output(0), expected, atol=2e-4,
                               rtol=1e-3)


def test_embedding_bundle(tmp_path):
    ids = S.Variable("data")
    emb = S.Embedding(data=ids, input_dim=11, output_dim=6, name="emb")
    net = S.LinearRegressionOutput(data=S.Flatten(data=emb), name="out")
    params = {"emb_weight": nd.array(
        np.random.RandomState(1).randn(11, 6).astype(np.float32))}
    py_pred = Predictor(net, params, input_names=["data"])
    inp = np.array([[0, 3, 10], [5, 1, 7]], np.float32)
    py_pred.forward(data=inp)
    expected = py_pred.get_output(0)
    bundle = str(tmp_path / "emb.mxtpu")
    py_pred.export(bundle)
    npred = native_predict.NativePredictor(bundle)
    npred.forward(data=inp)
    np.testing.assert_allclose(npred.get_output(0), expected, atol=1e-5)


def test_error_reporting(tmp_path):
    with pytest.raises(RuntimeError, match="failed to load bundle"):
        native_predict.NativePredictor(str(tmp_path / "missing.mxtpu"))


def test_standalone_predict_python_module(tmp_path):
    """predict/python/mxtpu_predict.py (reference: the ctypes-only
    predict/python/mxnet_predict.py deployment artifact) must drive a
    bundle with NO mxnet_tpu import of its own — verified by loading it
    as a plain module file and comparing against the in-package
    predictor."""
    import importlib.util

    mod_path = os.path.join(os.path.dirname(__file__), "..", "predict",
                            "python", "mxtpu_predict.py")
    spec = importlib.util.spec_from_file_location("mxtpu_predict", mod_path)
    standalone = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(standalone)

    # zero package dependency: its imports are ctypes/os/numpy only
    with open(mod_path) as f:
        src = f.read()
    assert "import mxnet_tpu" not in src and "from mxnet_tpu" not in src

    x = S.Variable("data")
    out = S.SoftmaxOutput(S.FullyConnected(data=x, num_hidden=4, name="fc"),
                          name="softmax")
    rng = np.random.RandomState(5)
    params = {"fc_weight": nd.array(rng.randn(4, 6).astype(np.float32)),
              "fc_bias": nd.array(rng.randn(4).astype(np.float32))}
    py_pred = Predictor(out, params, {}, input_names=["data"])
    inp = rng.randn(3, 6).astype(np.float32)
    py_pred.forward(data=inp)
    expected = py_pred.get_output(0)
    bundle = str(tmp_path / "m.mxtpu")
    py_pred.export(bundle)

    p = standalone.Predictor(bundle)
    outs = p.predict({"data": inp})
    assert len(outs) == 1
    np.testing.assert_allclose(outs[0], expected, atol=2e-4, rtol=1e-3)
