"""Native C++ pipeline tests: CREC format compatibility with the Python
writer, decode parity against PIL (both link the same libjpeg), augment
behavior, shuffle determinism, and ImageRecordIter integration."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import recordio as rio
from mxnet_tpu import native as native_mod

pytestmark = pytest.mark.skipif(
    native_mod.get_lib() is None, reason="native library unavailable"
)


def _make_jpeg_rec(tmp_path, n=20, size=40, quality=95):
    path = str(tmp_path / "imgs.rec")
    w = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    imgs, labels = [], []
    for i in range(n):
        # smooth gradients survive JPEG better than noise
        yy, xx = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
        img = np.stack([(yy * 255 / size), (xx * 255 / size),
                        np.full_like(yy, (i * 13) % 255)], axis=-1).astype(np.uint8)
        imgs.append(img)
        labels.append(float(i))
        w.write(rio.pack_img(rio.IRHeader(0, labels[-1], i, 0), img,
                             quality=quality, img_fmt=".jpg"))
    w.close()
    return path, imgs, labels


def test_scan_offsets_matches_python(tmp_path):
    path, _, _ = _make_jpeg_rec(tmp_path)
    native_offs = native_mod.scan_offsets(path)
    # python-side offsets
    r = rio.MXRecordIO(path, "r")
    py_offs = []
    while True:
        pos = r.tell()
        if r.read() is None:
            break
        py_offs.append(pos)
    r.close()
    assert native_offs == py_offs


def test_native_pipeline_decode_matches_pil(tmp_path):
    path, imgs, labels = _make_jpeg_rec(tmp_path, n=8, size=32)
    offs = native_mod.scan_offsets(path)
    pipe = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32))
    data, lab, pad = pipe.next()
    assert pad == 0
    np.testing.assert_allclose(lab, labels)
    # decode parity: PIL and the native path share libjpeg
    from PIL import Image
    import io as pyio

    r = rio.MXRecordIO(path, "r")
    for i in range(8):
        rec = r.read()
        _, img = rio.unpack_img(rec)
        np.testing.assert_allclose(
            data[i], img.transpose(2, 0, 1).astype(np.float32), atol=1.0
        )
    r.close()


def test_native_pipeline_epoch_and_pad(tmp_path):
    path, _, labels = _make_jpeg_rec(tmp_path, n=10, size=32)
    offs = native_mod.scan_offsets(path)
    pipe = native_mod.NativePipeline(path, offs, batch=4, data_shape=(3, 32, 32))
    assert pipe.batches_per_epoch == 3
    pads = []
    seen = []
    for _ in range(3):
        d, l, p = pipe.next()
        pads.append(p)
        seen.extend(l.tolist())
    assert pads == [0, 0, 2]  # wrap pad on the last batch
    assert seen[:10] == labels
    with pytest.raises(StopIteration):
        pipe.next()
    pipe.reset()
    d, l, p = pipe.next()
    np.testing.assert_allclose(l, labels[:4])


def test_native_pipeline_shuffle_deterministic(tmp_path):
    path, _, _ = _make_jpeg_rec(tmp_path, n=16, size=32)
    offs = native_mod.scan_offsets(path)

    def epoch_labels(seed):
        pipe = native_mod.NativePipeline(path, offs, batch=8,
                                         data_shape=(3, 32, 32), shuffle=True,
                                         seed=seed)
        out = []
        for _ in range(2):
            _, l, _ = pipe.next()
            out.extend(l.tolist())
        return out

    a, b = epoch_labels(7), epoch_labels(7)
    c = epoch_labels(8)
    assert a == b
    assert a != c
    assert sorted(a) == list(map(float, range(16)))


def test_native_mean_scale_crop(tmp_path):
    path, imgs, _ = _make_jpeg_rec(tmp_path, n=4, size=40)
    offs = native_mod.scan_offsets(path)
    pipe = native_mod.NativePipeline(path, offs, batch=4, data_shape=(3, 32, 32),
                                     mean=[128, 128, 128], scale=1 / 128.0)
    data, _, _ = pipe.next()
    # center crop of the deterministic gradient image, mean/scale applied
    expect = (imgs[0][4:36, 4:36].transpose(2, 0, 1).astype(np.float32)
              - 128.0) / 128.0
    np.testing.assert_allclose(data[0], expect, atol=0.05)


def test_image_record_iter_uses_native_for_jpeg(tmp_path):
    path, _, labels = _make_jpeg_rec(tmp_path, n=12, size=36)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=4)
    assert it._native is not None, "JPEG records should take the native path"
    got = []
    for b in it:
        got.extend(b.label[0].asnumpy().tolist())
    assert got == labels
    # second epoch works
    got2 = [x for b in it for x in b.label[0].asnumpy().tolist()]
    assert got2 == labels


def test_image_record_iter_falls_back_for_png(tmp_path):
    path = str(tmp_path / "png.rec")
    w = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(6):
        img = rng.randint(0, 255, (32, 32, 3), np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=3)
    assert it._native is None, "PNG records must fall back to the PIL path"
    labels = [x for b in it for x in b.label[0].asnumpy().tolist()]
    assert labels == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_image_record_iter_nhwc_layout(tmp_path):
    """NHWC batches must be the exact transpose of NCHW batches — native path
    (and provide_data must advertise the NHWC shape)."""
    path, _, _ = _make_jpeg_rec(tmp_path, n=12, size=40)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False)
    it_c = mio.ImageRecordIter(layout="NCHW", **kw)
    it_h = mio.ImageRecordIter(layout="NHWC", **kw)
    assert it_c._native is not None and it_h._native is not None
    assert it_h.provide_data == [("data", (4, 32, 32, 3))]
    for bc, bh in zip(it_c, it_h):
        np.testing.assert_allclose(
            bc.data[0].asnumpy(), bh.data[0].asnumpy().transpose(0, 3, 1, 2))
        np.testing.assert_allclose(bc.label[0].asnumpy(), bh.label[0].asnumpy())


def test_image_record_iter_nhwc_layout_python_path(tmp_path, monkeypatch):
    """Same parity on the pure-Python decode path (native disabled)."""
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    path, _, _ = _make_jpeg_rec(tmp_path, n=8, size=40)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False, mean_r=10.0, mean_g=20.0, mean_b=30.0, scale=0.5)
    it_c = mio.ImageRecordIter(layout="NCHW", **kw)
    it_h = mio.ImageRecordIter(layout="NHWC", **kw)
    assert it_c._native is None and it_h._native is None
    for bc, bh in zip(it_c, it_h):
        np.testing.assert_allclose(
            bc.data[0].asnumpy(), bh.data[0].asnumpy().transpose(0, 3, 1, 2))


def test_image_record_iter_uint8_output(tmp_path):
    """output_dtype='uint8' emits raw pixels equal to the f32 path at
    scale=1/no-mean, in both native and python pipelines."""
    path, _, _ = _make_jpeg_rec(tmp_path, n=8, size=40)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False, layout="NHWC")
    it_f = mio.ImageRecordIter(output_dtype="float32", **kw)
    it_u = mio.ImageRecordIter(output_dtype="uint8", **kw)
    assert it_u._native is not None
    for bf, bu in zip(it_f, it_u):
        u = bu.data[0].asnumpy()
        assert u.dtype == np.uint8
        np.testing.assert_allclose(bf.data[0].asnumpy(), u.astype(np.float32))
    with pytest.raises(mx.base.MXNetError):
        mio.ImageRecordIter(output_dtype="uint8", scale=0.5, **kw)
