"""Native C++ pipeline tests: CREC format compatibility with the Python
writer, decode parity against PIL (both link the same libjpeg), augment
behavior, shuffle determinism, and ImageRecordIter integration."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import recordio as rio
from mxnet_tpu import native as native_mod

pytestmark = pytest.mark.skipif(
    native_mod.get_lib() is None, reason="native library unavailable"
)


def _make_jpeg_rec(tmp_path, n=20, size=40, quality=95):
    path = str(tmp_path / "imgs.rec")
    w = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    imgs, labels = [], []
    for i in range(n):
        # smooth gradients survive JPEG better than noise
        yy, xx = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
        img = np.stack([(yy * 255 / size), (xx * 255 / size),
                        np.full_like(yy, (i * 13) % 255)], axis=-1).astype(np.uint8)
        imgs.append(img)
        labels.append(float(i))
        w.write(rio.pack_img(rio.IRHeader(0, labels[-1], i, 0), img,
                             quality=quality, img_fmt=".jpg"))
    w.close()
    return path, imgs, labels


def test_scan_offsets_matches_python(tmp_path):
    path, _, _ = _make_jpeg_rec(tmp_path)
    native_offs = native_mod.scan_offsets(path)
    # python-side offsets
    r = rio.MXRecordIO(path, "r")
    py_offs = []
    while True:
        pos = r.tell()
        if r.read() is None:
            break
        py_offs.append(pos)
    r.close()
    assert native_offs == py_offs


def test_native_pipeline_decode_matches_pil(tmp_path):
    path, imgs, labels = _make_jpeg_rec(tmp_path, n=8, size=32)
    offs = native_mod.scan_offsets(path)
    pipe = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32))
    data, lab, pad = pipe.next()
    assert pad == 0
    np.testing.assert_allclose(lab, labels)
    # decode parity: PIL and the native path share libjpeg
    from PIL import Image
    import io as pyio

    r = rio.MXRecordIO(path, "r")
    for i in range(8):
        rec = r.read()
        _, img = rio.unpack_img(rec)
        np.testing.assert_allclose(
            data[i], img.transpose(2, 0, 1).astype(np.float32), atol=1.0
        )
    r.close()


def test_native_pipeline_epoch_and_pad(tmp_path):
    path, _, labels = _make_jpeg_rec(tmp_path, n=10, size=32)
    offs = native_mod.scan_offsets(path)
    pipe = native_mod.NativePipeline(path, offs, batch=4, data_shape=(3, 32, 32))
    assert pipe.batches_per_epoch == 3
    pads = []
    seen = []
    for _ in range(3):
        d, l, p = pipe.next()
        pads.append(p)
        seen.extend(l.tolist())
    assert pads == [0, 0, 2]  # wrap pad on the last batch
    assert seen[:10] == labels
    with pytest.raises(StopIteration):
        pipe.next()
    pipe.reset()
    d, l, p = pipe.next()
    np.testing.assert_allclose(l, labels[:4])


def test_native_pipeline_shuffle_deterministic(tmp_path):
    path, _, _ = _make_jpeg_rec(tmp_path, n=16, size=32)
    offs = native_mod.scan_offsets(path)

    def epoch_labels(seed):
        pipe = native_mod.NativePipeline(path, offs, batch=8,
                                         data_shape=(3, 32, 32), shuffle=True,
                                         seed=seed)
        out = []
        for _ in range(2):
            _, l, _ = pipe.next()
            out.extend(l.tolist())
        return out

    a, b = epoch_labels(7), epoch_labels(7)
    c = epoch_labels(8)
    assert a == b
    assert a != c
    assert sorted(a) == list(map(float, range(16)))


def test_native_mean_scale_crop(tmp_path):
    path, imgs, _ = _make_jpeg_rec(tmp_path, n=4, size=40)
    offs = native_mod.scan_offsets(path)
    pipe = native_mod.NativePipeline(path, offs, batch=4, data_shape=(3, 32, 32),
                                     mean=[128, 128, 128], scale=1 / 128.0)
    data, _, _ = pipe.next()
    # center crop of the deterministic gradient image, mean/scale applied
    expect = (imgs[0][4:36, 4:36].transpose(2, 0, 1).astype(np.float32)
              - 128.0) / 128.0
    np.testing.assert_allclose(data[0], expect, atol=0.05)


def test_image_record_iter_uses_native_for_jpeg(tmp_path):
    path, _, labels = _make_jpeg_rec(tmp_path, n=12, size=36)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=4)
    assert it._native is not None, "JPEG records should take the native path"
    got = []
    for b in it:
        got.extend(b.label[0].asnumpy().tolist())
    assert got == labels
    # second epoch works
    got2 = [x for b in it for x in b.label[0].asnumpy().tolist()]
    assert got2 == labels


def test_image_record_iter_falls_back_for_png(tmp_path):
    path = str(tmp_path / "png.rec")
    w = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(6):
        img = rng.randint(0, 255, (32, 32, 3), np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=3)
    assert it._native is None, "PNG records must fall back to the PIL path"
    labels = [x for b in it for x in b.label[0].asnumpy().tolist()]
    assert labels == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_image_record_iter_nhwc_layout(tmp_path):
    """NHWC batches must be the exact transpose of NCHW batches — native path
    (and provide_data must advertise the NHWC shape)."""
    path, _, _ = _make_jpeg_rec(tmp_path, n=12, size=40)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False)
    it_c = mio.ImageRecordIter(layout="NCHW", **kw)
    it_h = mio.ImageRecordIter(layout="NHWC", **kw)
    assert it_c._native is not None and it_h._native is not None
    assert it_h.provide_data == [("data", (4, 32, 32, 3))]
    for bc, bh in zip(it_c, it_h):
        np.testing.assert_allclose(
            bc.data[0].asnumpy(), bh.data[0].asnumpy().transpose(0, 3, 1, 2))
        np.testing.assert_allclose(bc.label[0].asnumpy(), bh.label[0].asnumpy())


def test_image_record_iter_nhwc_layout_python_path(tmp_path, monkeypatch):
    """Same parity on the pure-Python decode path (native disabled)."""
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    path, _, _ = _make_jpeg_rec(tmp_path, n=8, size=40)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False, mean_r=10.0, mean_g=20.0, mean_b=30.0, scale=0.5)
    it_c = mio.ImageRecordIter(layout="NCHW", **kw)
    it_h = mio.ImageRecordIter(layout="NHWC", **kw)
    assert it_c._native is None and it_h._native is None
    for bc, bh in zip(it_c, it_h):
        np.testing.assert_allclose(
            bc.data[0].asnumpy(), bh.data[0].asnumpy().transpose(0, 3, 1, 2))


def test_image_record_iter_uint8_output(tmp_path):
    """output_dtype='uint8' emits raw pixels equal to the f32 path at
    scale=1/no-mean, in both native and python pipelines."""
    path, _, _ = _make_jpeg_rec(tmp_path, n=8, size=40)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False, layout="NHWC")
    it_f = mio.ImageRecordIter(output_dtype="float32", **kw)
    it_u = mio.ImageRecordIter(output_dtype="uint8", **kw)
    assert it_u._native is not None
    for bf, bu in zip(it_f, it_u):
        u = bu.data[0].asnumpy()
        assert u.dtype == np.uint8
        np.testing.assert_allclose(bf.data[0].asnumpy(), u.astype(np.float32))
    with pytest.raises(mx.base.MXNetError):
        mio.ImageRecordIter(output_dtype="uint8", scale=0.5, **kw)


def test_native_resize_matches_float_bilinear(tmp_path):
    """The fixed-point (16.16) bilinear resize must match a float
    reference within 1 LSB, and the identity-resize fast path (source
    already at the target short side) must be pixel-exact."""
    path = str(tmp_path / "resize.rec")
    w = rio.MXRecordIO(path, "w")
    yy, xx = np.meshgrid(np.arange(48), np.arange(64), indexing="ij")
    img = np.stack([(yy * 255 / 48), (xx * 255 / 64),
                    ((yy + xx) * 255 / 112)], axis=-1).astype(np.uint8)
    w.write(rio.pack_img(rio.IRHeader(0, 0.0, 0, 0), img, quality=100,
                         img_fmt=".jpg"))
    # second record: already at target geometry (identity-resize path)
    img2 = img[:32, :32]
    w.write(rio.pack_img(rio.IRHeader(0, 1.0, 1, 0), img2, quality=100,
                         img_fmt=".jpg"))
    w.close()
    offs = native_mod.scan_offsets(path)

    # resize short side 48x64 -> 32(x43), center-crop 32
    pipe = native_mod.NativePipeline(path, offs, batch=2,
                                     data_shape=(3, 32, 32), resize=32)
    data, labels, pad = pipe.next()

    # decode the same source through the Python-side reader (shared
    # libjpeg -> identical pixels), then float bilinear with the same
    # corner-aligned mapping as the reference result
    r = rio.MXRecordIO(path, "r")
    _, src = rio.unpack_img(r.read())
    _, src2 = rio.unpack_img(r.read())
    r.close()

    def float_bilinear(s, dh, dw):
        sh, sw = s.shape[:2]
        ry = (sh - 1) / (dh - 1) if dh > 1 else 0.0
        rx = (sw - 1) / (dw - 1) if dw > 1 else 0.0
        out = np.empty((dh, dw, 3), np.float64)
        for y in range(dh):
            fy = y * ry
            y0, wy = int(fy), fy - int(fy)
            y1 = min(y0 + 1, sh - 1)
            for x in range(dw):
                fx = x * rx
                x0, wx = int(fx), fx - int(fx)
                x1 = min(x0 + 1, sw - 1)
                out[y, x] = (s[y0, x0] * (1 - wy) * (1 - wx)
                             + s[y0, x1] * (1 - wy) * wx
                             + s[y1, x0] * wy * (1 - wx)
                             + s[y1, x1] * wy * wx)
        return np.round(out)

    # record 0: short side 48 -> 32, so full resize to (32, 43); crop 32
    ref = float_bilinear(src.astype(np.float64), 32, 43)
    left = (43 - 32) // 2
    ref_crop = ref[:, left:left + 32]
    got = data[0].transpose(1, 2, 0)
    assert np.max(np.abs(got - ref_crop)) <= 1.0 + 1e-9  # 1 LSB rounding

    # record 1: already 32x32 -> identity path, must be exactly the decode
    got2 = data[1].transpose(1, 2, 0)
    np.testing.assert_array_equal(got2, src2.astype(np.float32))
