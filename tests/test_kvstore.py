"""KVStore tests (reference: tests/python/unittest/test_kvstore.py —
single/list keys, aggregation over 4 fake devices, custom updater; plus the
ported dist_sync semantics test from tests/python/multi-node/
dist_sync_kvstore.py, run on an in-process worker group)."""

import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv_mod

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _same(a, b, tol=1e-5):
    np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


def test_single_kv_pair():
    kv = kv_mod.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    _same(val.asnumpy(), np.ones(SHAPE))


def test_list_kv_pair():
    kv = kv_mod.create("local")
    kv.init(KEYS, [mx.nd.ones(SHAPE) * 4] * len(KEYS))
    val = [mx.nd.empty(SHAPE)] * len(KEYS)
    kv.pull(KEYS, out=val)
    for v in val:
        _same(v.asnumpy(), np.ones(SHAPE) * 4)


def test_aggregator():
    """Push from 4 fake devices -> pull sees the sum (reference: test_aggregator)."""
    kv = kv_mod.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    num_devs = 4
    devs = [mx.cpu(i) for i in range(num_devs)]
    vals = [mx.nd.ones(SHAPE, d) for d in devs]
    kv.push(3, vals)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) * num_devs)
    # list interface
    kv.init(KEYS, [mx.nd.ones(SHAPE)] * len(KEYS))
    kv.push(KEYS, [[mx.nd.ones(SHAPE, d) * 2.0 for d in devs]] * len(KEYS))
    outs = [mx.nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        _same(o.asnumpy(), np.ones(SHAPE) * 2.0 * num_devs)


def test_updater():
    """Custom updater runs on push (reference: test_updater)."""
    kv = kv_mod.create("local")

    def updater(key, recv, stored):
        stored += recv * 2

    kv.set_updater(updater)
    kv.init(3, mx.nd.ones(SHAPE) * 4)
    devs = [mx.cpu(i) for i in range(4)]
    vals = [mx.nd.ones(SHAPE, d) for d in devs]
    kv.push(3, vals)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) * 4 * 2 + 4)  # 4 + 2*sum(ones*4)


def test_get_type():
    assert kv_mod.create("local").type == "local"
    assert kv_mod.create("device").type == "device"


def test_optimizer_on_kvstore():
    kv = kv_mod.create("local")
    opt = mx.optimizer.create("sgd", lr=0.1, rescale_grad=1.0)
    kv.set_optimizer(opt)
    kv.init(0, mx.nd.ones(SHAPE))
    kv.push(0, [mx.nd.ones(SHAPE)])
    out = mx.nd.empty(SHAPE)
    kv.pull(0, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) - 0.1)


def test_dist_sync_group_semantics():
    """Ported reference test (tests/python/multi-node/dist_sync_kvstore.py):
    each of N workers pushes rank-dependent values; BSP semantics give the
    closed-form reduced result on every worker."""
    n = 4
    stores = kv_mod.create_group(n)
    results = {}
    errors = []

    def worker(rank):
        try:
            kv = stores[rank]
            kv.init(3, mx.nd.ones(SHAPE))
            # one BSP round: every worker pushes (rank+1) * ones
            kv.push(3, [mx.nd.ones(SHAPE) * (rank + 1)])
            out = mx.nd.empty(SHAPE)
            kv.pull(3, out=out)
            results[rank] = out.asnumpy()
            kv.barrier()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    expected = np.ones(SHAPE) * sum(r + 1 for r in range(n))  # 1+2+3+4 = 10
    for rank in range(n):
        _same(results[rank], expected)


def test_dist_sync_group_with_updater():
    """BSP + server-side updater: update runs once per round with the
    across-worker sum (reference: dist server accumulate-until-N then
    updater, kvstore_dist_server.h:164-193)."""
    n = 3
    stores = kv_mod.create_group(n)

    def updater(key, recv, stored):
        stored += recv

    stores[0].set_updater(updater)  # server-side: one updater for the group
    results = {}

    def worker(rank):
        kv = stores[rank]
        kv.init(9, mx.nd.zeros(SHAPE))
        for _round in range(2):
            kv.push(9, [mx.nd.ones(SHAPE)])
        out = mx.nd.empty(SHAPE)
        kv.pull(9, out=out)
        results[rank] = out.asnumpy()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # 2 rounds x (sum over 3 workers of ones) accumulated
    for rank in range(n):
        _same(results[rank], np.ones(SHAPE) * 2 * n)


def test_dist_single_process():
    """dist_sync with one process degenerates to local semantics."""
    kv = kv_mod.create("dist_sync")
    assert kv.num_workers == 1 and kv.rank == 0
    kv.init(1, mx.nd.ones(SHAPE))
    kv.push(1, [mx.nd.ones(SHAPE) * 3])
    out = mx.nd.empty(SHAPE)
    kv.pull(1, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) * 3)
    kv.barrier()


def test_test_optimizer_updater_semantics():
    """reference optimizer.py:162 Test: w += rescale_grad * grad; the state
    mirrors the updated weight (used by kvstore updater tests)."""
    import numpy as np

    import mxnet_tpu as mx

    opt = mx.optimizer.create("test", rescale_grad=0.5)
    updater = mx.optimizer.get_updater(opt)
    w = mx.nd.array(np.ones(4, np.float32))
    g = mx.nd.array(np.full(4, 2.0, np.float32))
    updater(0, g, w)
    np.testing.assert_allclose(w.asnumpy(), 2.0)
