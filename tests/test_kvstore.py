"""KVStore tests (reference: tests/python/unittest/test_kvstore.py —
single/list keys, aggregation over 4 fake devices, custom updater; plus the
ported dist_sync semantics test from tests/python/multi-node/
dist_sync_kvstore.py, run on an in-process worker group)."""

import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv_mod

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _same(a, b, tol=1e-5):
    np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


def test_single_kv_pair():
    kv = kv_mod.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    _same(val.asnumpy(), np.ones(SHAPE))


def test_list_kv_pair():
    kv = kv_mod.create("local")
    kv.init(KEYS, [mx.nd.ones(SHAPE) * 4] * len(KEYS))
    val = [mx.nd.empty(SHAPE)] * len(KEYS)
    kv.pull(KEYS, out=val)
    for v in val:
        _same(v.asnumpy(), np.ones(SHAPE) * 4)


def test_aggregator():
    """Push from 4 fake devices -> pull sees the sum (reference: test_aggregator)."""
    kv = kv_mod.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    num_devs = 4
    devs = [mx.cpu(i) for i in range(num_devs)]
    vals = [mx.nd.ones(SHAPE, d) for d in devs]
    kv.push(3, vals)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) * num_devs)
    # list interface
    kv.init(KEYS, [mx.nd.ones(SHAPE)] * len(KEYS))
    kv.push(KEYS, [[mx.nd.ones(SHAPE, d) * 2.0 for d in devs]] * len(KEYS))
    outs = [mx.nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        _same(o.asnumpy(), np.ones(SHAPE) * 2.0 * num_devs)


def test_updater():
    """Custom updater runs on push (reference: test_updater)."""
    kv = kv_mod.create("local")

    def updater(key, recv, stored):
        stored += recv * 2

    kv.set_updater(updater)
    kv.init(3, mx.nd.ones(SHAPE) * 4)
    devs = [mx.cpu(i) for i in range(4)]
    vals = [mx.nd.ones(SHAPE, d) for d in devs]
    kv.push(3, vals)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) * 4 * 2 + 4)  # 4 + 2*sum(ones*4)


def test_get_type():
    assert kv_mod.create("local").type == "local"
    assert kv_mod.create("device").type == "device"


def test_optimizer_on_kvstore():
    kv = kv_mod.create("local")
    opt = mx.optimizer.create("sgd", lr=0.1, rescale_grad=1.0)
    kv.set_optimizer(opt)
    kv.init(0, mx.nd.ones(SHAPE))
    kv.push(0, [mx.nd.ones(SHAPE)])
    out = mx.nd.empty(SHAPE)
    kv.pull(0, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) - 0.1)


def test_dist_sync_group_semantics():
    """Ported reference test (tests/python/multi-node/dist_sync_kvstore.py):
    each of N workers pushes rank-dependent values; BSP semantics give the
    closed-form reduced result on every worker."""
    n = 4
    stores = kv_mod.create_group(n)
    results = {}
    errors = []

    def worker(rank):
        try:
            kv = stores[rank]
            kv.init(3, mx.nd.ones(SHAPE))
            # one BSP round: every worker pushes (rank+1) * ones
            kv.push(3, [mx.nd.ones(SHAPE) * (rank + 1)])
            out = mx.nd.empty(SHAPE)
            kv.pull(3, out=out)
            results[rank] = out.asnumpy()
            kv.barrier()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    expected = np.ones(SHAPE) * sum(r + 1 for r in range(n))  # 1+2+3+4 = 10
    for rank in range(n):
        _same(results[rank], expected)


def test_dist_sync_group_with_updater():
    """BSP + server-side updater: update runs once per round with the
    across-worker sum (reference: dist server accumulate-until-N then
    updater, kvstore_dist_server.h:164-193)."""
    n = 3
    stores = kv_mod.create_group(n)

    def updater(key, recv, stored):
        stored += recv

    stores[0].set_updater(updater)  # server-side: one updater for the group
    results = {}

    def worker(rank):
        kv = stores[rank]
        kv.init(9, mx.nd.zeros(SHAPE))
        for _round in range(2):
            kv.push(9, [mx.nd.ones(SHAPE)])
        out = mx.nd.empty(SHAPE)
        kv.pull(9, out=out)
        results[rank] = out.asnumpy()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # 2 rounds x (sum over 3 workers of ones) accumulated
    for rank in range(n):
        _same(results[rank], np.ones(SHAPE) * 2 * n)


def test_dist_single_process():
    """dist_sync with one process degenerates to local semantics."""
    kv = kv_mod.create("dist_sync")
    assert kv.num_workers == 1 and kv.rank == 0
    kv.init(1, mx.nd.ones(SHAPE))
    kv.push(1, [mx.nd.ones(SHAPE) * 3])
    out = mx.nd.empty(SHAPE)
    kv.pull(1, out=out)
    _same(out.asnumpy(), np.ones(SHAPE) * 3)
    kv.barrier()


def test_group_server_duplicate_push_idempotent():
    """Satellite (ISSUE 2): a duplicate resend of an already-applied push
    (retry after a lost ack) must not double-count in the BSP round."""
    n = 2
    stores = kv_mod.create_group(n)
    server = stores[0]._server
    server.init(1, np.zeros(SHAPE, np.float32))

    def updater(key, recv, stored):
        stored += recv

    server.updater = kv_mod.wrap_np_updater(updater)

    results = {}

    def worker(rank, resend):
        # drive the server directly with explicit (worker, seq) identities
        server.push(1, np.ones(SHAPE, np.float32) * (rank + 1),
                    worker=rank, seq=0)
        if resend:  # retry of the SAME logical push after a lost ack
            server.push(1, np.ones(SHAPE, np.float32) * (rank + 1),
                        worker=rank, seq=0)
        results[rank] = server.pull(1)

    threads = [threading.Thread(target=worker, args=(r, r == 0))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert server.duplicate_count == 1
    assert server._round[1] == 1  # ONE round completed, not 1.5
    for r in range(n):
        _same(results[r], np.ones(SHAPE) * 3)  # 1 + 2, each counted once


def test_group_push_retries_under_chaos_lost_messages():
    """Lost sends AND lost acks (chaos-injected) are retried by the worker
    handle with stable (worker, seq) ids; BSP results stay exact and the
    server reports every absorbed duplicate."""
    from mxnet_tpu.resilience import chaos_scope

    n = 3
    stores = kv_mod.create_group(n)
    results = {}
    errors = []

    def worker(rank):
        try:
            kv = stores[rank]
            kv.init(3, mx.nd.ones(SHAPE))
            for _ in range(3):  # 3 BSP rounds under fire
                kv.push(3, [mx.nd.ones(SHAPE) * (rank + 1)])
            out = mx.nd.empty(SHAPE)
            kv.pull(3, out=out)
            results[rank] = out.asnumpy()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    with chaos_scope(seed=11, rules={"group.push.send": 0.3,
                                     "group.push.ack": 0.3}) as cz:
        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    assert not errors, errors
    assert cz.fired.get("group.push.ack", 0) > 0  # duplicates were produced
    server = stores[0]._server
    assert server.duplicate_count == cz.fired.get("group.push.ack", 0)
    assert server._round[3] == 3  # exactly 3 rounds despite resends
    # default (no-updater) semantics: store holds the last round's merge
    for rank in range(n):
        _same(results[rank], np.ones(SHAPE) * sum(r + 1 for r in range(n)))


def test_async_server_dedups_replayed_push_pull():
    """dist_async server: a mutating request replayed after a reconnect is
    answered from the (rank, seq) cache, not applied twice."""
    from mxnet_tpu.kvstore_async import AsyncKVStore

    kv = AsyncKVStore()
    try:
        kv.init("w", mx.nd.zeros((4,)))
        opt = mx.optimizer.create("test", rescale_grad=1.0)
        kv.set_optimizer(opt)  # w += grad
        r1 = kv.push_pull({"w": np.ones((4,), np.float32)})
        _same(r1["w"], 1.0)
        # hand-replay the exact wire message (rank 0, seq 0): the server
        # must serve the cached reply and leave the store untouched
        from mxnet_tpu import kvstore_async as ka

        with kv._lock:
            ka._send_msg(kv._sock,
                         ("push_pull", {"w": np.ones((4,), np.float32)},
                          0, 0))
            replay = ka._recv_msg(kv._sock)
        assert replay[0] == "ok"
        _same(replay[1]["w"], 1.0)  # the ORIGINAL reply, not 2.0
        assert kv._server.duplicate_count == 1
        out = kv.pull_many(["w"])
        _same(out["w"], 1.0)  # store not double-updated
    finally:
        del kv


def test_async_server_replay_racing_inflight_apply():
    """A resend that lands while the ORIGINAL request is still applying
    (client timed out mid-apply) must wait for the cached reply, not
    apply the mutation twice — the in-progress claim in _replay."""
    import socket
    import time

    from mxnet_tpu import kvstore_async as ka

    kv = ka.AsyncKVStore()
    try:
        kv.init("w", mx.nd.zeros((4,)))
        applies = []

        def slow_updater(key, recv, stored):
            applies.append(1)
            time.sleep(0.4)  # hold the apply so the replay races it
            stored += recv

        kv._server.updater = slow_updater

        def raw_conn():
            s = socket.create_connection((kv._host, kv._port))
            s.sendall(ka._MAGIC)
            assert ka._recv_exact(s, 4) == ka._MAGIC
            return s

        msg = ("push_pull", {"w": np.ones((4,), np.float32)}, 0, 0)
        replies = {}

        def send(tag, delay):
            time.sleep(delay)
            c = raw_conn()
            ka._send_msg(c, msg)
            replies[tag] = ka._recv_msg(c)
            c.close()

        threads = [threading.Thread(target=send, args=("orig", 0)),
                   threading.Thread(target=send, args=("replay", 0.1))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(applies) == 1, "racing resend applied the mutation twice"
        assert kv._server.duplicate_count == 1
        _same(replies["orig"][1]["w"], 1.0)
        _same(replies["replay"][1]["w"], 1.0)
    finally:
        del kv


def test_test_optimizer_updater_semantics():
    """reference optimizer.py:162 Test: w += rescale_grad * grad; the state
    mirrors the updated weight (used by kvstore updater tests)."""
    import numpy as np

    import mxnet_tpu as mx

    opt = mx.optimizer.create("test", rescale_grad=0.5)
    updater = mx.optimizer.get_updater(opt)
    w = mx.nd.array(np.ones(4, np.float32))
    g = mx.nd.array(np.full(4, 2.0, np.float32))
    updater(0, g, w)
    np.testing.assert_allclose(w.asnumpy(), 2.0)
