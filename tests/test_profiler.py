"""Profiler tests: the trace-digest parser against a synthesized XProf
export (deterministic), plus a live profile_step smoke on CPU (host traces
carry no per-op XLA lanes, so stats may be empty there — the parser's op
rows come from device traces, as used for the bench.py analysis)."""

import gzip
import json
import os

import numpy as np

from mxnet_tpu.utils import profiler


def _write_trace(tmp_path, events):
    d = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    os.makedirs(d)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def test_trace_op_stats_parses_and_aggregates(tmp_path):
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 9, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
        # two instances of the same fusion (suffix-stripped -> aggregated)
        {"ph": "X", "pid": 3, "tid": 7, "name": "fusion.12", "dur": 100},
        {"ph": "X", "pid": 3, "tid": 7, "name": "fusion.13", "dur": 50},
        {"ph": "X", "pid": 3, "tid": 7, "name": "copy.1", "dur": 30},
        # host lane events must be ignored
        {"ph": "X", "pid": 9, "tid": 1, "name": "PjitFunction(f)", "dur": 999},
    ]
    log_dir = _write_trace(tmp_path, events)
    stats = profiler.trace_op_stats(log_dir)
    assert [(s.name, s.total_us, s.count) for s in stats] == [
        ("fusion", 150, 2), ("copy", 30, 1)]
    # device filter
    assert profiler.trace_op_stats(log_dir, device_substr="TPU")
    assert not profiler.trace_op_stats(log_dir, device_substr="GPU")
    # pretty print
    assert "fusion" in str(stats[0])


def test_profile_step_smoke(tmp_path):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return jnp.tanh(x).sum()

    x = jnp.asarray(np.random.randn(64, 64).astype(np.float32))
    stats, log_dir = profiler.profile_step(f, x, iters=2,
                                           log_dir=str(tmp_path / "tr"))
    assert os.path.isdir(log_dir)
    assert isinstance(stats, list)  # may be empty on host-only traces
