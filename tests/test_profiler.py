"""Profiler tests: the trace-digest parser against a synthesized XProf
export (deterministic), plus a live profile_step smoke on CPU (host traces
carry no per-op XLA lanes, so stats may be empty there — the parser's op
rows come from device traces, as used for the bench.py analysis)."""

import gzip
import json
import os

import numpy as np

from mxnet_tpu.utils import profiler


def _write_trace(tmp_path, events):
    d = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    os.makedirs(d)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def test_trace_op_stats_parses_and_aggregates(tmp_path):
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 9, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
        # two instances of the same fusion (suffix-stripped -> aggregated)
        {"ph": "X", "pid": 3, "tid": 7, "name": "fusion.12", "dur": 100},
        {"ph": "X", "pid": 3, "tid": 7, "name": "fusion.13", "dur": 50},
        {"ph": "X", "pid": 3, "tid": 7, "name": "copy.1", "dur": 30},
        # host lane events must be ignored
        {"ph": "X", "pid": 9, "tid": 1, "name": "PjitFunction(f)", "dur": 999},
    ]
    log_dir = _write_trace(tmp_path, events)
    stats = profiler.trace_op_stats(log_dir)
    assert [(s.name, s.total_us, s.count) for s in stats] == [
        ("fusion", 150, 2), ("copy", 30, 1)]
    # device filter
    assert profiler.trace_op_stats(log_dir, device_substr="TPU")
    assert not profiler.trace_op_stats(log_dir, device_substr="GPU")
    # pretty print
    assert "fusion" in str(stats[0])


def test_profile_step_smoke(tmp_path):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return jnp.tanh(x).sum()

    x = jnp.asarray(np.random.randn(64, 64).astype(np.float32))
    stats, log_dir = profiler.profile_step(f, x, iters=2,
                                           log_dir=str(tmp_path / "tr"))
    assert os.path.isdir(log_dir)
    assert isinstance(stats, list)  # may be empty on host-only traces


def test_timer_counts_dispatched_but_unfinished_work():
    """Regression (ISSUE 5 satellite): Timer must block on the actual
    outputs, not on jax.effects_barrier() — effects_barrier orders effects
    only and does not wait for committed pure computation on all jax pins,
    so an async-dispatched step could previously be timed at enqueue cost.
    A dispatched-but-unfinished computation must be FULLY counted."""
    import time

    import jax
    import jax.numpy as jnp

    @jax.jit
    def heavy(a):
        def body(_, x):
            return jnp.tanh(x @ a)

        return jax.lax.fori_loop(0, 40, body, a)

    a = jnp.asarray(np.random.RandomState(0).randn(512, 512)
                    .astype(np.float32))
    jax.block_until_ready(heavy(a))  # compile outside any timed window

    # ground truth: synchronous run time
    t0 = time.perf_counter()
    jax.block_until_ready(heavy(a))
    sync_s = time.perf_counter() - t0

    with profiler.Timer() as t:
        t.block(heavy(a))  # async dispatch; Timer must wait for the result
    assert t.elapsed >= 0.5 * sync_s, \
        f"Timer undercounted: {t.elapsed:.4f}s vs sync {sync_s:.4f}s"


def test_timer_block_returns_outputs_and_nests_pytrees():
    import jax.numpy as jnp

    with profiler.Timer() as t:
        out = t.block(jnp.ones(4) * 2)
        pair = t.block(jnp.zeros(2), {"a": jnp.ones(3)})
    assert float(out.sum()) == 8.0
    assert isinstance(pair, tuple) and len(pair) == 2
    assert t.elapsed >= 0.0
