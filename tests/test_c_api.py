"""Drive the flat C API (native/mxtpu_capi.cc) exactly as an external
binding would — through ctypes with C types only, no Python objects crossing
the boundary. Reference parity target: include/mxnet/c_api.h; the flows
tested here are the ones the reference's R/Python bindings are built from
(NDArray round-trips, registered functions, symbol compose/infer,
executor bind/forward/backward = a real SGD step, iterators, kvstore with a
C updater callback, RecordIO).

The library runs hosted here (loaded into an existing interpreter:
Py_IsInitialized() is true, so it attaches rather than re-initializing);
embedded operation (R / standalone C hosts) takes the Py_InitializeEx path
with PYTHONPATH pointing at the package.
"""

import ctypes
import os
import subprocess

import numpy as np
import pytest

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "mxnet_tpu", "native")
_SO = os.path.join(_DIR, "libmxtpu_capi.so")

mx_uint = ctypes.c_uint
NDHandle = ctypes.c_void_p


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(_SO):
        r = subprocess.run(["make", "-C", _DIR, "capi", "-s"],
                           capture_output=True, text=True, timeout=300)
        if not os.path.exists(_SO):
            pytest.skip(f"cannot build libmxtpu_capi.so: {r.stderr[-400:]}")
    lib = ctypes.CDLL(_SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def check(lib, rc):
    assert rc == 0, lib.MXGetLastError().decode()


def make_ndarray(lib, arr):
    arr = np.ascontiguousarray(arr, np.float32)
    shape = (mx_uint * arr.ndim)(*arr.shape)
    h = NDHandle()
    check(lib, lib.MXNDArrayCreate(shape, arr.ndim, 1, 0, 0,
                                   ctypes.byref(h)))
    check(lib, lib.MXNDArraySyncCopyFromCPU(
        h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), arr.size))
    return h


def read_ndarray(lib, h):
    ndim = mx_uint()
    pdata = ctypes.POINTER(mx_uint)()
    check(lib, lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                     ctypes.byref(pdata)))
    shape = tuple(pdata[i] for i in range(ndim.value))
    out = np.empty(shape, np.float32)
    n = int(np.prod(shape)) if shape else 1
    check(lib, lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n))
    return out


def test_ndarray_roundtrip_slice_context(lib):
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = make_ndarray(lib, a)
    assert np.array_equal(read_ndarray(lib, h), a)

    sl = NDHandle()
    check(lib, lib.MXNDArraySlice(h, 1, 3, ctypes.byref(sl)))
    assert np.array_equal(read_ndarray(lib, sl), a[1:3])

    dt, di = ctypes.c_int(), ctypes.c_int()
    check(lib, lib.MXNDArrayGetContext(h, ctypes.byref(dt), ctypes.byref(di)))
    assert dt.value == 1
    check(lib, lib.MXNDArrayFree(sl))
    check(lib, lib.MXNDArrayFree(h))


def test_ndarray_save_load(lib, tmp_path):
    f = str(tmp_path / "arrays.nd").encode()
    a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    h = make_ndarray(lib, a)
    keys = (ctypes.c_char_p * 1)(b"w")
    check(lib, lib.MXNDArraySave(f, 1, (NDHandle * 1)(h), keys))

    n = mx_uint()
    arrs = ctypes.POINTER(NDHandle)()
    nn = mx_uint()
    names = ctypes.POINTER(ctypes.c_char_p)()
    check(lib, lib.MXNDArrayLoad(f, ctypes.byref(n), ctypes.byref(arrs),
                                 ctypes.byref(nn), ctypes.byref(names)))
    assert n.value == 1 and nn.value == 1
    assert names[0] == b"w"
    assert np.allclose(read_ndarray(lib, NDHandle(arrs[0])), a)


def test_functions_list_and_invoke(lib):
    n = mx_uint()
    fns = ctypes.POINTER(ctypes.c_void_p)()
    check(lib, lib.MXListFunctions(ctypes.byref(n), ctypes.byref(fns)))
    assert n.value >= 18  # the reference registers 18 (ndarray.cc:601-652)

    fh = ctypes.c_void_p()
    check(lib, lib.MXGetFunction(b"_plus", ctypes.byref(fh)))
    nuse, nsc, nmut, mask = mx_uint(), mx_uint(), mx_uint(), ctypes.c_int()
    check(lib, lib.MXFuncDescribe(fh, ctypes.byref(nuse), ctypes.byref(nsc),
                                  ctypes.byref(nmut), ctypes.byref(mask)))
    assert (nuse.value, nsc.value, nmut.value) == (2, 0, 1)

    a = make_ndarray(lib, np.ones((2, 2)))
    b = make_ndarray(lib, np.full((2, 2), 3.0))
    out = make_ndarray(lib, np.zeros((2, 2)))
    check(lib, lib.MXFuncInvoke(fh, (NDHandle * 2)(a, b), None,
                                (NDHandle * 1)(out)))
    assert np.allclose(read_ndarray(lib, out), 4.0)


def _make_mlp_symbol(lib):
    """data -> FullyConnected(4) -> relu -> FullyConnected(2) -> softmax,
    built the way bindings do: CreateAtomicSymbol + Compose."""
    def atomic(opname, **params):
        creators_n = mx_uint()
        creators = ctypes.POINTER(ctypes.c_void_p)()
        check(lib, lib.MXSymbolListAtomicSymbolCreators(
            ctypes.byref(creators_n), ctypes.byref(creators)))
        name_p = ctypes.c_char_p()
        # find the creator whose name matches
        for i in range(creators_n.value):
            desc = ctypes.c_char_p()
            nargs = mx_uint()
            an = ctypes.POINTER(ctypes.c_char_p)()
            at = ctypes.POINTER(ctypes.c_char_p)()
            ad = ctypes.POINTER(ctypes.c_char_p)()
            kv = ctypes.c_char_p()
            check(lib, lib.MXSymbolGetAtomicSymbolInfo(
                ctypes.c_void_p(creators[i]), ctypes.byref(name_p),
                ctypes.byref(desc), ctypes.byref(nargs), ctypes.byref(an),
                ctypes.byref(at), ctypes.byref(ad), ctypes.byref(kv)))
            if name_p.value == opname.encode():
                keys = (ctypes.c_char_p * len(params))(
                    *[k.encode() for k in params])
                vals = (ctypes.c_char_p * len(params))(
                    *[str(v).encode() for v in params.values()])
                h = ctypes.c_void_p()
                check(lib, lib.MXSymbolCreateAtomicSymbol(
                    ctypes.c_void_p(creators[i]), len(params), keys, vals,
                    ctypes.byref(h)))
                return h
        raise AssertionError(f"op {opname} not found")

    def compose(sym, name, **inputs):
        keys = (ctypes.c_char_p * len(inputs))(*[k.encode() for k in inputs])
        args = (ctypes.c_void_p * len(inputs))(*inputs.values())
        check(lib, lib.MXSymbolCompose(sym, name.encode(), len(inputs), keys,
                                       args))

    data = ctypes.c_void_p()
    check(lib, lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)))
    fc1 = atomic("FullyConnected", num_hidden=4)
    compose(fc1, "fc1", data=data)
    act = atomic("Activation", act_type="relu")
    compose(act, "relu1", data=fc1)
    fc2 = atomic("FullyConnected", num_hidden=2)
    compose(fc2, "fc2", data=act)
    sm = atomic("SoftmaxOutput")
    compose(sm, "softmax", data=fc2)
    return sm


def test_symbol_compose_infer_json(lib):
    sm = _make_mlp_symbol(lib)
    n = mx_uint()
    names = ctypes.POINTER(ctypes.c_char_p)()
    check(lib, lib.MXSymbolListArguments(sm, ctypes.byref(n),
                                         ctypes.byref(names)))
    args = [names[i].decode() for i in range(n.value)]
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "softmax_label"]

    js = ctypes.c_char_p()
    check(lib, lib.MXSymbolSaveToJSON(sm, ctypes.byref(js)))
    back = ctypes.c_void_p()
    check(lib, lib.MXSymbolCreateFromJSON(js.value, ctypes.byref(back)))

    # infer shapes for data=(5, 3)
    keys = (ctypes.c_char_p * 1)(b"data")
    ind = (mx_uint * 2)(0, 2)
    shp = (mx_uint * 2)(5, 3)
    in_n, out_n, aux_n = mx_uint(), mx_uint(), mx_uint()
    in_nd = ctypes.POINTER(mx_uint)()
    out_nd = ctypes.POINTER(mx_uint)()
    aux_nd = ctypes.POINTER(mx_uint)()
    in_d = ctypes.POINTER(ctypes.POINTER(mx_uint))()
    out_d = ctypes.POINTER(ctypes.POINTER(mx_uint))()
    aux_d = ctypes.POINTER(ctypes.POINTER(mx_uint))()
    comp = ctypes.c_int()
    check(lib, lib.MXSymbolInferShape(
        sm, 1, keys, ind, shp, ctypes.byref(in_n), ctypes.byref(in_nd),
        ctypes.byref(in_d), ctypes.byref(out_n), ctypes.byref(out_nd),
        ctypes.byref(out_d), ctypes.byref(aux_n), ctypes.byref(aux_nd),
        ctypes.byref(aux_d), ctypes.byref(comp)))
    assert comp.value == 1
    # fc1_weight is argument 1: shape (4, 3)
    assert [in_d[1][j] for j in range(in_nd[1])] == [4, 3]
    # output: (5, 2)
    assert [out_d[0][j] for j in range(out_nd[0])] == [5, 2]


def test_executor_trains_through_c_api(lib):
    """The training FFI: bind with gradients, forward/backward, SGD in C
    caller space — proves an external binding can train (what the R
    training layer needs)."""
    rng = np.random.RandomState(0)
    sm = _make_mlp_symbol(lib)

    X = rng.randn(40, 3).astype(np.float32)
    w_true = rng.randn(3)
    y = (X @ w_true > 0).astype(np.float32)

    shapes = {"data": (8, 3), "fc1_weight": (4, 3), "fc1_bias": (4,),
              "fc2_weight": (2, 4), "fc2_bias": (2,), "softmax_label": (8,)}
    arg_names = list(shapes)
    args, grads, reqs = [], [], []
    for name in arg_names:
        init = (rng.randn(*shapes[name]) * 0.3).astype(np.float32) \
            if "weight" in name else np.zeros(shapes[name], np.float32)
        args.append(make_ndarray(lib, init))
        if name in ("data", "softmax_label"):
            grads.append(None)
            reqs.append(0)  # null
        else:
            grads.append(make_ndarray(lib, np.zeros(shapes[name])))
            reqs.append(1)  # write

    exec_h = ctypes.c_void_p()
    arg_arr = (NDHandle * len(args))(*args)
    grad_arr = (NDHandle * len(args))(*[g or None for g in grads])
    req_arr = (mx_uint * len(args))(*reqs)
    check(lib, lib.MXExecutorBind(sm, 1, 0, len(args), arg_arr, grad_arr,
                                  req_arr, 0, None, ctypes.byref(exec_h)))

    losses = []
    lr = 0.5
    for epoch in range(15):
        correct = 0
        for i in range(0, 40, 8):
            xb, yb = X[i:i + 8], y[i:i + 8]
            check(lib, lib.MXNDArraySyncCopyFromCPU(
                args[0], xb.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                xb.size))
            check(lib, lib.MXNDArraySyncCopyFromCPU(
                args[5], yb.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                yb.size))
            check(lib, lib.MXExecutorForward(exec_h, 1))
            n_out = mx_uint()
            outs = ctypes.POINTER(NDHandle)()
            check(lib, lib.MXExecutorOutputs(exec_h, ctypes.byref(n_out),
                                             ctypes.byref(outs)))
            prob = read_ndarray(lib, NDHandle(outs[0]))
            correct += int(np.sum(np.argmax(prob, 1) == yb))
            check(lib, lib.MXExecutorBackward(exec_h, 0, None))
            # SGD on the C side: w -= lr * g, via the registered functions
            for j, name in enumerate(arg_names):
                if grads[j] is None:
                    continue
                w = read_ndarray(lib, args[j])
                g = read_ndarray(lib, grads[j])
                w2 = (w - lr * g / 8).astype(np.float32)
                check(lib, lib.MXNDArraySyncCopyFromCPU(
                    args[j],
                    w2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    w2.size))
        losses.append(correct / 40.0)
    assert losses[-1] >= 0.9, f"C-API training failed to converge: {losses}"


def test_kvstore_with_c_updater(lib):
    UPDATER = ctypes.CFUNCTYPE(None, ctypes.c_int, NDHandle, NDHandle,
                               ctypes.c_void_p)
    calls = []

    @UPDATER
    def sgd_updater(key, recv, local, _):
        # ctypes delivers handle params as bare ints: re-wrap as c_void_p
        # before passing back (else they truncate to 32-bit C ints)
        recv, local = NDHandle(recv), NDHandle(local)
        g = read_ndarray(lib, recv)
        w = read_ndarray(lib, local)
        w2 = (w - 0.1 * g).astype(np.float32)
        check(lib, lib.MXNDArraySyncCopyFromCPU(
            local, w2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            w2.size))
        calls.append(key)

    kv = ctypes.c_void_p()
    check(lib, lib.MXKVStoreCreate(b"local", ctypes.byref(kv)))
    t = ctypes.c_char_p()
    check(lib, lib.MXKVStoreGetType(kv, ctypes.byref(t)))
    assert t.value == b"local"
    check(lib, lib.MXKVStoreSetUpdater(kv, sgd_updater, None))

    w0 = np.ones((4,), np.float32)
    wh = make_ndarray(lib, w0)
    keys = (ctypes.c_int * 1)(3)
    check(lib, lib.MXKVStoreInit(kv, 1, keys, (NDHandle * 1)(wh)))

    gh = make_ndarray(lib, np.full((4,), 2.0, np.float32))
    check(lib, lib.MXKVStorePush(kv, 1, keys, (NDHandle * 1)(gh), 0))
    out = make_ndarray(lib, np.zeros((4,), np.float32))
    check(lib, lib.MXKVStorePull(kv, 1, keys, (NDHandle * 1)(out), 0))
    assert calls == [3]
    assert np.allclose(read_ndarray(lib, out), 1.0 - 0.1 * 2.0)

    rank, size = ctypes.c_int(), ctypes.c_int()
    check(lib, lib.MXKVStoreGetRank(kv, ctypes.byref(rank)))
    check(lib, lib.MXKVStoreGetGroupSize(kv, ctypes.byref(size)))
    assert (rank.value, size.value) == (0, 1)


def test_data_iter_through_c_api(lib, tmp_path):
    # pack a small RecordIO file through the C API writer...
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from mxnet_tpu import recordio as rio

    rec = str(tmp_path / "it.rec")
    w = ctypes.c_void_p()
    check(lib, lib.MXRecordIOWriterCreate(rec.encode(), ctypes.byref(w)))
    rng = np.random.RandomState(0)
    for i in range(24):
        img = rng.randint(0, 255, (12, 12, 3), np.uint8)
        payload = rio.pack_img(rio.IRHeader(0, float(i % 3), i, 0), img,
                               img_fmt=".jpg")
        check(lib, lib.MXRecordIOWriterWriteRecord(
            w, payload, len(payload)))
    check(lib, lib.MXRecordIOWriterFree(w))

    # ...read one record back through the reader...
    r = ctypes.c_void_p()
    check(lib, lib.MXRecordIOReaderCreate(rec.encode(), ctypes.byref(r)))
    buf = ctypes.c_char_p()
    size = ctypes.c_size_t()
    check(lib, lib.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                              ctypes.byref(size)))
    assert size.value > 0
    check(lib, lib.MXRecordIOReaderFree(r))

    # ...and drive ImageRecordIter over it
    n = mx_uint()
    creators = ctypes.POINTER(ctypes.c_void_p)()
    check(lib, lib.MXListDataIters(ctypes.byref(n), ctypes.byref(creators)))
    target = None
    for i in range(n.value):
        name = ctypes.c_char_p()
        desc = ctypes.c_char_p()
        na = mx_uint()
        an = ctypes.POINTER(ctypes.c_char_p)()
        at = ctypes.POINTER(ctypes.c_char_p)()
        ad = ctypes.POINTER(ctypes.c_char_p)()
        check(lib, lib.MXDataIterGetIterInfo(
            ctypes.c_void_p(creators[i]), ctypes.byref(name),
            ctypes.byref(desc), ctypes.byref(na), ctypes.byref(an),
            ctypes.byref(at), ctypes.byref(ad)))
        if name.value == b"ImageRecordIter":
            target = ctypes.c_void_p(creators[i])
    assert target is not None

    keys = [b"path_imgrec", b"data_shape", b"batch_size"]
    vals = [rec.encode(), b"(3, 10, 10)", b"8"]
    it = ctypes.c_void_p()
    check(lib, lib.MXDataIterCreateIter(
        target, len(keys), (ctypes.c_char_p * 3)(*keys),
        (ctypes.c_char_p * 3)(*vals), ctypes.byref(it)))

    total, batches = 0, 0
    has = ctypes.c_int(1)
    while True:
        check(lib, lib.MXDataIterNext(it, ctypes.byref(has)))
        if not has.value:
            break
        data_h, label_h = NDHandle(), NDHandle()
        check(lib, lib.MXDataIterGetData(it, ctypes.byref(data_h)))
        check(lib, lib.MXDataIterGetLabel(it, ctypes.byref(label_h)))
        d = read_ndarray(lib, data_h)
        lab = read_ndarray(lib, label_h)
        assert d.shape == (8, 3, 10, 10)
        assert lab.shape == (8,)
        pad = ctypes.c_int()
        check(lib, lib.MXDataIterGetPadNum(it, ctypes.byref(pad)))
        total += 8 - pad.value
        batches += 1
    assert total == 24 and batches == 3
    check(lib, lib.MXDataIterBeforeFirst(it))
    check(lib, lib.MXDataIterNext(it, ctypes.byref(has)))
    assert has.value == 1


def test_random_seed_and_error_path(lib):
    check(lib, lib.MXRandomSeed(7))
    # error path: bad op name through atomic creator is caught and reported
    h = ctypes.c_void_p()
    rc = lib.MXSymbolCreateFromJSON(b"{not json", ctypes.byref(h))
    assert rc == -1
    assert len(lib.MXGetLastError()) > 0


def test_ndarray_raw_bytes_roundtrip(lib):
    a = np.random.RandomState(1).randn(3, 5).astype(np.float32)
    h = make_ndarray(lib, a)
    size = ctypes.c_size_t()
    buf = ctypes.POINTER(ctypes.c_char)()
    check(lib, lib.MXNDArraySaveRawBytes(h, ctypes.byref(size),
                                         ctypes.byref(buf)))
    assert size.value > a.nbytes
    raw = ctypes.string_at(buf, size.value)
    h2 = NDHandle()
    check(lib, lib.MXNDArrayLoadFromRawBytes(raw, len(raw),
                                             ctypes.byref(h2)))
    assert np.array_equal(read_ndarray(lib, h2), a)


def test_symbol_internals_and_output_slice(lib):
    sm = _make_mlp_symbol(lib)
    internals = ctypes.c_void_p()
    check(lib, lib.MXSymbolGetInternals(sm, ctypes.byref(internals)))
    n = mx_uint()
    names = ctypes.POINTER(ctypes.c_char_p)()
    check(lib, lib.MXSymbolListOutputs(internals, ctypes.byref(n),
                                       ctypes.byref(names)))
    outs = [names[i].decode() for i in range(n.value)]
    assert "fc1_output" in outs
    idx = outs.index("fc1_output")
    head = ctypes.c_void_p()
    check(lib, lib.MXSymbolGetOutput(internals, idx, ctypes.byref(head)))
    n2 = mx_uint()
    check(lib, lib.MXSymbolListOutputs(head, ctypes.byref(n2),
                                       ctypes.byref(names)))
    assert n2.value == 1 and names[0] == b"fc1_output"


def test_wait_and_shutdown_and_getdata(lib):
    a = make_ndarray(lib, np.arange(6, dtype=np.float32).reshape(2, 3))
    check(lib, lib.MXNDArrayWaitToRead(a))
    check(lib, lib.MXNDArrayWaitToWrite(a))
    check(lib, lib.MXNDArrayWaitAll())
    p = ctypes.POINTER(ctypes.c_float)()
    check(lib, lib.MXNDArrayGetData(a, ctypes.byref(p)))
    assert [p[i] for i in range(6)] == [0, 1, 2, 3, 4, 5]
    check(lib, lib.MXNotifyShutdown())  # no-op, must not invalidate state
    b = make_ndarray(lib, np.ones((2, 2)))
    assert np.allclose(read_ndarray(lib, b), 1.0)
