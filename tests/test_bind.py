"""Executor bind tests (reference: tests/python/unittest/test_bind.py —
bind + gradient correctness vs numpy for composed graphs)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _same(a, b, tol=1e-4):
    np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


def test_bind_mul_graph():
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    net = lhs * rhs
    shape = (4, 4)
    lv = np.random.uniform(-1, 1, shape).astype(np.float32)
    rv = np.random.uniform(-1, 1, shape).astype(np.float32)
    args = {"lhs": mx.nd.array(lv), "rhs": mx.nd.array(rv)}
    grads = {"lhs": mx.nd.zeros(shape), "rhs": mx.nd.zeros(shape)}
    exe = net.bind(mx.cpu(), args=args, args_grad=grads)
    (o,) = exe.forward(is_train=True)
    _same(o.asnumpy(), lv * rv)
    og = np.random.uniform(-1, 1, shape).astype(np.float32)
    exe.backward([mx.nd.array(og)])
    _same(grads["lhs"].asnumpy(), og * rv)
    _same(grads["rhs"].asnumpy(), og * lv)


def test_bind_positional_lists():
    a = sym.Variable("a")
    b = sym.Variable("b")
    net = a + b
    shape = (3, 3)
    args = [mx.nd.ones(shape), mx.nd.ones(shape)]
    grads = [mx.nd.zeros(shape), mx.nd.zeros(shape)]
    exe = net.bind(mx.cpu(), args=args, args_grad=grads)
    (o,) = exe.forward(is_train=True)
    _same(o.asnumpy(), np.full(shape, 2.0))
    exe.backward([mx.nd.ones(shape)])
    _same(grads[0].asnumpy(), np.ones(shape))


def test_grad_req_add():
    x = sym.Variable("x")
    net = x * x
    shape = (2, 2)
    xv = np.full(shape, 3.0, np.float32)
    args = {"x": mx.nd.array(xv)}
    grads = {"x": mx.nd.zeros(shape)}
    exe = net.bind(mx.cpu(), args=args, args_grad=grads, grad_req="add")
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones(shape)])
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones(shape)])
    _same(grads["x"].asnumpy(), 2 * 2 * xv)  # accumulated twice


def test_grad_req_null():
    x = sym.Variable("x")
    y = sym.Variable("y")
    net = x * y
    shape = (2, 2)
    args = {"x": mx.nd.ones(shape), "y": mx.nd.ones(shape)}
    grads = {"x": mx.nd.zeros(shape)}
    exe = net.bind(mx.cpu(), args=args, args_grad=grads,
                   grad_req={"x": "write", "y": "null"})
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones(shape)])
    _same(grads["x"].asnumpy(), np.ones(shape))


def test_forward_kwargs_update_args():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc", num_hidden=2)
    exe = net.simple_bind(mx.cpu(), data=(3, 4))
    w = np.random.uniform(size=(2, 4)).astype(np.float32)
    exe.arg_dict["fc_weight"][:] = w
    dv = np.random.uniform(size=(3, 4)).astype(np.float32)
    (o,) = exe.forward(data=mx.nd.array(dv))
    _same(o.asnumpy(), dv @ w.T, tol=1e-4)
    _same(exe.arg_dict["data"].asnumpy(), dv)


def test_copy_params_from():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc", num_hidden=2)
    exe = net.simple_bind(mx.cpu(), data=(3, 4))
    w = mx.nd.array(np.random.uniform(size=(2, 4)).astype(np.float32))
    exe.copy_params_from({"fc_weight": w})
    _same(exe.arg_dict["fc_weight"].asnumpy(), w.asnumpy())


def test_debug_str():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc", num_hidden=2)
    exe = net.simple_bind(mx.cpu(), data=(3, 4))
    exe.forward()
    s = exe.debug_str()
    assert "fc" in s


def test_backward_uses_captured_residuals():
    """forward(is_train=True)+backward() must not re-run the forward pass:
    the executor captures VJP residuals in the forward program (reference
    contract: GraphExecutor::Forward/Backward each run their half once,
    graph_executor.cc:616-643)."""
    import numpy as np

    import mxnet_tpu as mx

    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(data=fc, name="softmax")
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 8), softmax_label=(2,))
    rng = np.random.RandomState(0)
    ex.arg_dict["data"][:] = rng.randn(2, 8)
    ex.arg_dict["fc_weight"][:] = rng.randn(4, 8) * 0.1
    ex.forward(is_train=True)
    assert ex._res_ok and ex._res_leaves is not None
    ex.backward()
    g_res = ex.grad_dict["fc_weight"].asnumpy().copy()

    # the fallback (fused fwd+bwd recompute) must agree
    ex2 = net.simple_bind(ctx=mx.cpu(), data=(2, 8), softmax_label=(2,))
    ex2._res_ok = False
    for k in ("data", "fc_weight"):
        ex.arg_dict[k].copyto(ex2.arg_dict[k])
    ex2.forward(is_train=True)
    assert ex2._res_leaves is None
    ex2.backward()
    assert np.allclose(g_res, ex2.grad_dict["fc_weight"].asnumpy(), atol=1e-5)
