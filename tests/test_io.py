"""IO tests (reference: tests/python/unittest/test_io.py — epoch determinism,
NDArrayIter padding; datasets are synthesized since this environment has no
network access)."""

import gzip
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio


def test_ndarray_iter_basic():
    data = np.random.uniform(size=(100, 3)).astype(np.float32)
    label = np.arange(100, dtype=np.float32)
    it = mio.NDArrayIter(data, label, batch_size=10)
    batches = list(it)
    assert len(batches) == 10
    for i, b in enumerate(batches):
        np.testing.assert_allclose(b.data[0].asnumpy(), data[i * 10:(i + 1) * 10])
        np.testing.assert_allclose(b.label[0].asnumpy(), label[i * 10:(i + 1) * 10])
        assert b.pad == 0


def test_ndarray_iter_padding():
    """Reference: test_NDArrayIter — 105 samples, batch 10 -> last batch pad 5
    wrapping to epoch start."""
    data = np.arange(105, dtype=np.float32).reshape(105, 1)
    it = mio.NDArrayIter(data, np.arange(105, dtype=np.float32), batch_size=10)
    batches = list(it)
    assert len(batches) == 11
    assert batches[-1].pad == 5
    last = batches[-1].data[0].asnumpy().ravel()
    np.testing.assert_allclose(last[:5], np.arange(100, 105))
    np.testing.assert_allclose(last[5:], np.arange(0, 5))  # wrapped


def test_ndarray_iter_epoch_determinism():
    data = np.random.uniform(size=(40, 2)).astype(np.float32)
    it = mio.NDArrayIter(data, np.zeros(40, np.float32), batch_size=8)
    e1 = [b.data[0].asnumpy() for b in it]
    e2 = [b.data[0].asnumpy() for b in it]
    for a, b in zip(e1, e2):
        np.testing.assert_allclose(a, b)


def test_ndarray_iter_shuffle_covers_all():
    data = np.arange(32, dtype=np.float32).reshape(32, 1)
    it = mio.NDArrayIter(data, np.zeros(32, np.float32), batch_size=8, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(32))


def _write_idx(path, arr):
    """Write an idx-format file (the MNIST container format)."""
    dtype_code = {np.uint8: 0x08, np.float32: 0x0D}[arr.dtype.type]
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, dtype_code, arr.ndim))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        f.write(arr.tobytes())


def test_mnist_iter(tmp_path):
    images = (np.random.uniform(0, 255, (50, 28, 28))).astype(np.uint8)
    labels = np.random.randint(0, 10, (50,)).astype(np.uint8)
    img_path, lbl_path = str(tmp_path / "img.idx"), str(tmp_path / "lbl.idx")
    _write_idx(img_path, images)
    _write_idx(lbl_path, labels)

    it = mio.MNISTIter(image=img_path, label=lbl_path, batch_size=10, flat=True)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (10, 784)
    np.testing.assert_allclose(
        batches[0].data[0].asnumpy(), images[:10].reshape(10, 784) / 255.0,
        rtol=1e-5,
    )
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), labels[:10])

    it4 = mio.MNISTIter(image=img_path, label=lbl_path, batch_size=10, flat=False)
    b = next(iter(it4))
    assert b.data[0].shape == (10, 1, 28, 28)


def test_mnist_iter_sharding(tmp_path):
    images = np.arange(40 * 4, dtype=np.uint8).reshape(40, 2, 2)
    labels = np.arange(40, dtype=np.uint8)
    img_path, lbl_path = str(tmp_path / "i.idx"), str(tmp_path / "l.idx")
    _write_idx(img_path, images)
    _write_idx(lbl_path, labels)
    part0 = mio.MNISTIter(image=img_path, label=lbl_path, batch_size=5,
                          flat=True, num_parts=2, part_index=0)
    part1 = mio.MNISTIter(image=img_path, label=lbl_path, batch_size=5,
                          flat=True, num_parts=2, part_index=1)
    l0 = np.concatenate([b.label[0].asnumpy() for b in part0])
    l1 = np.concatenate([b.label[0].asnumpy() for b in part1])
    assert len(l0) == 20 and len(l1) == 20
    assert not np.allclose(l0, l1)


def test_prefetching_iter():
    data = np.random.uniform(size=(64, 3)).astype(np.float32)
    base = mio.NDArrayIter(data, np.zeros(64, np.float32), batch_size=8)
    pf = mio.PrefetchingIter(base)
    b1 = [b.data[0].asnumpy() for b in pf]
    assert len(b1) == 8
    # second epoch works and matches
    b2 = [b.data[0].asnumpy() for b in pf]
    for a, b in zip(b1, b2):
        np.testing.assert_allclose(a, b)


def test_prefetching_iter_reset_mid_epoch_drains_queue():
    """Regression for the deque future queue: a reset() mid-epoch must
    drain the in-flight prefetch futures and restart cleanly from the
    epoch head — no stale batch from the abandoned epoch may leak, and
    the full epoch after the reset matches an undisturbed pass."""
    data = np.arange(64 * 3, dtype=np.float32).reshape(64, 3)
    ref = [b.data[0].asnumpy()
           for b in mio.NDArrayIter(data, np.zeros(64, np.float32),
                                    batch_size=8)]
    base = mio.NDArrayIter(data, np.zeros(64, np.float32), batch_size=8)
    pf = mio.PrefetchingIter(base, depth=4)
    pf.reset()
    for _ in range(3):  # abandon the epoch with futures still queued
        pf.next()
    assert len(pf._queue) > 0  # in-flight work to drain
    pf.reset()
    fresh = []
    while True:
        try:
            fresh.append(pf.next().data[0].asnumpy())
        except StopIteration:
            break
    assert len(fresh) == len(ref)
    for a, b in zip(fresh, ref):
        np.testing.assert_allclose(a, b)
    # and the NEXT epoch still starts at the head (exhaustion handled)
    pf.reset()
    np.testing.assert_allclose(pf.next().data[0].asnumpy(), ref[0])


def test_csv_iter(tmp_path):
    data = np.random.uniform(size=(20, 4)).astype(np.float32)
    labels = np.arange(20, dtype=np.float32)
    dpath, lpath = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, labels, delimiter=",")
    it = mio.CSVIter(data_csv=dpath, data_shape=(4,), label_csv=lpath, batch_size=5)
    batches = list(it)
    assert len(batches) == 4
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5], rtol=1e-5)
