"""Device-time profiler acceptance (ISSUE 15).

Covers: config resolution and the capture doorway (hub events, soft
failure on concurrent windows, finally-safe stop), named-scope provenance
landing in HLO op metadata (executor scopes AND user ``profile_scope``
annotations), trace parsing + attribution on a real capture, the e2e
contract — a profiled dp-8 ``fit`` window attributes >= 80% of in-window
device time to named layers/kernels with an explicit unattributed row,
produces ``source: "measured"`` roofline rows joined to the FLOP models,
reconciles measured vs modeled MFU, prices the window as ``profile``
badput, and stays green under the armed zero-recompile epoch stacked on
compression + overlap + fused-Adam + guards + health — plus
``predict(profile=...)``, the flight-recorder profile section (CRC-valid
with and without), the ``telemetry profile`` CLI, the per-op rows in the
``telemetry diff`` CI gate, schema back-fill, and the out-of-window
overhead bound (<0.5% of a step).
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import profiling
from mxnet_tpu.utils import compile as cm
from mxnet_tpu.utils import profiler as profiler_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_hub():
    telemetry.reset()
    yield
    # a failing test must never leak a running process-global trace into
    # the rest of the suite
    profiling.stop_capture()


def _ctx8():
    return [mx.cpu(i) for i in range(8)]


def _mlp(hidden=64, classes=4, dim=10):
    data = mx.sym.Variable("data")
    h1 = mx.sym.Activation(mx.sym.FullyConnected(
        data, name="fc1", num_hidden=hidden), name="a1", act_type="tanh")
    return mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        h1, name="fc2", num_hidden=classes), name="softmax")


def _blobs(n=160, dim=10, classes=4):
    rng = np.random.RandomState(0)
    X = rng.randn(n, dim).astype(np.float32)
    y = rng.randint(0, classes, (n,)).astype(np.float32)
    return X, y


# -- config + capture doorway --------------------------------------------------

def test_profile_config_resolution(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_PROFILE", raising=False)
    assert profiling.ProfileConfig.resolve(None) is None
    assert profiling.ProfileConfig.resolve(False) is None
    cfg = profiling.ProfileConfig.resolve(True)
    assert cfg.steps == 6 and cfg.warmup == 2
    assert profiling.ProfileConfig.resolve(9).steps == 9
    assert profiling.ProfileConfig.resolve(cfg) is cfg
    monkeypatch.setenv("MXNET_TPU_PROFILE", "0")
    assert profiling.ProfileConfig.resolve(None) is None
    monkeypatch.setenv("MXNET_TPU_PROFILE", "1")
    assert profiling.ProfileConfig.resolve(None).steps == 6
    monkeypatch.setenv("MXNET_TPU_PROFILE", "12")
    assert profiling.ProfileConfig.resolve(None).steps == 12
    # 0 means off everywhere: a computed "no window" stays a no-op, like
    # the env gate's MXNET_TPU_PROFILE=0
    assert profiling.ProfileConfig.resolve(0) is None
    assert profiling.ProfileConfig.resolve(-3) is None
    with pytest.raises(ValueError):
        profiling.ProfileConfig.resolve(1.5)


def test_capture_emits_hub_events_and_fails_soft(tmp_path):
    """The capture doorway: start/stop are hub events (a JSONL sink sees
    every capture), a concurrent window raises for the CALLER to handle,
    and an unmatched stop is a safe no-op."""
    assert profiling.stop_capture() == (None, 0.0)  # finally-safe
    d = str(tmp_path / "trace")
    with profiling.capture(d, owner="test"):
        assert profiling.capture_active() == d
        with pytest.raises(RuntimeError):
            profiling.start_capture(str(tmp_path / "other"))
    assert profiling.capture_active() is None
    phases = [e["phase"] for e in telemetry.hub().events(kind="profile")]
    assert phases == ["start", "capture"]
    caps = [e for e in telemetry.hub().events(kind="profile")
            if e["phase"] == "capture"]
    assert caps[0]["seconds"] > 0 and caps[0]["owner"] == "test"


def test_profiler_module_routes_through_capture_path(tmp_path):
    """ISSUE 15 satellite: utils.profiler.start_trace/stop_trace and
    profile_step ride the shared capture path — hub events, one window
    at a time — instead of a second uninstrumented doorway."""
    d = str(tmp_path / "t")
    profiler_mod.start_trace(d)
    try:
        assert profiling.capture_active() == d
    finally:
        profiler_mod.stop_trace()
    assert profiling.capture_active() is None

    f = jax.jit(lambda x: jnp.tanh(x @ x))
    x = jnp.ones((64, 64))
    stats, log_dir = profiler_mod.profile_step(
        f, x, iters=2, log_dir=str(tmp_path / "ps"))
    assert stats and stats[0].total_us > 0
    phases = [e["phase"] for e in telemetry.hub().events(kind="profile")]
    assert phases == ["start", "capture", "start", "capture"]


def test_profile_scope_lands_in_hlo_metadata():
    """ISSUE 15 satellite: a user ``profile_scope`` annotation doubles as
    a named_scope, so its ops carry the scope in HLO op metadata and the
    attribution tables can name them like a framework layer."""
    def f(x):
        with profiler_mod.profile_scope("userblock"):
            return jnp.tanh(x @ x)

    txt = jax.jit(f).lower(jnp.ones((32, 32))).compile().as_text()
    _, meta = profiling.hlo_op_metadata(txt)
    assert any("userblock" in v for v in meta.values()), meta
    layer, prim = profiling.attribute_op_name(
        next(v for v in meta.values() if "userblock" in v), {"userblock"})
    assert layer == "userblock"


# -- attribution machinery -----------------------------------------------------

def test_attribute_op_name_unwraps_transforms():
    layers = {"fc1", "a1"}
    cases = [
        ("jit(step)/jit(main)/jvp(fc1/FullyConnected)/dot_general",
         "fc1", "dot_general"),
        ("jit(step)/jit(main)/transpose(jvp(fc1/FullyConnected))/dot_general",
         "fc1", "dot_general"),
        ("jit(step)/jit(main)/shmap_body/a1/Activation/tanh", "a1", "tanh"),
        ("jit(step)/jit(main)/optimizer/update/sub", "optimizer", "sub"),
        ("jit(step)/jit(main)/comm/allreduce/psum", "comm", "psum"),
        ("jit(step)/jit(main)/convert_element_type", None,
         "convert_element_type"),
    ]
    for op_name, want_layer, want_prim in cases:
        layer, prim = profiling.attribute_op_name(op_name, layers)
        assert (layer, prim) == (want_layer, want_prim), op_name


def test_parse_and_build_report_on_real_capture(tmp_path):
    """Capture a scoped jitted fn, parse the trace, join through the HLO
    metadata map: the report attributes the layers, carries an explicit
    unattributed remainder, and its coverage is consistent."""
    def f(x, w1, w2):
        with jax.named_scope("l1"):
            h = jnp.tanh(x @ w1)
        with jax.named_scope("l2"):
            return jnp.sum(h @ w2)

    jf = jax.jit(f)
    x = jnp.ones((256, 256))
    w1 = jnp.ones((256, 256))
    w2 = jnp.ones((256, 64))
    jax.block_until_ready(jf(x, w1, w2))  # compile outside the window
    d = str(tmp_path / "trace")
    with profiling.capture(d):
        for _ in range(3):
            out = jf(x, w1, w2)
        jax.block_until_ready(out)
    rows = profiling.parse_trace_dir(d)
    assert rows and all(r["us"] >= 0 for r in rows.values())
    _, meta = profiling.hlo_op_metadata(
        jf.lower(x, w1, w2).compile().as_text())
    report = profiling.build_report(rows, [meta], {"l1", "l2"}, steps=3,
                                    window_seconds=0.1)
    assert report.total_us > 0
    assert {"l1", "l2"} <= set(report.layers)
    assert report.coverage_pct > 50.0
    assert abs(report.attributed_us + report.unattributed_us
               - report.total_us) < 1e-6
    top = report.to_dict(top_k=5)["top"]
    assert len(top) <= 5 and top[0]["us"] >= top[-1]["us"]


def test_parse_trace_dir_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        profiling.parse_trace_dir(str(tmp_path / "empty"))


def test_measured_peak_bandwidth_cached():
    bw = profiling.measured_peak_bandwidth()
    assert bw > 0
    assert profiling.measured_peak_bandwidth() == bw  # cached


# -- the e2e fit contract ------------------------------------------------------

def _profiled_fit(tmp_path, **fit_kwargs):
    X, y = _blobs(256)
    model = mx.FeedForward(_mlp(), ctx=_ctx8(), num_epoch=2,
                           optimizer="adam", fused=True,
                           learning_rate=0.01)
    jsonl = str(tmp_path / "run.jsonl")
    model.fit(X, y, batch_size=32,
              telemetry=telemetry.TelemetryConfig(jsonl=jsonl,
                                                  memory=False),
              profile=telemetry.ProfileConfig(steps=4, warmup=2),
              **fit_kwargs)
    return model, jsonl


def test_fit_profile_window_acceptance(tmp_path):
    """ACCEPTANCE: a profiled dp-8 fit window (guards + health + int8
    compression + overlap + fused-Adam stacked) attributes >= 80% of
    in-window device time to named layers/kernels, reports the coverage
    ratio and an explicit unattributed row, joins measured roofline rows
    to the registry FLOP models with source="measured", reconciles
    measured vs modeled MFU, and prices the window as profile badput."""
    model, jsonl = _profiled_fit(tmp_path, guards=True, health=True,
                                 compression="int8", overlap=True)
    rep = model.profile_report
    assert rep is not None and rep.steps == 4
    assert rep.coverage_pct >= 80.0, rep.table()
    assert rep.unattributed_us >= 0.0
    # real model layers attributed, not just the pseudo-categories
    assert {"fc1", "fc2"} <= set(rep.layers), rep.layers
    assert "comm" in rep.layers  # the int8 sync's device cost is named
    # measured roofline: source stamped, models joined, bound classified
    assert rep.roofline, "no measured roofline rows"
    for row in rep.roofline:
        assert row["source"] == "measured"
        assert row["model_flops"] > 0
        assert row.get("bound") in ("compute", "bandwidth", None)
    prims = {r["op"] for r in rep.roofline}
    assert "dot_general" in prims
    # measured-vs-modeled MFU reconciliation resolved
    assert rep.mfu["measured_mfu_pct"] is not None
    assert rep.mfu["modeled_mfu_pct"] is not None
    assert rep.mfu["delta_pct"] == pytest.approx(
        rep.mfu["measured_mfu_pct"] - rep.mfu["modeled_mfu_pct"])

    # the window is priced as `profile` badput — observation, not
    # throughput — and the epoch summary carries the bucket
    h = telemetry.hub()
    bads = [e for e in h.events(kind="badput")
            if e.get("reason") == "profile"]
    assert bads and bads[0]["seconds"] > 0
    snap = h.snapshot()
    assert snap["counters"].get("badput_profile_seconds_total", 0) > 0
    epochs = [e for e in h.events(kind="epoch_summary")]
    assert any(e.get("badput_profile_seconds", 0) > 0 for e in epochs)

    # surface: summary event with golden keys + per-layer gauges
    summaries = [e for e in h.events(kind="profile")
                 if e.get("phase") == "summary"]
    assert len(summaries) == 1
    s = summaries[0]
    for key in telemetry.EVENT_GOLDEN_KEYS["profile"]:
        assert key in s, key
    assert s["steps"] == 4 and s["coverage_pct"] >= 80.0
    gauges = snap["gauges"]
    assert gauges.get("profile_coverage_pct", 0) >= 80.0
    assert any(k.startswith("profile_layer_device_ms") for k in gauges), \
        sorted(gauges)

    # the JSONL stream saw the capture lifecycle
    rows = telemetry.read_events(jsonl)
    phases = [e["phase"] for e in rows if e.get("kind") == "profile"]
    assert phases == ["start", "capture", "summary"]


def test_fit_profile_zero_recompile_full_stack():
    """ACCEPTANCE: the armed zero-recompile epoch stays green with
    named-scope annotations + a profiling window stacked on compression +
    overlap + fused-Adam + guards + health — scopes are trace-time
    metadata, and the window's HLO harvest precompiles (never a cache
    miss)."""
    X, y = _blobs(160)
    model = mx.FeedForward(_mlp(), ctx=_ctx8(), num_epoch=3,
                           optimizer="adam", fused=True,
                           learning_rate=0.01)
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    cm.reset_compile_stats()
    try:
        # warmup=6 places the window in epoch 2 — inside the ARMED span,
        # so the capture machinery itself is proven recompile-free
        model.fit(X, y, batch_size=32, compression="int8", overlap=True,
                  guards=True, health=True,
                  profile=telemetry.ProfileConfig(steps=3, warmup=6),
                  epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    assert tracker.recompiles == []
    per = cm.compile_stats()["per_function"]
    train = [c for lbl, c in per.items() if lbl.startswith("train_step:")]
    assert train and train[0]["misses"] == 1  # compiled exactly once
    assert model.profile_report is not None
    assert model.profile_report.coverage_pct >= 80.0


def test_fit_profile_out_of_window_overhead():
    """ACCEPTANCE: once the window closes, the loop's per-step profiler
    cost is one state poll — priced per-poll against the session's own
    measured window, far under 0.5% of a step."""
    import time

    ses = profiling.ProfileSession(telemetry.ProfileConfig(), layers=())
    ses._state = "done"
    reps = 50000
    t0 = time.perf_counter()
    for _ in range(reps):
        _ = ses.pending
        _ = ses.open
    poll_s = (time.perf_counter() - t0) / reps
    # 0.5% of even a very fast 1 ms step is 5 us; the poll is ~100 ns
    assert poll_s < 5e-6, f"out-of-window poll {poll_s * 1e9:.0f} ns"
    # and a done session's hooks are no-ops
    assert ses.after_step(None) == 0.0
    assert ses.close() == 0.0


def test_predict_profile_emits_summary(tmp_path):
    X, _ = _blobs(256)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    model._init_params({"data": (32, 10), "softmax_label": (32,)})
    out = model.predict(X, batch_size=32,
                        profile=telemetry.ProfileConfig(steps=3, warmup=1))
    assert out.shape == (256, 4)
    rep = model.profile_report
    assert rep is not None and rep.steps == 3
    assert rep.coverage_pct > 0
    summaries = [e for e in telemetry.hub().events(kind="profile")
                 if e.get("phase") == "summary"]
    assert summaries and summaries[0]["owner"] == "predict"


def test_reused_log_dir_isolates_windows(tmp_path):
    """A ProfileConfig with an explicit log_dir can be reused: every
    window captures into its own subdirectory, so a second run's report
    never folds the first window's trace events into its totals."""
    cfg = telemetry.ProfileConfig(steps=3, warmup=1,
                                  log_dir=str(tmp_path / "prof"))
    X, _ = _blobs(256)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    model._init_params({"data": (32, 10), "softmax_label": (32,)})
    model.predict(X, batch_size=32, profile=cfg)
    first = model.profile_report
    model.predict(X, batch_size=32, profile=cfg)
    second = model.profile_report
    # the structural fix: sibling per-window directories under the
    # configured dir, so the second parse cannot see the first's files
    assert first.log_dir != second.log_dir
    assert os.path.dirname(first.log_dir) == str(tmp_path / "prof")
    assert os.path.dirname(second.log_dir) == str(tmp_path / "prof")
    assert first.steps == second.steps == 3
    # same program, same window length: the second report must be in the
    # same ballpark, not a two-window aggregate (the bug read ~2x;
    # generous margin for shared-box noise)
    assert second.total_us < 1.75 * first.total_us, \
        (first.total_us, second.total_us)


def test_short_predict_closes_partial_window():
    """A dataset shorter than warmup+steps still closes cleanly: the
    partial window publishes what it captured and the process-global
    profiler is released."""
    X, _ = _blobs(96)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    model._init_params({"data": (32, 10), "softmax_label": (32,)})
    model.predict(X, batch_size=32,
                  profile=telemetry.ProfileConfig(steps=50, warmup=1))
    assert profiling.capture_active() is None
    rep = model.profile_report
    assert rep is not None and 0 < rep.steps < 50


# -- flight-recorder section ---------------------------------------------------

def test_flight_dump_embeds_last_capture(tmp_path):
    """Flight dumps embed the last capture summary; dumps from
    un-profiled processes simply lack the section — both CRC-validate."""
    from mxnet_tpu.telemetry import flight

    # no capture yet in this hub epoch: absence is graceful
    profiling._set_last_summary(None)
    p0 = str(tmp_path / "no_profile.json")
    flight.dump(p0, reason="test")
    ok, payload = telemetry.validate_flight(p0)
    assert ok and "profile" not in payload

    model, _ = _profiled_fit(tmp_path)
    p1 = str(tmp_path / "with_profile.json")
    flight.dump(p1, reason="test")
    ok, payload = telemetry.validate_flight(p1)
    assert ok, payload
    prof = payload["profile"]
    assert prof["steps"] == 4 and prof["coverage_pct"] > 0
    assert prof["top"], prof


# -- CLI + diff gate -----------------------------------------------------------

def _cli(argv):
    from mxnet_tpu.telemetry.__main__ import main

    return main(argv)


def test_profile_cli_renders_hotspots(tmp_path, capsys):
    _, jsonl = _profiled_fit(tmp_path)
    rc = _cli(["profile", jsonl])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device profile:" in out and "coverage" in out
    assert "dot_general" in out
    assert "measured roofline" in out and "MFU: measured" in out
    # flight show renders the embedded section too
    from mxnet_tpu.telemetry import flight

    dump = str(tmp_path / "f.json")
    flight.dump(dump, reason="test")
    rc = _cli(["flight", "show", dump])
    out = capsys.readouterr().out
    assert rc == 0 and "last device-profile capture:" in out


def test_profile_cli_without_summary(tmp_path, capsys):
    path = str(tmp_path / "empty.jsonl")
    telemetry.write_jsonl(path, [{"kind": "span", "ts": 1.0, "name": "step",
                                  "epoch": 0, "step": 0, "dur_ms": 1.0,
                                  "phases": [], "trace_id": None,
                                  "span_id": None, "rank": 0}])
    assert _cli(["profile", path]) == 1
    assert "no profile summary" in capsys.readouterr().out


def _summary_event(op_us):
    top = [{"layer": "fc1", "op": op, "us": us, "count": 4, "pct": 50.0,
            "program": "jit_step", "ms_per_step": us / 1e3 / 4}
           for op, us in op_us.items()]
    return {"kind": "profile", "phase": "summary", "steps": 4,
            "device_ms": sum(op_us.values()) / 1e3, "coverage_pct": 90.0,
            "window_seconds": 0.1, "unattributed_ms": 0.0,
            "layers": {"fc1": 1.0}, "top": top, "roofline": [], "mfu": {},
            "ts": 1.0}


def _span_events(n=8, dur=2.0):
    return [{"kind": "span", "ts": float(i), "name": "step", "epoch": 0,
             "step": i, "dur_ms": dur, "phases": [], "trace_id": None,
             "span_id": None, "rank": 0} for i in range(n)]


def test_diff_gates_hotspot_regression(tmp_path, capsys):
    """ISSUE 15: the last capture's per-op rows join the telemetry diff
    CI gate — a hotspot that regresses beyond the threshold exits 3."""
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    telemetry.write_jsonl(
        a, _span_events() + [_summary_event({"dot_general": 1000.0})])
    telemetry.write_jsonl(
        b, _span_events() + [_summary_event({"dot_general": 2000.0})])
    rc = _cli(["diff", a, b, "--threshold", "25"])
    out = capsys.readouterr().out
    assert rc == 3, out
    assert "op_ms[fc1/dot_general]" in out and "REGRESSION" in out
    # within threshold: clean exit
    telemetry.write_jsonl(
        b, _span_events() + [_summary_event({"dot_general": 1100.0})])
    assert _cli(["diff", a, b, "--threshold", "25"]) == 0
    capsys.readouterr()


def test_read_events_backfills_profile_defaults(tmp_path):
    """Old/hand-rolled profile rows gain the additive fields (schema
    satellite): phase/steps/device_ms/coverage_pct/top."""
    path = str(tmp_path / "old.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 2, "kind": "profile", "ts": 1.0,
                            "rank": 0, "world_size": 1}) + "\n")
    rows = telemetry.read_events(path)
    assert rows[0]["phase"] == "summary"
    assert rows[0]["steps"] == 0
    assert rows[0]["device_ms"] == 0.0
    assert rows[0]["coverage_pct"] is None
    assert rows[0]["top"] == []
