"""Typed parameter system tests (dmlc::Parameter parity:
include/mxnet/operator.h:456-459 declares op params through reflection;
c_api.cc:378-391 exports generated docs; dmlc::ParamError names the field).
"""

import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.params import REQUIRED, Range, TupleParam, apply_params


def test_range_validator():
    r = Range(int, lo=1, hi=8)
    assert r("4") == 4
    with pytest.raises(MXNetError):
        r(0)
    with pytest.raises(MXNetError):
        r(9)
    assert "int" in r.__name__ and ">= 1" in r.__name__


def test_apply_params_errors_name_owner_and_field():
    spec = {"n": (Range(int, lo=1), REQUIRED, "count")}
    with pytest.raises(MXNetError, match="MyOp.*'n'"):
        apply_params("MyOp", spec, {"n": 0})
    with pytest.raises(MXNetError, match="MyOp.*'bogus'"):
        apply_params("MyOp", spec, {"bogus": 1})
    with pytest.raises(MXNetError, match="MyOp.*'n' is required"):
        apply_params("MyOp", spec, {})


def test_op_params_range_checked():
    with pytest.raises(MXNetError, match="num_filter"):
        sym.Convolution(data=sym.Variable("d"), kernel=(3, 3), num_filter=0)
    with pytest.raises(MXNetError, match="num_hidden"):
        sym.FullyConnected(data=sym.Variable("d"), num_hidden=-1)
    with pytest.raises(MXNetError, match="'p'"):
        sym.Dropout(data=sym.Variable("d"), p=1.5)


def test_op_docstrings_generated():
    doc = mx.sym.Convolution.__doc__
    assert "Parameters" in doc
    assert "num_filter : int (>= 1), required" in doc
    assert "kernel : tuple of int, required" in doc
    doc = mx.sym.BatchNorm.__doc__
    assert "momentum : float (>= 0.0, <= 1.0), default=0.9" in doc


def test_iterator_params_validated(tmp_path):
    with pytest.raises(MXNetError, match="ImageRecordIter.*'batch_size'"):
        mio.ImageRecordIter(path_imgrec="x.rec", data_shape=(3, 8, 8),
                            batch_size=0)
    with pytest.raises(MXNetError, match="ImageRecordIter.*'bogus'"):
        mio.ImageRecordIter(path_imgrec="x.rec", data_shape=(3, 8, 8),
                            batch_size=2, bogus=1)
    with pytest.raises(MXNetError, match="'path_imgrec' is required"):
        mio.ImageRecordIter(data_shape=(3, 8, 8), batch_size=2)
    with pytest.raises(MXNetError, match="MNISTIter.*'num_parts'"):
        mio.MNISTIter(image="a", label="b", num_parts=0)


def test_iterator_docstrings_generated():
    doc = mio.ImageRecordIter.__doc__
    assert "Parameters" in doc
    assert "batch_size : int (>= 1), required" in doc
    assert "output_dtype : one of ('float32', 'uint8')" in doc
    assert "Parameters" in mio.MNISTIter.__doc__
    assert "Parameters" in mio.CSVIter.__doc__


def test_explicit_none_means_default():
    """Passing None for an optional param behaves like omitting it (many
    reference call sites pass None for old signature defaults)."""
    spec = {"mean_img": (str, None, "path"),
            "threads": (int, 4, "n"),
            "req": (int, REQUIRED, "r")}
    out = apply_params("It", spec, {"mean_img": None, "threads": None,
                                    "req": 2})
    assert out["mean_img"] is None  # NOT the string 'None'
    assert out["threads"] == 4
    assert out["req"] == 2


def test_dropout_p_upper_bound_exclusive():
    """p == 1 would make keep == 0 (divide by zero at train time)."""
    with pytest.raises(MXNetError, match="'p'.*< 1.0"):
        sym.Dropout(data=sym.Variable("d"), p=1.0)
    sym.Dropout(data=sym.Variable("d"), p=0.99)  # ok


def test_reference_only_flags_tolerated_with_warning():
    """Reference augmenter flags we don't implement warn instead of raise
    (scripts ported from the reference keep running)."""
    with pytest.warns(UserWarning, match="reference-only"):
        with pytest.raises((MXNetError, FileNotFoundError)):
            mio.ImageRecordIter(path_imgrec="/nonexistent.rec",
                                data_shape=(3, 8, 8), batch_size=2,
                                max_random_contrast=0.5, verbose=True)


def test_string_coercion_like_dmlc():
    """dmlc parses stringly-typed configs; '(2,2)' / 'true' / '0.5' all work."""
    op = sym.Convolution(data=sym.Variable("d"), kernel="(3,3)",
                         num_filter="8", no_bias="true")
    g = op.get_internals()
    assert g is not None
    it_params = apply_params(
        "ImageRecordIter", mio.ImageRecordIter.params,
        {"path_imgrec": "x", "data_shape": "(3,8,8)", "batch_size": "4",
         "rand_mirror": "TRUE"})
    assert it_params["data_shape"] == (3, 8, 8)
    assert it_params["batch_size"] == 4
    assert it_params["rand_mirror"] is True
