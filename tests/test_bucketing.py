"""Bucketing tests: variable-seq-len LM training through per-bucket compiled
steps over shared weights (reference capability: example/rnn/lstm.py binding
one executor per seq_len — SURVEY.md §5)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import lstm_unroll

VOCAB = 8


def _sentences(n=64, rng_seed=0):
    """Learnable corpus: tokens 1..7 cycle (t -> t%7+1); 0 is reserved as the
    pad/invalid label so padded positions (data 0 -> label 0) stay consistent
    with the cycle rule."""
    rng = np.random.RandomState(rng_seed)
    out = []
    for _ in range(n):
        length = int(rng.choice([3, 4, 6, 7]))
        start = int(rng.randint(1, VOCAB))
        sent = [start]
        for _ in range(length - 1):
            sent.append(sent[-1] % 7 + 1)
        out.append(sent)
    return out


def _sym_gen(seq_len):
    return lstm_unroll(num_layers=1, seq_len=seq_len, input_size=VOCAB,
                       num_hidden=16, num_embed=8, num_label=VOCAB)


def test_bucket_sentence_iter_shapes_and_padding():
    it = mx.BucketSentenceIter(_sentences(), buckets=[4, 8], batch_size=8,
                               shuffle=False)
    seen_keys = set()
    n_batches = 0
    for batch in it:
        seen_keys.add(batch.bucket_key)
        assert len(batch.data) == batch.bucket_key
        assert len(batch.label) == batch.bucket_key
        assert batch.data[0].shape == (8,)
        assert batch.data_names[0] == "t0_data"
        # label is the next-token shift of data
        np.testing.assert_array_equal(
            batch.label[0].asnumpy(), batch.data[1].asnumpy())
        n_batches += 1
    assert seen_keys == {4, 8}
    assert n_batches >= 2
    # provide_data describes the default (largest) bucket
    assert len(it.provide_data) == 8
    # epochs are re-iterable
    it.reset()
    assert sum(1 for _ in it) == n_batches


def test_bucket_iter_drops_too_long():
    it = mx.BucketSentenceIter([[1, 2], [1] * 50], buckets=[4], batch_size=1)
    assert it.discarded == 1


def test_bucketing_feedforward_trains_across_buckets():
    init_states = [("l0_init_c", (8, 16)), ("l0_init_h", (8, 16))]
    it = mx.BucketSentenceIter(_sentences(), buckets=[4, 8], batch_size=8,
                               init_states=init_states, shuffle=True)
    model = mx.BucketingFeedForward(
        _sym_gen, default_bucket_key=it.default_bucket_key,
        num_epoch=10, optimizer="adam", learning_rate=0.02,
        initializer=mx.init.Xavier())
    model.fit(it, batch_size=8, eval_metric="accuracy")

    # the shared weights must have learned the +1 cycle: check accuracy on
    # a bucketed eval pass through both compiled bucket programs
    metric = mx.metric.create("accuracy")
    params = {k: v.data for k, v in model.arg_params.items()}
    aux = {k: v.data for k, v in model.aux_params.items()}
    it.reset()
    model._eval(it, metric, params, aux, None, None)
    name, value = metric.get()
    # every position is consistently predictable except the one sentence-end
    # -> pad transition per row, so well-trained accuracy lands > 0.7
    assert value > 0.7, (name, value)
    # one compiled eval step per (bucket key, metric)
    assert {k for k, _ in model._eval_fns} == {4, 8}
