"""Distributed tracing + flight recorder acceptance (ISSUE 6).

Covers: trace/rank identity and its propagation through the kvstore
envelopes (in-process group BSP server and the dist_async parameter
host), server-side handling and replay-dedup hits as child spans of the
worker step that caused them, the always-on flight recorder (ring
semantics, CRC-sealed atomic dumps, crash-path triggers), cross-rank
JSONL merge into one fleet Chrome trace with clock-offset beacons, the
MAD-envelope straggler detector with per-phase blame, the `diff` CI perf
gate, exporter thread-safety under concurrent scrapes, and the end-to-end
chaos acceptance: slow rank + dropped pushes + NaN-step incident +
mid-run-killed worker -> surviving ranks' dumps valid, one merged trace
spanning all ranks, the injected straggler named with the right blame.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.kvstore import create_group
from mxnet_tpu.ndarray import NDArray
from mxnet_tpu.resilience.chaos import chaos_scope
from mxnet_tpu.telemetry import flight
from mxnet_tpu.telemetry.__main__ import main as telemetry_cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_hub():
    telemetry.reset()
    flight.reset()
    telemetry.set_world(0, 1)
    yield
    telemetry.reset()
    flight.reset()
    telemetry.set_world(0, 1)


# -- identity ------------------------------------------------------------------

def test_rank_scope_and_event_stamping():
    e0 = telemetry.emit("tick")
    assert e0["rank"] == 0 and e0["world_size"] == 1
    telemetry.set_world(2, 8)
    assert telemetry.emit("tick")["rank"] == 2
    with telemetry.rank_scope(5, 8):
        assert telemetry.emit("tick")["rank"] == 5
        # metric families carry the scoped identity at export time
        telemetry.counter("scoped_total")
        assert ('mxtpu_scoped_total{rank="5",world_size="8"} 1'
                in telemetry.prom_dump())
    assert telemetry.emit("tick")["rank"] == 2  # scope restored


def test_span_identity_is_deterministic_and_joinable():
    tid = telemetry.trace_id()
    assert telemetry.trace_id() == tid  # stable within the run
    with telemetry.rank_scope(3, 4):
        tl = telemetry.StepTimeline()
        with tl.begin_step(7, 11) as span:
            pass
    assert span.rank == 3 and span.trace_id == tid
    # any rank can re-derive the id — the merge join key
    assert span.span_id == telemetry.mint_span_id(3, 7, 11)
    d = span.to_dict()
    for key in ("trace_id", "span_id", "rank", "wall_ts"):
        assert key in d, key


def test_trace_id_adoption_rules():
    mine = telemetry.trace_id()
    # adopt=True never re-brands a run that already has an id
    assert telemetry.set_trace_id("other", adopt=True) == mine
    # an explicit set (worker adopting rank 0's id) wins
    assert telemetry.set_trace_id("fleet-id") == "fleet-id"
    assert telemetry.trace_id() == "fleet-id"


# -- flight recorder -----------------------------------------------------------

def test_flight_rings_route_and_bound():
    rec = flight.recorder()
    for i in range(200):
        telemetry.emit("span", name="step", epoch=0, step=i, dur_ms=1.0,
                       phases=[])
    telemetry.emit("retry", op="push", attempt=0)
    telemetry.emit("chaos", site="kvstore.push")
    steps, events, incidents = rec.snapshot()
    assert len(steps) == 64  # ring-bounded to the last K
    assert steps[-1]["step"] == 199
    kinds = {e["kind"] for e in incidents}
    assert "retry" in kinds and "chaos" in kinds
    # a noisy event stream cannot evict incidents: spam and re-check
    for i in range(2000):
        telemetry.emit("noise", i=i)
    _, _, incidents = rec.snapshot()
    assert {e["kind"] for e in incidents} >= {"retry", "chaos"}


def test_flight_dump_crc_and_tamper_detection(tmp_path):
    flight.note_step(0, 0)
    telemetry.emit("retry", op="push", attempt=1)
    path = str(tmp_path / "f.json")
    out = flight.dump(path, reason="unit")
    assert out == path and not os.listdir(str(tmp_path)).count("tmp")
    ok, payload = telemetry.validate_flight(path)
    assert ok, payload
    assert payload["reason"] == "unit"
    assert payload["trace_id"] == telemetry.trace_id()
    assert any(s.get("kind") == "step_lite" for s in payload["steps"])
    assert any(e.get("kind") == "retry" for e in payload["incidents"])
    # a flight_dump event was emitted (observable in traces)
    assert telemetry.hub().events("flight_dump")
    # tamper: flip a byte inside the payload -> CRC fails closed
    blob = json.load(open(path))
    blob["payload"]["reason"] = "doctored"
    json.dump(blob, open(path, "w"))
    ok, err = telemetry.validate_flight(path)
    assert not ok and "CRC" in err


def test_dump_flight_from_model_timeline(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 4, (64,)).astype(np.float32)
    data = mx.sym.Variable("data")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        data, name="fc", num_hidden=4), name="softmax")
    model = mx.FeedForward(out, ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    model.fit(X, y, batch_size=32, telemetry=True)
    path = model.telemetry.dump_flight(str(tmp_path / "fit.json"))
    ok, payload = telemetry.validate_flight(path)
    assert ok
    full = [s for s in payload["steps"] if s.get("kind") == "span"]
    assert len(full) == 2  # one per step, with phase breakdowns
    assert all(s["phases"] for s in full)
    # without a path and without MXNET_TPU_FLIGHT_DIR: explicit error
    with pytest.raises(ValueError):
        model.telemetry.dump_flight()


def test_flight_auto_dump_env_gated(tmp_path, monkeypatch):
    assert flight.auto_dump("unit") is None  # no dir -> no-op
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    flight.note_step(0, 1)
    path = flight.auto_dump("unit")
    assert path is not None and os.path.exists(path)
    assert "unit" in os.path.basename(path)
    ok, _ = telemetry.validate_flight(path)
    assert ok


def test_fit_without_timeline_still_records_flight_steps():
    rng = np.random.RandomState(0)
    X = rng.randn(96, 8).astype(np.float32)
    y = rng.randint(0, 4, (96,)).astype(np.float32)
    data = mx.sym.Variable("data")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        data, name="fc", num_hidden=4), name="softmax")
    model = mx.FeedForward(out, ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    model.fit(X, y, batch_size=32)  # telemetry OFF
    steps, _, _ = flight.recorder().snapshot()
    lite = [s for s in steps if s.get("kind") == "step_lite"]
    assert len(lite) == 3
    assert [s["step"] for s in lite] == [0, 1, 2]


# -- exporter concurrency (satellite) ------------------------------------------

def test_concurrent_emit_while_exporters_scrape():
    """Hammer emit()/observe()/counter() from trainer threads while
    prom_dump(), the /metrics HTTP endpoint, and snapshot() poll: no torn
    reads, no exceptions, no lock-order inversions (deadlock == timeout
    here), and the final counts add up."""
    import urllib.request

    port = telemetry.serve_http(0)
    errors = []
    stop = threading.Event()
    N, THREADS = 2000, 4

    def writer(tid):
        try:
            with telemetry.rank_scope(tid, THREADS):
                for i in range(N):
                    telemetry.emit("hammer", tid=tid, i=i)
                    telemetry.observe("hammer_seconds", i * 1e-6,
                                      tid=tid)
                    telemetry.counter("hammer_total")
        except Exception as e:  # noqa: BLE001 - the assertion surface
            errors.append(("writer", e))

    def reader(kind):
        try:
            while not stop.is_set():
                if kind == "prom":
                    out = telemetry.prom_dump()
                    assert "mxtpu_" in out
                elif kind == "http":
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=10).read()
                else:
                    telemetry.hub().snapshot()
        except Exception as e:  # noqa: BLE001
            errors.append((kind, e))

    writers = [threading.Thread(target=writer, args=(t,))
               for t in range(THREADS)]
    readers = [threading.Thread(target=reader, args=(k,))
               for k in ("prom", "http", "snapshot")]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join(timeout=60)
    stop.set()
    for t in readers:
        t.join(timeout=60)
    telemetry.stop_http()
    assert not errors, errors
    assert not any(t.is_alive() for t in writers + readers), "deadlock"
    snap = telemetry.hub().snapshot()
    assert snap["counters"]["hammer_total"] == N * THREADS
    hist = sum(v["count"] for k, v in snap["histograms"].items()
               if k.startswith("hammer_seconds"))
    assert hist == N * THREADS


# -- kvstore propagation -------------------------------------------------------

def _worker_loop(kv, rank, world, steps, jsonl_dir, slow_rank=None,
                 die_after=None, chaos=False):
    """One emulated worker: per-rank JSONL stream, per-step spans with
    device/kvstore phases, BSP push/pull each step. ``die_after`` models
    a SIGKILL'd worker: the thread keeps serving the BSP protocol (a real
    kill would stall the collective — out of scope here) but its
    telemetry stream and flight dump stop cold, mid-span."""
    with telemetry.rank_scope(rank, world):
        sink = telemetry.hub().add_sink(telemetry.JsonlWriter(
            os.path.join(jsonl_dir, f"rank{rank}.jsonl"), only_rank=rank))
        tl = telemetry.StepTimeline()
        grad = NDArray(np.ones(8, np.float32))
        out = NDArray(np.zeros(8, np.float32))
        try:
            # init barriers across the group: every worker calls it from
            # its own thread (rank 0 seeds the server)
            kv.init("w", NDArray(np.zeros(8, np.float32)))
            for step in range(steps):
                dead = die_after is not None and step >= die_after
                span = None if dead else tl.begin_step(0, step)
                if span is not None:
                    span.mark("device")
                # the skew must survive full-suite CPU contention (fast
                # ranks' sleeps stretch under load, compressing it)
                time.sleep(0.05 if rank == slow_rank else 0.004)
                if span is not None:
                    span.mark("kvstore")
                kv.push("w", grad)
                kv.pull("w", out)
                if span is not None:
                    span.mark("host")
                    if step == 1 and rank == 0:
                        # the NaN-step stand-in: a guard skip incident
                        span.event("step_retry", reason="nonfinite")
                    span.end()
            if die_after is None:
                flight.dump(os.path.join(jsonl_dir,
                                         f"flight_r{rank}.json"),
                            reason="test", only_rank=rank)
        finally:
            telemetry.clear_current_span()
            telemetry.hub().remove_sink(sink)
            sink.close()


def test_group_push_parents_server_spans_and_dedups_under_chaos(tmp_path):
    """Worker pushes carry trace context: the BSP server's handling lands
    as server_span events parented under the exact worker step span, and
    a chaos-dropped ack (resend of the same (worker, seq)) surfaces as a
    server_dedup incident instead of a double-count."""
    world = 2
    workers = create_group(world)
    with chaos_scope(seed=5, rules={"group.push.ack": {1}}):
        ts = [threading.Thread(target=_worker_loop,
                               args=(w, r, world, 3, str(tmp_path)),
                               kwargs={"chaos": True})
              for r, w in enumerate(workers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    assert not any(t.is_alive() for t in ts)
    evs = telemetry.hub().events()
    spans = {e["span_id"]: e for e in evs if e["kind"] == "span"}
    server = [e for e in evs if e["kind"] == "server_span"]
    assert server, "no server spans recorded"
    for e in server:
        assert e["parent_span"] in spans, e
        # parented under a step of the ORIGIN rank
        assert spans[e["parent_span"]]["rank"] == e["origin_rank"]
    dedups = [e for e in evs if e["kind"] == "server_dedup"]
    assert dedups and workers[0]._server.duplicate_count == len(dedups)
    assert all(d["parent_span"] in spans for d in dedups)
    # retry incidents carry the span they interrupted
    retries = [e for e in evs if e["kind"] == "retry"]
    assert retries and all(e.get("span_id") in spans for e in retries)


def test_bsp_push_span_excludes_collective_wait(tmp_path):
    """A fast rank's BSP push blocks in the server's cv.wait_for until
    the slow rank arrives — that is straggler skew, not server work, and
    must land in barrier_wait_ms, NOT in the server_span's dur_ms (or the
    fleet trace would blame the parameter server for the slow rank)."""
    world = 2
    workers = create_group(world)
    ts = [threading.Thread(target=_worker_loop,
                           args=(w, r, world, 3, str(tmp_path)),
                           kwargs={"slow_rank": 1})
          for r, w in enumerate(workers)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in ts)
    fast_pushes = [e for e in telemetry.hub().events()
                   if e["kind"] == "server_span" and e["op"] == "push"
                   and e["origin_rank"] == 0]
    assert fast_pushes
    waited = [e for e in fast_pushes if e["barrier_wait_ms"] > 5.0]
    assert waited, "fast rank's pushes never waited on the slow rank"
    for e in waited:
        # handling (decode + accumulate of 8 floats) is far below wait
        assert e["dur_ms"] < e["barrier_wait_ms"], e


# -- the end-to-end chaos acceptance -------------------------------------------

def test_chaos_fleet_flight_merge_straggler(tmp_path):
    """ISSUE 6 acceptance: under injected faults — slow rank 2, dropped
    pushes, a NaN-step incident on rank 0, rank 3's recorder killed
    mid-run — the surviving ranks' flight dumps are CRC-clean with the
    last K steps and incidents attached; `merge` yields ONE Chrome trace
    spanning all ranks with server spans parented under the right worker
    steps; the straggler detector names rank 2 and blames the device
    phase."""
    world, steps, slow = 4, 8, 2
    workers = create_group(world)
    # probability-based drops: several pushes fail/lose acks across the
    # fleet (seeded; retries + server dedup keep BSP correctness)
    with chaos_scope(seed=11, rules={"group.push.send": 0.12,
                                     "group.push.ack": 0.08}):
        ts = []
        for r, w in enumerate(workers):
            kwargs = {"slow_rank": slow}
            if r == 3:
                kwargs["die_after"] = 5  # "SIGKILL" at step 5
            ts.append(threading.Thread(
                target=_worker_loop,
                args=(w, r, world, steps, str(tmp_path)), kwargs=kwargs))
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
    assert not any(t.is_alive() for t in ts)

    # -- surviving ranks' flight dumps are valid ------------------------------
    for r in (0, 1, 2):
        path = str(tmp_path / f"flight_r{r}.json")
        ok, payload = telemetry.validate_flight(path)
        assert ok, (r, payload)
        assert payload["rank"] == r
        got = [s for s in payload["steps"] if s.get("kind") == "span"]
        assert len(got) == steps, (r, len(got))  # last K covers the run
        assert [s["step"] for s in got] == list(range(steps))
    assert not os.path.exists(str(tmp_path / "flight_r3.json"))  # killed
    # incidents attached: each surviving dump carries what ITS rank saw;
    # across the fleet the chaos drops, the retries they forced, and the
    # NaN-step guard event are all on record
    incidents = []
    for r in (0, 1, 2):
        _, p = telemetry.validate_flight(str(tmp_path / f"flight_r{r}.json"))
        incidents.extend(p["incidents"])
    kinds = {e["kind"] for e in incidents}
    assert "chaos" in kinds and "retry" in kinds, kinds
    assert any(e["kind"] == "step_event" and e.get("name") == "step_retry"
               and e.get("rank") == 0 for e in incidents)

    # -- one merged fleet trace -----------------------------------------------
    paths = [str(tmp_path / f"rank{r}.jsonl") for r in range(world)]
    out = str(tmp_path / "fleet.json")
    trace, report = telemetry.merge_traces(paths, out=out)
    assert sorted(report["ranks"]) == [0, 1, 2, 3]
    assert len(report["trace_ids"]) == 1  # one run, one identity
    events = json.load(open(out))["traceEvents"]
    pids = {e["pid"] for e in events if e.get("ph") == "X"}
    assert pids == {0, 1, 2, 3}  # the killed rank's partial stream too
    # server spans parent under the correct worker step spans
    span_ids = {e["args"]["span_id"]: e for e in events
                if e.get("ph") == "X" and "span_id" in e.get("args", {})
                and e["args"]["span_id"]}
    server = [e for e in events if e.get("cat") == "kvstore_server"]
    assert server and report["orphan_server_spans"] == 0
    # server-span emission is gated on an open worker step, so the killed
    # rank's zombie pushes (no span) produce nothing and every emitted
    # server span parents under the right step of the right rank
    parented = [e for e in server if e["args"]["parent"] is not None]
    assert parented == server
    for e in parented:
        parent = span_ids[e["args"]["parent"]]
        assert parent["pid"] == e["args"]["origin_rank"] == e["pid"]
        # ...and under the matching step, not just the matching rank
        assert f"-s{parent['args']['step']}" in e["args"]["parent"]

    # -- the straggler detector names the injected slow rank ------------------
    srep = telemetry.detect_stragglers(
        telemetry.load_rank_streams(paths))
    flagged = {s["rank"]: s for s in srep["stragglers"]}
    assert slow in flagged, srep
    assert flagged[slow]["blame"] == "device", srep
    assert srep["skew_seconds"] > 0.01  # ~46ms/step injected skew
    # the skew gauge was published back through the hub
    assert ('mxtpu_skew_seconds' in telemetry.prom_dump())

    # the CLI front door agrees
    rc = telemetry_cli(["merge", *paths, "-o",
                        str(tmp_path / "fleet2.json")])
    assert rc == 0


# -- diff CI gate --------------------------------------------------------------

def _write_run(path, step_ms, mfu):
    tl_events = []
    for i in range(20):
        tl_events.append({"v": 2, "kind": "span", "ts": float(i),
                          "rank": 0, "world_size": 1, "name": "step",
                          "epoch": 0, "step": i, "dur_ms": step_ms,
                          "phases": [], "trace_id": "t", "span_id": f"s{i}",
                          "wall_ts": float(i)})
    tl_events.append({"v": 2, "kind": "epoch_summary", "ts": 21.0,
                      "rank": 0, "world_size": 1, "epoch": 0, "steps": 20,
                      "seconds": 1.0, "mfu_pct": mfu, "goodput_pct": 90.0})
    with open(path, "w") as f:
        for e in tl_events:
            f.write(json.dumps(e) + "\n")


def test_diff_cli_perf_gate(tmp_path, capsys):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_run(a, step_ms=10.0, mfu=40.0)
    _write_run(b, step_ms=10.4, mfu=39.5)        # within 10%
    assert telemetry_cli(["diff", a, b]) == 0
    _write_run(b, step_ms=13.0, mfu=40.0)        # 30% step-time regression
    assert telemetry_cli(["diff", a, b]) == 3
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    # tighter threshold catches the small slip too; MFU drops regress
    _write_run(b, step_ms=10.4, mfu=30.0)
    assert telemetry_cli(["diff", a, b, "--threshold", "3"]) == 3
    # improvement is never a regression
    _write_run(b, step_ms=8.0, mfu=50.0)
    assert telemetry_cli(["diff", a, b]) == 0


def test_flight_cli_show_and_validate(tmp_path, capsys):
    tl = telemetry.StepTimeline()
    with tl.begin_step(0, 0) as span:
        span.mark("device")
        span.event("step_retry")
    path = str(tmp_path / "f.json")
    flight.dump(path, reason="unit")
    assert telemetry_cli(["flight", "validate", path]) == 0
    assert telemetry_cli(["flight", "show", path]) == 0
    out = capsys.readouterr().out
    assert "reason=unit" in out and "step_retry" in out
    # corrupted dump: nonzero exit
    blob = json.load(open(path))
    blob["crc32"] ^= 1
    json.dump(blob, open(path, "w"))
    assert telemetry_cli(["flight", "validate", path]) == 3


# -- tracing stays compile-clean -----------------------------------------------

def test_zero_recompile_armed_epoch_with_tracing(tmp_path, monkeypatch):
    """ISSUE 6 acceptance: the zero-recompile armed epoch stays green with
    tracing + flight recording enabled (identity stamping and ring writes
    are host-side; nothing leaks into jit cache keys)."""
    from mxnet_tpu.utils import compile as cm

    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    rng = np.random.RandomState(0)
    X = rng.randn(128, 8).astype(np.float32)
    y = rng.randint(0, 4, (128,)).astype(np.float32)
    data = mx.sym.Variable("data")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        data, name="fc", num_hidden=4), name="softmax")
    model = mx.FeedForward(out, ctx=mx.cpu(), num_epoch=3,
                           learning_rate=0.1)
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    try:
        model.fit(X, y, batch_size=32, telemetry=True,
                  epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    assert tracker.recompiles == []
    assert len(model.telemetry.steps("step")) == 12
