"""Concurrency tier (ISSUE 11): the runtime lock-order watchdog, the
thread-name contract, and hammer tests for the three scariest shared
structures — hub reset() racing emit(), memory-ledger GC callbacks racing
track_arrays() adds, and _GroupServer membership churn racing an open
accumulate round — all run under the watchdog with zero cycles asserted.

Acceptance (ISSUE 11): a seeded deliberate lock-order inversion is
detected both statically (MX702) and at runtime (a lockwatch incident in
a CRC-valid flight dump)."""

import gc
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.analysis import concurrency, lockwatch
from mxnet_tpu.ndarray import NDArray


@pytest.fixture(autouse=True)
def _restore_world_identity():
    """ElasticCoordinator.commit relabels the process (rank, world) —
    the heartbeat-monitor test commits resizes, which must not leak this
    module's world into later tests' metric labels."""
    prev = (telemetry.current_rank(), telemetry.world_size())
    yield
    telemetry.set_world(*prev)


@pytest.fixture
def watchdog():
    """A fresh enabled watcher for the test; disabled afterwards."""
    was = lockwatch.enabled()
    lockwatch.enable()
    lockwatch.reset()
    yield lockwatch.watcher()
    if not was:
        lockwatch.disable()


# -- the watchdog itself -------------------------------------------------------

def test_disabled_watchdog_is_passthrough():
    lockwatch.disable()
    lk = lockwatch.named_lock("t.passthrough")
    with lk:
        pass
    assert lk.acquire(blocking=False)
    lk.release()
    assert lockwatch.report() == {"enabled": False}


def test_seeded_inversion_detected_at_runtime(watchdog):
    a = lockwatch.named_lock("t.A")
    b = lockwatch.named_lock("t.B")
    with a:
        with b:
            pass
    with b:
        with a:      # closes the cycle: A->B and B->A both observed
            pass
    rep = lockwatch.report()
    assert rep["enabled"]
    assert len(rep["cycles"]) == 1
    assert sorted(rep["cycles"][0]["cycle"]) == ["t.A", "t.B"]
    # the same cycle re-observed is reported once
    with b:
        with a:
            pass
    assert len(lockwatch.report()["cycles"]) == 1


def test_inversion_incident_lands_in_crc_valid_flight_dump(
        tmp_path, watchdog):
    """ISSUE 11 acceptance: the deadlock risk shows up in the same
    post-mortem tooling as everything else — a lockwatch incident inside
    a CRC-validated flight dump, plus the hub gauges."""
    telemetry.reset()
    telemetry.flight.reset()
    a = lockwatch.named_lock("t.flight.A")
    b = lockwatch.named_lock("t.flight.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    path = str(tmp_path / "flight.json")
    telemetry.flight.dump(path, reason="lockwatch-test")
    ok, payload = telemetry.validate_flight(path)
    assert ok, payload
    incidents = [e for e in payload["incidents"]
                 if e.get("kind") == "lockwatch"]
    assert incidents, payload["incidents"]
    assert incidents[0]["what"] == "cycle"
    assert "t.flight.A" in incidents[0]["cycle"]
    gauges = telemetry.hub().snapshot()["gauges"]
    assert gauges.get("lockwatch_cycles_total", 0) >= 1
    assert "lockwatch_max_hold_ms" in gauges


def test_seeded_inversion_detected_statically():
    """The SAME inversion shape, caught by MX702 before any thread runs."""
    src = (
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def f():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def g():\n"
        "    with B:\n"
        "        with A:\n"
        "            pass\n"
    )
    findings = concurrency.lint_source(src, "fx.py")
    assert [f.rule.id for f in findings] == ["MX702"]
    assert "fx.A" in findings[0].extra["cycle"]


def test_stall_detection(watchdog):
    lockwatch.reset(stall_ms=20)
    lk = lockwatch.named_lock("t.stall")
    with lk:
        time.sleep(0.05)
    rep = lockwatch.report()
    assert rep["stalls"] and rep["stalls"][0]["lock"] == "t.stall"
    assert rep["max_hold_ms"] >= 20


def test_named_condition_rejects_reentrant_lock():
    """Condition.wait must fully release its lock; the wrapper does not
    forward RLock's multi-level _release_save, so a cv over a
    named_rlock would sleep still holding the lock — rejected loudly at
    construction instead of wedging at the first wait."""
    with pytest.raises(TypeError, match="reentrant"):
        lockwatch.named_condition("t.bad_cv", lockwatch.named_rlock("t.rl"))
    # a plain watched lock stays Condition-compatible, armed or not
    lockwatch.disable()
    cv = lockwatch.named_condition("t.ok_cv")
    with cv:
        assert not cv.wait(timeout=0.01)  # no deadlock, normal timeout


def test_rlock_reentrancy_no_self_edge(watchdog):
    rl = lockwatch.named_rlock("t.rlock")
    with rl:
        with rl:       # reentrant re-acquire: no A->A edge, no cycle
            pass
    rep = lockwatch.report()
    assert rep["cycles"] == []
    assert all(e["from"] != e["to"] for e in rep["edges"])


def test_condition_over_watched_lock(watchdog):
    lk = lockwatch.named_lock("t.cv_lock")
    cv = lockwatch.named_condition("t.cv", lk)
    state = []

    def waiter():
        with cv:
            assert cv.wait_for(lambda: state, timeout=10)

    t = threading.Thread(target=waiter, daemon=True, name="t-waiter")
    t.start()
    time.sleep(0.05)
    with cv:
        state.append(1)
        cv.notify_all()
    t.join(timeout=10)
    assert not t.is_alive()
    assert lockwatch.report()["cycles"] == []


# -- hammer 1: hub reset() racing emit() ---------------------------------------

def test_hub_reset_racing_emit_zero_cycles(watchdog):
    telemetry.reset()
    stop = threading.Event()
    errors = []

    def writer(tid):
        try:
            i = 0
            while not stop.is_set():
                telemetry.emit("hammer", tid=tid, i=i)
                telemetry.counter("hammer_total")
                telemetry.observe("hammer_ms", 0.1, tid=tid)
                i += 1
        except Exception as e:  # noqa: BLE001 - the assertion surface
            errors.append(("writer", e))

    writers = [threading.Thread(target=writer, args=(t,), daemon=True)
               for t in range(4)]
    for t in writers:
        t.start()
    try:
        for _ in range(30):
            telemetry.reset()       # swaps the hub under the writers
            telemetry.hub().snapshot()
            time.sleep(0.002)
    finally:
        stop.set()
        for t in writers:
            t.join(timeout=30)
    assert not errors, errors
    assert lockwatch.report()["cycles"] == []


# -- hammer 2: ledger GC callbacks racing track_arrays() adds ------------------

def test_ledger_gc_callbacks_racing_adds_zero_cycles(watchdog):
    from mxnet_tpu.telemetry import memory as memory_mod

    prev = telemetry.track_arrays(True)
    stop = threading.Event()
    errors = []

    def churner(seed):
        try:
            rng = np.random.RandomState(seed)
            while not stop.is_set():
                arrs = [NDArray(rng.randn(8, 8).astype(np.float32))
                        for _ in range(8)]
                del arrs           # GC callbacks fire under churn
        except Exception as e:  # noqa: BLE001
            errors.append(("churner", e))

    threads = [threading.Thread(target=churner, args=(s,), daemon=True)
               for s in range(4)]
    for t in threads:
        t.start()
    try:
        led = memory_mod.ledger()
        for _ in range(50):
            led.stats()
            led.top_arrays(4)
            gc.collect()           # force collector-driven callbacks too
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        telemetry.track_arrays(prev)
    assert not errors, errors
    gc.collect()
    stats = memory_mod.ledger().stats()
    assert stats["live_bytes"] >= 0 and stats["live_count"] >= 0
    assert lockwatch.report()["cycles"] == []


# -- hammer 3: _GroupServer membership churn vs an open accumulate round -------

def test_group_server_membership_churn_zero_cycles(watchdog):
    """Ranks 0-2 push 16 rounds; rank 3 pushes 6 then dies. The
    deregistration lands while the survivors are blocked inside the open
    round 7 — they must release and finish, the re-registration must be
    idempotent, and the watchdog must see zero lock-order cycles."""
    from mxnet_tpu import kvstore as kv_mod

    workers = kv_mod.create_group(4, op_timeout=60.0)
    server = workers[0]._server
    init = NDArray(np.zeros((4,), np.float32))
    rounds = {0: 16, 1: 16, 2: 16, 3: 6}
    errors = []

    def run(rank):
        try:
            w = workers[rank]
            for _ in range(rounds[rank]):
                w.push("k", NDArray(np.ones((4,), np.float32)))
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    server.init("k", init.asnumpy())   # direct: the group barrier would
    del init                           # wait for all 4 worker threads
    threads = [threading.Thread(target=run, args=(r,), daemon=True,
                                name=f"t-rank{r}") for r in range(4)]
    for t in threads:
        t.start()
    threads[3].join(timeout=60)        # rank 3 finishes its 6 rounds
    time.sleep(0.1)                    # survivors block in round 7
    epoch = server.deregister_worker(3)
    assert epoch >= 1
    for t in threads[:3]:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)
    assert not errors, errors
    # rejoin handshake between rounds: register is idempotent
    assert server.register_worker(3) == server.register_worker(3)
    assert server.num_workers == 4
    assert lockwatch.report()["cycles"] == []


# -- thread-name contract ------------------------------------------------------

def _names():
    return {t.name for t in threading.enumerate()}


def test_kv_async_and_metrics_http_thread_names():
    from mxnet_tpu.kvstore_async import AsyncKVStore

    kv = AsyncKVStore()                # rank 0 spawns the server in-proc
    try:
        kv.init("w", NDArray(np.zeros((2,), np.float32)))
        names = _names()
        assert "mx-kv-accept" in names, names
        assert any(n.startswith("mx-kv-serve-") for n in names), names
    finally:
        del kv
    port = telemetry.serve_http(0)
    try:
        assert port > 0
        assert "mx-metrics-http" in _names()
    finally:
        telemetry.stop_http()


def test_prefetch_and_heartbeat_thread_names():
    from mxnet_tpu.model import _AsyncDeviceFeed
    from mxnet_tpu.resilience import ElasticCoordinator

    feed = _AsyncDeviceFeed(iter([{"x": 1}, {"x": 2}]),
                            extract=lambda b: b, place=lambda b: b)
    try:
        assert feed._thread.name == "mx-prefetch"
        assert feed._thread.daemon
    finally:
        feed.close()

    co = ElasticCoordinator(4, heartbeat_timeout=10.0)
    t = co.start_heartbeat_monitor(interval=0.05)
    try:
        assert t is not None and t.name == "mx-heartbeat" and t.daemon
        assert co.start_heartbeat_monitor() is t  # idempotent
    finally:
        co.stop_heartbeat_monitor()
    assert not t.is_alive()


def test_precompile_thread_names():
    """The parallel AOT warmup pool carries the mx-precompile role name
    (sampled concurrently: pool threads live only inside precompile)."""
    from mxnet_tpu.models import lstm_unroll

    sents = [[1, 2, 3], [2, 3, 4, 5, 6, 7], [3, 4], [1] * 7] * 4

    def sym_gen(seq_len):
        return lstm_unroll(num_layers=1, seq_len=seq_len, input_size=8,
                           num_hidden=8, num_embed=4, num_label=8)

    init_states = [("l0_init_c", (4, 8)), ("l0_init_h", (4, 8))]
    it = mx.BucketSentenceIter(sents, buckets=[4, 8], batch_size=4,
                               init_states=init_states, shuffle=False)
    model = mx.BucketingFeedForward(sym_gen, default_bucket_key=8,
                                    num_epoch=1, learning_rate=0.1,
                                    initializer=mx.init.Xavier())
    seen = set()
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            seen.update(_names())
            time.sleep(0.001)

    s = threading.Thread(target=sampler, daemon=True)
    s.start()
    try:
        out = model.precompile(data=it)
    finally:
        stop.set()
        s.join(timeout=10)
    assert out["programs"] == 2
    assert any(n.startswith("mx-precompile") for n in seen), sorted(seen)


# -- heartbeat monitor behavior ------------------------------------------------

def test_heartbeat_monitor_detects_silence():
    from mxnet_tpu.resilience import ElasticCoordinator

    co = ElasticCoordinator(4, heartbeat_timeout=0.1)
    for r in range(4):
        co.heartbeat(r)
    co.start_heartbeat_monitor(interval=0.02)
    try:
        deadline = time.monotonic() + 5.0
        # ranks 0-1 keep beating; 2-3 go silent and must be killed by
        # the monitor thread without any fit-loop poll
        while co.world_size > 2 and time.monotonic() < deadline:
            co.heartbeat(0)
            co.heartbeat(1)
            ev = co.poll()
            if ev is not None:
                co.commit(ev)
            time.sleep(0.02)
    finally:
        co.stop_heartbeat_monitor()
    assert co.world_size == 2
    assert sorted(co.alive) == [0, 1]
