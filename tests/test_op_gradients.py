"""Numeric gradient checks across the op library (reference test tier:
test_operator.py's forward-AND-backward pattern, SURVEY.md §4 — here the
autodiff backward comes from jax.grad through the symbol graph, validated
against central finite differences at sampled coordinates)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu.symbol as S
from mxnet_tpu.executor import _build_graph_fn

EPS = 1e-3


def _check_grads(sym, input_shapes, aux_values=None, n_samples=8, atol=2e-2,
                 seed=0):
    """Compare jax.grad through the symbol graph vs finite differences of
    a random linear functional of the outputs, at sampled coordinates."""
    arg_names = sym.list_arguments()
    arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**input_shapes)
    rng = np.random.RandomState(seed)
    values = {}
    for name, shape in zip(arg_names, arg_shapes):
        values[name] = rng.uniform(-1.0, 1.0, shape).astype(np.float32)
    aux = dict(aux_values or {})
    for name, shape in zip(sym.list_auxiliary_states(), aux_shapes):
        if name not in aux:
            aux[name] = (np.ones(shape, np.float32) if "var" in name
                         else np.zeros(shape, np.float32))
    aux = {k: jnp.asarray(v) for k, v in aux.items()}
    weights = [rng.uniform(-1.0, 1.0, s).astype(np.float32)
               for s in out_shapes]
    graph_fn = _build_graph_fn(sym, is_train=True)
    key = jax.random.PRNGKey(0)

    def loss(vals):
        outs, _ = graph_fn(vals, aux, key)
        return sum(jnp.sum(o.astype(jnp.float32) * w)
                   for o, w in zip(outs, weights))

    vals_j = {k: jnp.asarray(v) for k, v in values.items()}
    grads = jax.grad(lambda v: loss(v))(vals_j)

    for name in arg_names:
        flat = values[name].ravel()
        g_flat = np.asarray(grads[name]).ravel()
        idxs = rng.choice(flat.size, size=min(n_samples, flat.size),
                          replace=False)
        for i in idxs:
            orig = flat[i]
            flat[i] = orig + EPS
            up = float(loss({k: jnp.asarray(v) for k, v in values.items()}))
            flat[i] = orig - EPS
            dn = float(loss({k: jnp.asarray(v) for k, v in values.items()}))
            flat[i] = orig
            numeric = (up - dn) / (2 * EPS)
            assert abs(numeric - g_flat[i]) < atol * max(1.0, abs(numeric)), \
                (name, i, numeric, g_flat[i])


def test_fullyconnected_grad():
    sym = S.FullyConnected(data=S.Variable("data"), num_hidden=5, name="fc")
    _check_grads(sym, {"data": (4, 6)})


def test_convolution_grad_nchw():
    sym = S.Convolution(data=S.Variable("data"), kernel=(3, 3), pad=(1, 1),
                        num_filter=4, name="c")
    _check_grads(sym, {"data": (2, 3, 6, 6)})


def test_convolution_grad_nhwc_1x1_dot_path():
    """The NHWC 1x1 fast path lowers as dot_general (ops/nn.py); its
    autodiff must match finite differences, including the strided variant
    that slices before the GEMM."""
    sym = S.Convolution(data=S.Variable("data"), kernel=(1, 1), num_filter=6,
                        layout="NHWC", name="c")
    _check_grads(sym, {"data": (2, 5, 5, 4)})
    sym = S.Convolution(data=S.Variable("data"), kernel=(1, 1), num_filter=6,
                        stride=(2, 2), layout="NHWC", name="c")
    _check_grads(sym, {"data": (2, 6, 6, 4)}, seed=1)


def test_convolution_grad_grouped():
    sym = S.Convolution(data=S.Variable("data"), kernel=(3, 3), pad=(1, 1),
                        num_filter=4, num_group=2, name="c")
    _check_grads(sym, {"data": (2, 4, 5, 5)}, seed=2)


def test_unary_grads():
    for name in ("exp", "square", "abs"):
        sym = getattr(S, name)(S.Variable("data"))
        _check_grads(sym, {"data": (3, 4)}, seed=4)
    for name in ("log", "sqrt"):
        # compose under exp to keep the argument positive at any sample
        sym = getattr(S, name)(S.exp(S.Variable("data")))
        _check_grads(sym, {"data": (3, 4)}, seed=4)


def test_blockgrad_stops_gradient():
    data = S.Variable("data")
    sym = S.FullyConnected(data=S.BlockGrad(data=data), num_hidden=3,
                           name="fc")
    graph_fn = _build_graph_fn(sym, is_train=True)
    rng = np.random.RandomState(0)
    vals = {n: jnp.asarray(rng.randn(*s).astype(np.float32))
            for n, s in zip(sym.list_arguments(),
                            sym.infer_shape(data=(2, 4))[0])}

    def loss(v):
        outs, _ = graph_fn(v, {}, jax.random.PRNGKey(0))
        return jnp.sum(outs[0] ** 2)

    grads = jax.grad(loss)(vals)
    np.testing.assert_allclose(grads["data"], 0.0)  # blocked
    assert float(jnp.abs(grads["fc_weight"]).sum()) > 0  # flows elsewhere


def test_deconvolution_grad():
    sym = S.Deconvolution(data=S.Variable("data"), kernel=(3, 3),
                          stride=(2, 2), num_filter=3, name="dc")
    _check_grads(sym, {"data": (2, 4, 5, 5)})


def test_pooling_grads():
    for pool_type in ("avg", "max", "sum"):
        sym = S.Pooling(data=S.Variable("data"), kernel=(2, 2), stride=(2, 2),
                        pool_type=pool_type, name="p")
        _check_grads(sym, {"data": (2, 3, 6, 6)}, seed=3)


def test_lrn_grad():
    sym = S.LRN(data=S.Variable("data"), nsize=3, name="lrn")
    _check_grads(sym, {"data": (2, 6, 4, 4)})


def test_batchnorm_grad():
    sym = S.BatchNorm(data=S.Variable("data"), name="bn")
    _check_grads(sym, {"data": (4, 3, 5, 5)}, atol=5e-2)


def test_batchnorm_relu_fused_grad():
    """The executor fuses BatchNorm -> Activation(relu); its hand-written
    VJP (recomputed relu mask) must match finite differences."""
    bn = S.BatchNorm(data=S.Variable("data"), name="bn")
    sym = S.Activation(data=bn, act_type="relu", name="relu")
    _check_grads(sym, {"data": (4, 3, 5, 5)}, atol=5e-2)


def test_batchnorm_add_relu_fused_grad():
    """Bottleneck-tail pattern BN -> +shortcut -> relu (fused by the
    executor into one kernel) vs finite differences."""
    bn = S.BatchNorm(data=S.Variable("data"), name="bn")
    sc = S.Convolution(data=S.Variable("shortcut"), kernel=(1, 1),
                       num_filter=3, no_bias=True, name="sc")
    sym = S.Activation(data=bn + sc, act_type="relu", name="relu")
    _check_grads(sym, {"data": (4, 3, 5, 5), "shortcut": (4, 3, 5, 5)},
                 atol=5e-2)


def test_batchnorm_add_relu_fused_matches_unfused(monkeypatch):
    """BN+add+relu fused vs MXNET_TPU_FUSE=0: outputs, grads, aux agree."""
    bn = S.BatchNorm(data=S.Variable("data"), name="bn")
    sym = S.Activation(data=bn + S.Variable("z"), act_type="relu",
                       name="relu")
    rng = np.random.RandomState(2)
    shapes = dict(zip(sym.list_arguments(),
                      sym.infer_shape(data=(4, 3, 5, 5), z=(4, 3, 5, 5))[0]))
    vals = {n: jnp.asarray(rng.uniform(-1, 1, s).astype(np.float32))
            for n, s in shapes.items()}
    aux = {"bn_moving_mean": jnp.zeros(3), "bn_moving_var": jnp.ones(3)}
    key = jax.random.PRNGKey(0)

    def run():
        fn = _build_graph_fn(sym, is_train=True)

        def loss(v):
            outs, new_aux = fn(v, aux, key)
            return jnp.sum(outs[0] ** 2), (outs[0], new_aux)

        (l, (out, new_aux)), grads = jax.value_and_grad(
            loss, has_aux=True)(vals)
        return l, out, new_aux, grads

    monkeypatch.setenv("MXNET_TPU_FUSE", "0")
    l0, out0, aux0, g0 = run()
    monkeypatch.setenv("MXNET_TPU_FUSE", "1")
    l1, out1, aux1, g1 = run()
    np.testing.assert_allclose(out0, out1, atol=1e-6)
    for k in aux0:
        np.testing.assert_allclose(aux0[k], aux1[k], atol=1e-6)
    for k in g0:
        np.testing.assert_allclose(g0[k], g1[k], atol=1e-5, err_msg=k)


def test_batchnorm_relu_fused_matches_unfused(monkeypatch):
    """Fused vs MXNET_TPU_FUSE=0 paths agree on outputs, grads, and aux."""
    bn = S.BatchNorm(data=S.Variable("data"), name="bn")
    sym = S.Activation(data=bn, act_type="relu", name="relu")
    rng = np.random.RandomState(1)
    vals = {n: jnp.asarray(rng.uniform(-1, 1, s).astype(np.float32))
            for n, s in zip(sym.list_arguments(),
                            sym.infer_shape(data=(4, 3, 5, 5))[0])}
    aux = {"bn_moving_mean": jnp.zeros(3), "bn_moving_var": jnp.ones(3)}
    key = jax.random.PRNGKey(0)

    def run():
        fn = _build_graph_fn(sym, is_train=True)

        def loss(v):
            outs, new_aux = fn(v, aux, key)
            return jnp.sum(outs[0] ** 2), (outs[0], new_aux)

        (l, (out, new_aux)), grads = jax.value_and_grad(
            loss, has_aux=True)(vals)
        return l, out, new_aux, grads

    monkeypatch.setenv("MXNET_TPU_FUSE", "0")
    l0, out0, aux0, g0 = run()
    monkeypatch.setenv("MXNET_TPU_FUSE", "1")
    l1, out1, aux1, g1 = run()
    np.testing.assert_allclose(out0, out1, atol=1e-6)
    np.testing.assert_allclose(l0, l1, atol=1e-5)
    for k in aux0:
        np.testing.assert_allclose(aux0[k], aux1[k], atol=1e-6)
    for k in g0:
        np.testing.assert_allclose(g0[k], g1[k], atol=1e-5, err_msg=k)


def test_embedding_grad():
    emb = S.Embedding(data=S.Variable("data"), input_dim=7, output_dim=4,
                      name="emb")
    sym = S.FullyConnected(data=emb, num_hidden=3, name="fc")
    arg_names = sym.list_arguments()
    # ids must stay fixed (non-differentiable input): check weight grads only
    graph_fn = _build_graph_fn(sym, is_train=True)
    rng = np.random.RandomState(0)
    shapes = {"data": (5,)}
    arg_shapes, out_shapes, _ = sym.infer_shape(**shapes)
    values = {}
    for name, shape in zip(arg_names, arg_shapes):
        if name == "data":
            values[name] = rng.randint(0, 7, shape).astype(np.float32)
        else:
            values[name] = rng.uniform(-1, 1, shape).astype(np.float32)
    w = rng.uniform(-1, 1, out_shapes[0]).astype(np.float32)

    def loss(vals):
        outs, _ = graph_fn(vals, {}, jax.random.PRNGKey(0))
        return jnp.sum(outs[0] * w)

    grads = jax.grad(lambda v: loss(v))(
        {k: jnp.asarray(v) for k, v in values.items()})
    for name in ("emb_weight", "fc_weight", "fc_bias"):
        flat = values[name].ravel()
        g = np.asarray(grads[name]).ravel()
        for i in rng.choice(flat.size, size=min(6, flat.size), replace=False):
            orig = flat[i]
            flat[i] = orig + EPS
            up = float(loss({k: jnp.asarray(v) for k, v in values.items()}))
            flat[i] = orig - EPS
            dn = float(loss({k: jnp.asarray(v) for k, v in values.items()}))
            flat[i] = orig
            numeric = (up - dn) / (2 * EPS)
            assert abs(numeric - g[i]) < 2e-2 * max(1.0, abs(numeric))


def test_slice_channel_concat_grad():
    x = S.Variable("data")
    parts = S.SliceChannel(data=x, num_outputs=3, name="sc")
    sym = S.Concat(parts[2], parts[0], parts[1], name="cat")
    _check_grads(sym, {"data": (2, 6, 3, 3)})


def test_leakyrelu_grads():
    for act in ("leaky", "elu"):
        sym = S.LeakyReLU(data=S.Variable("data"), act_type=act, name="lr")
        _check_grads(sym, {"data": (3, 7)}, seed=2)


def test_activation_grads_all():
    for act in ("relu", "sigmoid", "tanh", "softrelu"):
        sym = S.Activation(data=S.Variable("data"), act_type=act, name="a")
        _check_grads(sym, {"data": (3, 9)}, seed=4)


def test_transpose_reshape_grad():
    x = S.Variable("data")
    t = S.Transpose(data=x, axes=(0, 2, 1), name="t")
    sym = S.Reshape(data=t, target_shape=(2, 12), name="r")
    _check_grads(sym, {"data": (2, 3, 4)})


def test_elementwise_binary_grads():
    a, b = S.Variable("a"), S.Variable("b")
    for sym in (a + b, a - b, a * b, a / b):
        _check_grads(sym, {"a": (3, 4), "b": (3, 4)}, seed=6)
