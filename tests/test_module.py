"""Module API tier (BASELINE north star: "train end-to-end via
module.fit()"): the explicit bind/init/forward/backward/update lifecycle
and the one-call fit must both train to accuracy over the same TPU-native
executor machinery FeedForward uses."""

import numpy as np

import mxnet_tpu as mx


def _dataset(n=256, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    X = np.concatenate([rng.randn(n // 2, dim) + 1.0,
                        rng.randn(n // 2, dim) - 1.0]).astype(np.float32)
    y = np.concatenate([np.ones(n // 2), np.zeros(n // 2)]).astype(np.float32)
    perm = rng.permutation(n)
    return X[perm], y[perm]


def _mlp():
    net = mx.symbol.Variable("data")
    net = mx.symbol.FullyConnected(data=net, num_hidden=16, name="fc1")
    net = mx.symbol.Activation(data=net, act_type="relu", name="relu1")
    net = mx.symbol.FullyConnected(data=net, num_hidden=2, name="fc2")
    return mx.symbol.SoftmaxOutput(data=net, name="softmax")


def test_module_fit_and_score():
    X, y = _dataset()
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.fit(it, num_epoch=6, initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1 / 32.0})
    name, acc = mod.score(mx.io.NDArrayIter(X, y, batch_size=32))
    assert name == "accuracy" and acc > 0.95, (name, acc)
    preds = mod.predict(mx.io.NDArrayIter(X, y, batch_size=32))
    assert preds.shape == (len(X), 2)
    assert (preds.argmax(1) == y).mean() > 0.95


def test_module_explicit_lifecycle_matches_fit():
    """The by-hand loop (bind -> init_params -> init_optimizer ->
    forward/backward/update) is the same training path as fit()."""
    X, y = _dataset(seed=3)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9,
                                         "rescale_grad": 1 / 32.0})
    metric = mx.metric.create("accuracy")
    for _ in range(6):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, batch.label)
    _, acc = metric.get()
    assert acc > 0.95, acc


def test_module_checkpoint_roundtrip(tmp_path):
    X, y = _dataset(seed=5)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.fit(it, num_epoch=4, initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1 / 32.0})
    prefix = str(tmp_path / "mod")
    mod.save_checkpoint(prefix, 4)

    # explicit lifecycle restore: bind + init_params picks up the loaded
    # checkpoint (no fit needed)
    mod2 = mx.mod.Module.load(prefix, 4)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    _, acc2 = mod2.score(mx.io.NDArrayIter(X, y, batch_size=32))
    _, acc1 = mod.score(mx.io.NDArrayIter(X, y, batch_size=32))
    assert abs(acc1 - acc2) < 1e-6, (acc1, acc2)

    # and the checkpoint interoperates with FeedForward.load (same
    # prefix-symbol.json + prefix-%04d.params container)
    ff = mx.model.FeedForward.load(prefix, 4)
    p = ff.predict(X)
    assert (p.argmax(1) == y).mean() > 0.9


def test_module_bind_without_label_shapes_keeps_labels_as_inputs():
    """Forgetting label_shapes must not silently turn the label into a
    trainable parameter: bind infers declared label names as inputs, so
    forward feeds the batch's real labels and update never touches them."""
    X, y = _dataset(seed=7)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.bind(data_shapes=it.provide_data)  # label_shapes forgotten
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9,
                                         "rescale_grad": 1 / 32.0})
    assert "softmax_label" not in mod._param_names
    metric = mx.metric.create("accuracy")
    for _ in range(6):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, batch.label)
    _, acc = metric.get()
    assert acc > 0.95, acc  # real labels flowed: training converged


def test_module_fit_with_kvstore():
    """Gradients round through a kvstore each step (push/pull before the
    local update) — the update-on-worker aggregation path."""
    X, y = _dataset(seed=11)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    kv = mx.kv.create("local")
    mod.fit(it, num_epoch=6, initializer=mx.init.Xavier(), kvstore=kv,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1 / 32.0})
    _, acc = mod.score(mx.io.NDArrayIter(X, y, batch_size=32))
    assert acc > 0.95, acc


def test_bucketing_module_trains_shared_weights():
    """BucketingModule: one executor per seq-len bucket over ONE shared
    parameter set (the successor API over BucketingFeedForward's
    per-shape compile cache). Trains the cyclic-token LM from the
    bucketing tier and checks cross-bucket weight sharing by object
    identity."""
    from mxnet_tpu.models import lstm_unroll

    VOCAB = 8
    rng = np.random.RandomState(0)
    sents = []
    for _ in range(64):
        length = int(rng.choice([3, 4, 6, 7]))
        start = int(rng.randint(1, VOCAB))
        s = [start]
        for _ in range(length - 1):
            s.append(s[-1] % 7 + 1)
        sents.append(s)

    def sym_gen(seq_len):
        return lstm_unroll(num_layers=1, seq_len=seq_len, input_size=VOCAB,
                           num_hidden=16, num_embed=8, num_label=VOCAB)

    init_states = [("l0_init_c", (8, 16)), ("l0_init_h", (8, 16))]
    it = mx.BucketSentenceIter(sents, buckets=[4, 8], batch_size=8,
                               init_states=init_states)
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.fit(it, num_epoch=12, initializer=mx.init.Xavier(),
            eval_metric="accuracy",
            optimizer_params={"learning_rate": 0.3, "momentum": 0.9,
                              "rescale_grad": 1 / 8.0})
    # both buckets bound, parameters shared by OBJECT identity
    assert set(mod._bucket_execs) == {4, 8}
    e4, e8 = mod._bucket_execs[4], mod._bucket_execs[8]
    shared = [n for n in e4.arg_dict if "weight" in n]
    assert shared and all(e4.arg_dict[n] is e8.arg_dict[n] for n in shared)

    name, acc = mod.score(mx.BucketSentenceIter(sents, buckets=[4, 8],
                                                batch_size=8,
                                                init_states=init_states))
    # the cycle rule t -> t%7+1 is deterministic: well above chance
    assert acc > 0.5, acc


def test_module_fit_with_do_checkpoint_callback(tmp_path):
    """mx.callback.do_checkpoint plugs into Module.fit's epoch_end hook
    unchanged (same (epoch, symbol, args, aux) signature as FeedForward)."""
    X, y = _dataset(seed=13)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    prefix = str(tmp_path / "cb")
    mod = mx.mod.Module(_mlp())
    mod.fit(it, num_epoch=2, initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1 / 32.0},
            epoch_end_callback=mx.callback.do_checkpoint(prefix))
    # both epochs checkpointed in FeedForward's container format
    ff = mx.model.FeedForward.load(prefix, 2)
    assert (ff.predict(X).argmax(1) == y).mean() > 0.9


def test_module_install_monitor():
    """Monitor attaches to the bound executor and reports per-batch
    internal stats through tic/toc, like the reference Module surface."""
    from mxnet_tpu.monitor import Monitor

    X, y = _dataset(seed=17)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mon = Monitor(interval=1, pattern=".*fc1.*")
    mod.install_monitor(mon)
    it.reset()
    batch = next(iter(it))
    mon.tic()
    mod.forward(batch, is_train=False)
    stats = mon.toc()
    assert stats and all("fc1" in name for _, name, _ in stats)
    assert all(np.isfinite(s) for _, _, s in stats)


def test_module_with_imagerecorditer(tmp_path):
    """Module.fit over the RecordIO pipeline iterator (DataIter protocol
    integration: provide_data/label shapes, pad handling, conv net)."""
    from mxnet_tpu import recordio as rio
    from mxnet_tpu.models import lenet

    rec = str(tmp_path / "d.rec")
    rng = np.random.RandomState(0)
    w = rio.MXRecordIO(rec, "w")
    for i in range(192):
        cls = i % 2
        img = rng.randint(0, 60, (32, 32, 3), np.uint8)
        if cls:
            img[8:24, 8:24] = 220
        w.write(rio.pack_img(rio.IRHeader(0, float(cls), i, 0), img,
                             img_fmt=".jpg", quality=92))
    w.close()

    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 28, 28),
                               batch_size=32, rand_crop=True, shuffle=True,
                               mean_r=60.0, mean_g=60.0, mean_b=60.0,
                               scale=1 / 255.0)
    mod = mx.mod.Module(lenet(num_classes=2),
                        data_names=tuple(n for n, _ in it.provide_data))
    mod.fit(it, num_epoch=8, initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "rescale_grad": 1 / 32.0})
    _, acc = mod.score(mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 28, 28), batch_size=32,
        mean_r=60.0, mean_g=60.0, mean_b=60.0, scale=1 / 255.0))
    assert acc > 0.9, acc


def test_module_inference_only_bind():
    """bind(for_training=False): no gradient buffers anywhere, forward
    works, update is refused (optimizer lifecycle never ran)."""
    X, y = _dataset(seed=19)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    train = mx.mod.Module(_mlp())
    train.fit(it, num_epoch=4, initializer=mx.init.Xavier(),
              optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                "rescale_grad": 1 / 32.0})
    arg, aux = train.get_params()

    infer = mx.mod.Module(_mlp())
    infer.bind(data_shapes=it.provide_data, for_training=False)
    infer.init_params(arg_params=arg, aux_params=aux)
    assert not any(infer._exec.grad_dict.values())
    preds = infer.predict(mx.io.NDArrayIter(X, y, batch_size=32))
    assert (preds.argmax(1) == y).mean() > 0.95
    try:
        infer.update()
        raise AssertionError("expected MXNetError")
    except mx.base.MXNetError:
        pass


def test_module_update_rejects_server_side_updater_stores():
    """Stores whose updater runs server-side (group set_optimizer, or any
    store after set_updater) must be refused by Module.update: their pull
    returns weights, which this path would mis-apply as gradients."""
    from mxnet_tpu.kvstore import create_group

    X, y = _dataset(seed=23)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer()

    kv = create_group(1)[0]
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    try:
        mod.update(kvstore=kv)
        raise AssertionError("expected MXNetError for group server updater")
    except mx.base.MXNetError as e:
        assert "update-on-kvstore" in str(e)

    kv2 = mx.kv.create("local")
    kv2.set_updater(lambda k, g, w: None)
    try:
        mod.update(kvstore=kv2)
        raise AssertionError("expected MXNetError for local set_updater")
    except mx.base.MXNetError as e:
        assert "update-on-kvstore" in str(e)
