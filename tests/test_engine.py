"""Host-engine tests (reference: tests/cpp/threaded_engine_test.cc —
randomized read/write workloads compared against serial execution)."""

import random
import threading
import time

import pytest

from mxnet_tpu.engine import Engine


def test_push_and_wait():
    eng = Engine(num_workers=4)
    v = eng.new_variable()
    results = []
    eng.push(lambda: results.append(1), write_vars=[v])
    eng.wait_for_var(v)
    assert results == [1]


def test_write_ordering():
    """Writes to the same var execute in push order."""
    eng = Engine(num_workers=4)
    v = eng.new_variable()
    seq = []
    for i in range(20):
        eng.push(lambda i=i: seq.append(i), write_vars=[v])
    eng.wait_for_all()
    assert seq == list(range(20))


def test_read_write_dependency():
    eng = Engine(num_workers=4)
    v = eng.new_variable()
    log = []

    def writer(tag):
        def _w():
            time.sleep(0.01)
            log.append(("w", tag))
        return _w

    def reader(tag):
        def _r():
            log.append(("r", tag))
        return _r

    eng.push(writer(0), write_vars=[v])
    eng.push(reader(0), read_vars=[v])
    eng.push(reader(1), read_vars=[v])
    eng.push(writer(1), write_vars=[v])
    eng.wait_for_all()
    # writer0 first; readers before writer1
    assert log[0] == ("w", 0)
    assert set(log[1:3]) == {("r", 0), ("r", 1)}
    assert log[3] == ("w", 1)


def test_randomized_workload_matches_serial():
    """Generate a random read/write workload over N counters and check the
    threaded engine produces the same final state as serial evaluation
    (the reference's GenerateWorkload pattern)."""
    rng = random.Random(42)
    n_vars, n_ops = 6, 120
    tasks = []
    for _ in range(n_ops):
        writes = rng.sample(range(n_vars), 1)
        reads = rng.sample([i for i in range(n_vars) if i not in writes],
                           rng.randint(0, 2))
        delta = rng.randint(1, 5)
        tasks.append((reads, writes, delta))

    # serial reference
    serial = [0] * n_vars
    for reads, writes, delta in tasks:
        base = sum(serial[r] for r in reads)
        for w in writes:
            serial[w] += delta + base

    eng = Engine(num_workers=8)
    vars_ = [eng.new_variable() for _ in range(n_vars)]
    state = [0] * n_vars
    for reads, writes, delta in tasks:
        def task(reads=reads, writes=writes, delta=delta):
            base = sum(state[r] for r in reads)
            for w in writes:
                state[w] += delta + base
        eng.push(task, read_vars=[vars_[r] for r in reads],
                 write_vars=[vars_[w] for w in writes])
    eng.wait_for_all()
    assert state == serial


def test_exception_propagates():
    eng = Engine(num_workers=2)
    v = eng.new_variable()

    def boom():
        raise ValueError("boom")

    eng.push(boom, write_vars=[v])
    with pytest.raises(ValueError, match="boom"):
        eng.wait_for_var(v)


def test_naive_engine_synchronous():
    eng = Engine(synchronous=True)
    order = []
    eng.push(lambda: order.append(1))
    order.append(2)
    assert order == [1, 2]


def test_push_sync_returns_value():
    eng = Engine(num_workers=2)
    assert eng.push_sync(lambda: 42) == 42


def test_reader_list_does_not_leak():
    """Finished read tasks must leave the var's reader list (a long-lived
    read-only var previously accumulated every read future)."""
    from mxnet_tpu.engine import Engine

    eng = Engine(num_workers=2)
    v = eng.new_variable("hot")
    for _ in range(200):
        eng.push(lambda: None, read_vars=(v,)).result()
    eng.wait_for_all()
    # allow stragglers' done-callbacks to fire
    import time
    for _ in range(50):
        if not v._readers:
            break
        time.sleep(0.01)
    assert len(v._readers) == 0
