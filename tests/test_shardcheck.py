"""mxlint Pass 5 — the sharding audit of the lowered program (ISSUE 16).

Four layers of coverage, mirroring the pass's own structure:

- GOLDEN collective tables: the dp-8 gradient exchange compiled under
  every compression tier (none/bf16/int8/twobit) x overlap on/off must
  reconcile against its closed-form plan with ZERO MX802 drift, and the
  faithful-dtype payloads (s8/u8/f32) must match the plan's element
  counts EXACTLY (==, not approx). bf16 payloads are upcast to f32 by
  the CPU backend; the audit matches them via ``allow_widen`` and
  reports each in ``widened`` — never silently.
- SEEDED violations: every rule (MX801-MX805) has a fixture it must
  fire on and a near-miss it must stay silent on. The MX802 fixtures
  cross-audit programs against the WRONG plan (compression dropped /
  unplanned collectives / element-count drift).
- The RUNTIME gate: ``precompile(shard_audit=...)`` report and raise
  paths, the ``MXNET_TPU_SHARD_AUDIT`` env resolution.
- The TIER-1 SELF-AUDIT: ``selfcheck_report()`` — the repo's own dp-8
  full-stack fused step (int8 + overlap + comm kernels + health +
  guards) audits clean. This is the shipped contract behind
  ``python -m mxnet_tpu.analysis --shardcheck``.

Plus the CLI surfaces: ``--list-rules`` carries the MX80x band, findings
dedup across passes, and the ``--baseline`` CI flow exits 3 exactly when
NEW violations appear. Runs on conftest's 8-virtual-CPU-device rig.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import comm
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.compat import shard_map
from mxnet_tpu.analysis import main as mxlint_main
from mxnet_tpu.analysis.rules import RULES, Finding, get_rule
from mxnet_tpu.analysis.sharding import (
    DEFAULT_MIN_REPLICATED_BYTES, ShardAuditReport, audit_collective_drift,
    audit_jaxpr_sharding, audit_step_program, check_partition_specs,
    expected_collectives, selfcheck_report, shard_audit_enabled)
from mxnet_tpu.analysis.source_lint import lint_paths, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
L = 8192  # flat gradient elements for the golden exchange


def _mesh8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    return Mesh(np.array(devs[:8]), ("dp",))


def _exchange_hlo(mode, overlap):
    """Compile the dp-8 gradient exchange one way; return (hlo, plan).

    ``overlap`` with a real compression tier uses the bucketed
    overlap_allreduce and its wire_plan(); mode None has no overlapped
    form (plan_overlap refuses — the schedule pipelines the *quantized*
    sync), so that cell compiles the fused psum and audits it against
    allreduce_plan, which is exactly what fit() runs for that config.
    """
    mesh = _mesh8()
    g = np.random.RandomState(0).randn(8, L).astype(np.float32)
    if overlap and mode is not None:
        oplan = comm.plan_overlap({"w": (L,)}, mode, 8)
        plan = oplan.wire_plan()
        resid = comm.init_overlap_residuals(oplan)
        if resid is None:  # bf16: no error feedback to carry

            def body(gs):
                out, _ = comm.overlap_allreduce(
                    {"w": gs[0]}, None, oplan, "dp", average=True)
                return out["w"][None]

            f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"),
                                  out_specs=P("dp"), check_vma=False))
            return f.lower(g).compile().as_text(), plan

        def body(gs, res):
            out, res2 = comm.overlap_allreduce(
                {"w": gs[0]}, res, oplan, "dp", average=True)
            return out["w"][None], res2

        f = jax.jit(shard_map(body, mesh=mesh,
                              in_specs=(P("dp"), P("dp")),
                              out_specs=(P("dp"), P("dp")),
                              check_vma=False))
        return f.lower(g, resid).compile().as_text(), plan

    plan = comm.allreduce_plan(L, 8, mode)

    def body(gs):
        out = comm.compressed_allreduce({"w": gs[0]}, mode, "dp",
                                        axis_size=8, average=True)
        return out["w"][None]

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"),
                          out_specs=P("dp"), check_vma=False))
    return f.lower(g).compile().as_text(), plan


# -- golden collective tables: 4 tiers x overlap on/off ------------------------

@pytest.mark.parametrize("overlap", [False, True],
                         ids=["fused", "overlap"])
@pytest.mark.parametrize("mode", [None, "bf16", "int8", "twobit"])
def test_golden_exchange_reconciles_exactly(mode, overlap):
    """ACCEPTANCE: MX802 zero drift on every compression x overlap cell,
    with EXACT (==) element equality for every faithfully-lowered dtype
    and explicit ``widened`` rows (never silent) for the CPU backend's
    bf16->f32 payload normalization."""
    hlo, plan = _exchange_hlo(mode, overlap)
    findings, report = audit_collective_drift(hlo, plan, compression=mode)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert report["unplanned"] == []
    # every planned group resolved, each to the exact element count
    resolved = report["matched"] + report["widened"]
    assert len(resolved) == len(report["expected"])
    for row in resolved:
        assert row["hlo_elements"] == row["expected_elements"], row
    # faithful dtypes match at their own width; widening only ever
    # explains a bf16/f16 plan row
    for row in report["matched"]:
        assert row["hlo_dtype"] == row["dtype"]
    for row in report["widened"]:
        assert row["dtype"] in ("bf16", "f16") and \
            row["hlo_dtype"] == "f32", row
    # the bare exchange has no loss/metric scalars: nothing unexplained
    assert report["stat_rows"] == []


def test_hlo_collective_rows_structure():
    """SATELLITE (a): per-collective rows expose op kind, replica-group
    shape, and element dtype — the evidence surface MX802 consumes."""
    hlo, _ = _exchange_hlo("int8", False)
    rows = comm.hlo_collective_rows(hlo, 8)
    assert rows, "int8 exchange must contain collectives"
    for r in rows:
        assert set(r) >= {"op", "async", "payload_bytes", "wire_bytes",
                          "group_size", "replica_groups", "parts"}
        assert r["group_size"] == 8
        for part in r["parts"]:
            assert set(part) == {"dtype", "elements", "bytes"}
    ops = {r["op"] for r in rows}
    assert ops >= {"all-to-all", "all-gather"}
    dtypes = {p["dtype"] for r in rows for p in r["parts"]}
    assert "s8" in dtypes, f"int8 codes must be visible on the wire: {dtypes}"
    table = comm.hlo_collective_table(hlo, 8)
    for trow in table:
        assert set(trow) >= {"op", "count", "payload_bytes", "wire_bytes",
                             "elements", "dtypes", "replica_groups"}


def test_expected_collectives_rejects_mode_mismatch():
    plan = comm.allreduce_plan(L, 8, "int8")
    with pytest.raises(ValueError, match="does not match plan mode"):
        expected_collectives(plan, compression="bf16")


# -- MX802 seeded drift --------------------------------------------------------

def test_mx802_fires_when_compression_silently_dropped():
    """The plan says int8 (a2a + ag of codes and scales) but the program
    lowered the uncompressed psum: every planned collective is missing
    AND the full-size f32 all-reduce is unplanned."""
    hlo, _ = _exchange_hlo(None, False)
    plan = comm.allreduce_plan(L, 8, "int8")
    # the 8192-element f32 sync is 32 KiB — drop the stat allowance
    # below it so the unplanned op is named, not absorbed
    findings, report = audit_collective_drift(hlo, plan,
                                              compression="int8",
                                              small_allreduce_bytes=1024)
    assert findings and all(f.rule.id == "MX802" for f in findings)
    assert all(f.is_error for f in findings)
    msgs = " | ".join(f.message for f in findings)
    assert "missing" in msgs
    assert "unplanned all-reduce" in msgs  # the fp32 sync sneaking back
    assert report["unplanned"], "full-size all-reduce must be named"


def test_mx802_fires_on_unplanned_compressed_collectives():
    """Converse drift: the program compresses but the plan says plain
    all-reduce — every all-to-all/all-gather on the wire is named."""
    hlo, _ = _exchange_hlo("int8", False)
    plan = comm.allreduce_plan(L, 8, None)
    findings, _ = audit_collective_drift(hlo, plan)
    named_ops = {f.node.split(":")[0] for f in findings
                 if "unplanned" in f.message}
    assert {"all-to-all", "all-gather"} <= named_ops


def test_mx802_fires_on_element_count_drift():
    """Same op set, wrong payload size (the plan describes a larger
    parameter count than the program syncs) — the per-(op,dtype)
    element totals disagree and no allowance can absorb a SHORTFALL."""
    hlo, _ = _exchange_hlo(None, False)
    plan = comm.allreduce_plan(2 * L, 8, None)
    findings, _ = audit_collective_drift(hlo, plan)
    assert findings
    assert any("expects" in f.message and "moves" in f.message
               for f in findings)


_GROUPS8 = "replica_groups={{0,1,2,3,4,5,6,7}}"
_SYNTH_GRAD = ("  %ar.1 = f32[8192]{0} all-reduce(f32[8192]{0} %x), "
               + _GROUPS8 + "\n")
_SYNTH_STAT_F32 = ("  %ar.2 = f32[8]{0} all-reduce(f32[8]{0} %y), "
                   + _GROUPS8 + "\n")
_SYNTH_STAT_S32 = ("  %ar.3 = s32[4]{0} all-reduce(s32[4]{0} %z), "
                   + _GROUPS8 + "\n")


def test_mx802_small_allreduce_allowance_is_bounded():
    """The step's own bookkeeping scalars (loss psum, guard counters)
    are allowed under the threshold — via BOTH shapes they lower to: a
    same-dtype scalar merged into the planned gradient all-reduce
    (extra elements), and a separate small all-reduce of another dtype
    (stat row). One byte past the threshold, each becomes drift."""
    plan = comm.allreduce_plan(8192, 8, None)
    hlo = _SYNTH_GRAD + _SYNTH_STAT_F32 + _SYNTH_STAT_S32
    findings, report = audit_collective_drift(hlo, plan)
    assert findings == [], "\n".join(f.format() for f in findings)
    # the f32 scalars merged into the planned group: extra elements
    (m,) = report["matched"]
    assert m["stat_elements"] == 8
    # the s32 guard counters stayed a separate tiny all-reduce: stat row
    (s,) = report["stat_rows"]
    assert (s["dtype"], s["elements"], s["bytes"]) == ("s32", 4, 16)
    # threshold is a hard bound: 32 extra f32 bytes vs a 31-byte allowance
    findings31, _ = audit_collective_drift(hlo, plan,
                                           small_allreduce_bytes=31)
    msgs = " | ".join(f.message for f in findings31)
    assert "expects 8192" in msgs and "moves 8200" in msgs
    # and 16 s32 bytes vs a 15-byte allowance
    findings15, _ = audit_collective_drift(
        _SYNTH_GRAD + _SYNTH_STAT_S32, plan, small_allreduce_bytes=15)
    assert any("unplanned all-reduce" in f.message for f in findings15)


# -- MX801 / MX803 seeded jaxprs ----------------------------------------------

def test_mx801_fires_on_large_replicated_constraint():
    mesh = _mesh8()
    big = jnp.zeros((1024, 1024), jnp.float32)  # 4 MiB >= 1 MiB threshold

    def f(x):
        return jax.lax.with_sharding_constraint(
            x * 2.0, NamedSharding(mesh, P()))

    closed = jax.make_jaxpr(f)(big)
    findings = audit_jaxpr_sharding(closed, axis_sizes={"dp": 8})
    assert [f_.rule.id for f_ in findings] == ["MX801"]
    assert "replicated" in findings[0].message
    assert findings[0].extra["bytes"] == 4 * 1024 * 1024


def test_mx801_silent_on_small_or_sharded_or_single_device():
    mesh = _mesh8()
    small = jnp.zeros((8, 8), jnp.float32)
    big = jnp.zeros((1024, 1024), jnp.float32)

    def repl_small(x):
        return jax.lax.with_sharding_constraint(
            x * 2.0, NamedSharding(mesh, P()))

    def sharded_big(x):
        return jax.lax.with_sharding_constraint(
            x * 2.0, NamedSharding(mesh, P("dp")))

    assert audit_jaxpr_sharding(jax.make_jaxpr(repl_small)(small),
                                axis_sizes={"dp": 8}) == []
    assert audit_jaxpr_sharding(jax.make_jaxpr(sharded_big)(big),
                                axis_sizes={"dp": 8}) == []
    # dp=1: replication is free, no finding even on the big tensor
    assert audit_jaxpr_sharding(jax.make_jaxpr(repl_small)(big),
                                axis_sizes={"dp": 1}) == []


def test_mx803_fires_on_collective_in_scan_body():
    mesh = _mesh8()

    def body(xs):
        def scan_step(carry, x):
            return carry + jax.lax.psum(x, "dp"), None

        out, _ = jax.lax.scan(scan_step, jnp.zeros(()), xs[0])
        return jax.lax.psum(out, "dp")[None]  # one-shot: must NOT fire

    f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P(),
                  check_vma=False)
    closed = jax.make_jaxpr(f)(np.zeros((8, 16), np.float32))
    findings = audit_jaxpr_sharding(closed, axis_sizes={"dp": 8})
    mx803 = [f_ for f_ in findings if f_.rule.id == "MX803"]
    assert len(mx803) == 1, [f_.format() for f_ in findings]
    assert "scan" in mx803[0].message
    assert "EVERY iteration" in mx803[0].message


def test_mx803_silent_on_one_shot_collectives():
    mesh = _mesh8()

    def body(xs):
        return jax.lax.psum(xs.sum(), "dp")[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P(),
                  check_vma=False)
    closed = jax.make_jaxpr(f)(np.zeros((8, 16), np.float32))
    assert audit_jaxpr_sharding(closed, axis_sizes={"dp": 8}) == []


# -- MX804 seeded specs --------------------------------------------------------

def test_mx804_fires_on_unknown_axis_and_unsharded_batch():
    findings = check_partition_specs(
        {"w": P("tp"), "data": P(None, None)},
        {"dp": 8}, batch=("data",))
    ids = sorted(f.rule.id for f in findings)
    assert ids == ["MX804", "MX804"]
    msgs = " | ".join(f.message for f in findings)
    assert "'tp'" in msgs and "unsharded" in msgs
    assert all(f.is_error for f in findings)


def test_mx804_silent_on_clean_specs():
    mesh = _mesh8()
    assert check_partition_specs(
        {"w": P(), "data": P("dp")}, mesh, batch=("data",)) == []
    # dp=1 mesh: an unsharded batch is fine
    assert check_partition_specs(
        {"data": P(None)}, {"dp": 1}, batch=("data",)) == []


# -- MX805 source fixtures -----------------------------------------------------

_MX805_SRC = (
    "import jax\n"
    "from jax.sharding import NamedSharding, PartitionSpec as P\n"
    "def place(x, mesh):\n"
    "    sh = NamedSharding(mesh, P())\n"
    "    a = jax.device_put(x, sh)\n"
    "    b = jax.device_put(x, NamedSharding(mesh, P('dp')))\n"
    "    shards = {k: NamedSharding(mesh, P()) for k in ('w', 'b')}\n"
    "    c = jax.device_put(x, shards['w'])\n"
    "    d = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))\n"
    "    return a, b, c, d\n")


def test_mx805_fires_on_placement_outside_owner_layers():
    findings = [f for f in lint_source(_MX805_SRC,
                                       "mxnet_tpu/models/foo.py")
                if f.rule.id == "MX805"]
    # named sharding, inline sharding, dict-comprehension subscript,
    # and the raw constraint: four distinct sites
    assert len(findings) == 4, [f.format() for f in findings]


def test_mx805_silent_in_owner_layers_and_on_device_placement():
    for owner in ("mxnet_tpu/parallel/foo.py", "mxnet_tpu/comm/foo.py"):
        assert [f for f in lint_source(_MX805_SRC, owner)
                if f.rule.id == "MX805"] == []
    src = ("import jax\n"
           "def place(x, dev):\n"
           "    return jax.device_put(x, dev)\n")  # a Device, not a sharding
    assert [f for f in lint_source(src, "mxnet_tpu/models/foo.py")
            if f.rule.id == "MX805"] == []


def test_mx805_pragma_suppression_with_justification():
    src = ("import jax\n"
           "from jax.sharding import NamedSharding, PartitionSpec as P\n"
           "def restore(x, mesh):\n"
           "    return jax.device_put(x, NamedSharding(mesh, P()))"
           "  # mxlint: disable=MX805 - checkpoint restore\n")
    assert [f for f in lint_source(src, "mxnet_tpu/models/foo.py")
            if f.rule.id == "MX805"] == []


def test_self_lint_mx805_clean():
    """The tree itself keeps placement inside parallel/ + comm/; each
    deliberate exception carries a justified pragma."""
    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX805"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- the runtime gate ----------------------------------------------------------

def test_shard_audit_enabled_resolution(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_SHARD_AUDIT", raising=False)
    assert shard_audit_enabled() is False
    assert shard_audit_enabled(True) is True
    assert shard_audit_enabled(False) is False
    for off in ("", "0", "false", "off", "no"):
        monkeypatch.setenv("MXNET_TPU_SHARD_AUDIT", off)
        assert shard_audit_enabled() is False
    monkeypatch.setenv("MXNET_TPU_SHARD_AUDIT", "1")
    assert shard_audit_enabled() is True
    assert shard_audit_enabled(False) is False  # explicit arg wins


def _small_model(ctx):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=16)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=2)
    net = sym.SoftmaxOutput(data=net, name="softmax")
    return mx.FeedForward(net, ctx=ctx, num_epoch=1, learning_rate=0.5)


def test_precompile_shard_audit_raises_on_seeded_error(monkeypatch):
    """The gate's contract: an error-severity finding in the report
    aborts precompile(shard_audit=True) BEFORE any step could run,
    naming the rule; shard_audit='report' returns the same findings
    without raising."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from mxnet_tpu.analysis import sharding as shard_mod

    seeded = ShardAuditReport(findings=[Finding(
        get_rule("MX802"), "seeded drift for the raise-path test",
        node="all-gather:s8")])
    monkeypatch.setattr(shard_mod, "audit_step_program",
                        lambda *a, **k: seeded)
    model = _small_model([mx.cpu(i) for i in range(8)])
    kw = dict(data_shapes={"data": (16, 4)},
              label_shapes={"softmax_label": (16,)},
              compression="int8")
    with pytest.raises(MXNetError, match="MX802"):
        model.precompile(shard_audit=True, **kw)
    out = _small_model([mx.cpu(i) for i in range(8)]).precompile(
        shard_audit="report", **kw)
    assert out["shard_audit"], "report mode must still collect findings"
    assert any(f.rule.id == "MX802"
               for rep in out["shard_audit"] for f in rep.findings)


def test_precompile_shard_audit_report_clean_on_real_program():
    """The real (un-seeded) small int8 program audits clean through the
    precompile gate — the report path returns evidence, not findings."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    model = _small_model([mx.cpu(i) for i in range(8)])
    out = model.precompile(data_shapes={"data": (16, 4)},
                           label_shapes={"softmax_label": (16,)},
                           compression="int8", shard_audit="report")
    reports = out["shard_audit"]
    assert reports
    for rep in reports:
        assert rep.findings == [], \
            "\n".join(f.format() for f in rep.findings)
        assert rep.reconciliation.get("matched"), \
            "audit must show evidence it reconciled, not just silence"


def test_fit_shard_audit_gate_runs_and_trains(monkeypatch):
    """The fit-loop hook: with shard_audit=True the warmed program is
    audited once per batch signature before its first dispatch, and a
    clean program trains normally. The audit call is observed through
    the same audit_step_program the CLI uses."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from mxnet_tpu.analysis import sharding as shard_mod

    calls = []
    real = shard_mod.audit_step_program

    def spy(*a, **k):
        rep = real(*a, **k)
        calls.append(rep)
        return rep

    monkeypatch.setattr(shard_mod, "audit_step_program", spy)
    rng = np.random.RandomState(0)
    X = rng.randn(64, 4).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    model = _small_model([mx.cpu(i) for i in range(8)])
    model.fit(X, y, batch_size=32, compression="int8", shard_audit=True)
    assert calls, "fit(shard_audit=True) must audit the warmed program"
    for rep in calls:
        assert rep.errors == [], \
            "\n".join(f.format() for f in rep.errors)
    assert model.arg_params  # trained through the gate


# -- the tier-1 self-audit -----------------------------------------------------

def test_selfcheck_full_stack_dp8_zero_findings():
    """ACCEPTANCE: the repo's own dp-8 full-stack fused step (int8 +
    overlap + fused comm kernels + health stats + guards) audits clean
    — the --shardcheck CLI target. Evidence-bearing: the report must
    show exact matched rows, not a skipped audit."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    rep = selfcheck_report()
    assert rep.findings == [], "\n".join(f.format() for f in rep.findings)
    rec = rep.reconciliation
    assert rec.get("matched"), "reconciliation must have matched rows"
    assert rec.get("unplanned") == []
    for row in rec["matched"] + rec["widened"]:
        assert row["hlo_elements"] >= row["expected_elements"]
    assert not rep.errors


# -- registry / docs / CLI parity ----------------------------------------------

def test_mx80x_registry_docs_and_list_rules_agree(capsys):
    """SATELLITE (f): every MX80x rule exists in the registry, appears in
    the static_analysis.md catalog, and is printed by --list-rules —
    drift in any direction fails."""
    band = sorted(r for r in RULES if r.startswith("MX8"))
    assert band == ["MX801", "MX802", "MX803", "MX804", "MX805"]
    doc = open(os.path.join(
        REPO, "doc", "developer-guide", "static_analysis.md"),
        encoding="utf-8").read()
    for rid in band:
        assert f"| {rid} |" in doc, f"{rid} missing from the rule catalog"
    assert "MXNET_TPU_SHARD_AUDIT" in open(
        os.path.join(REPO, "doc", "env_var.md"), encoding="utf-8").read()
    rc = mxlint_main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rid in band:
        assert rid in out
    # severities in the listing match the registry
    assert RULES["MX802"].severity == "error"
    assert RULES["MX804"].severity == "error"


def test_cli_dedups_findings_across_duplicate_inputs(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    try:\n        pass\n"
                   "    except:\n        pass\n")
    rc = mxlint_main([str(bad), str(bad)])
    out = capsys.readouterr().out
    assert rc == 1  # MX601 is error severity
    assert out.count("MX601") == 1, out


def test_cli_baseline_flow_exits_3_only_on_new(tmp_path, capsys):
    """SATELLITE (CI surface): first run seeds the baseline (exit 0),
    an unchanged tree compares clean (exit 0), and a NEW violation —
    and only the new one — is reported with exit 3."""
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    try:\n        pass\n"
                   "    except:\n        pass\n")
    base = tmp_path / "lint_baseline.json"
    assert mxlint_main([str(bad), "--baseline", str(base)]) == 0
    assert json.loads(base.read_text()), "baseline must record the finding"
    capsys.readouterr()
    assert mxlint_main([str(bad), "--baseline", str(base)]) == 0
    out = capsys.readouterr().out
    assert "0 new vs baseline" in out
    worse = tmp_path / "worse.py"
    worse.write_text("def g():\n    try:\n        pass\n"
                     "    except:\n        pass\n")
    rc = mxlint_main([str(bad), str(worse), "--baseline", str(base),
                      "--ci"])
    out = capsys.readouterr().out
    assert rc == 3
    rows = [ln for ln in out.splitlines() if ln.startswith("MX")]
    assert len(rows) == 1 and "worse.py" in rows[0], out
    cols = rows[0].split("\t")
    assert cols[0] == "MX601" and cols[1] == "error"


def test_audit_step_program_notes_when_plan_missing():
    """Sub-checks that cannot run are recorded, never silently skipped."""
    mesh = _mesh8()

    def body(xs):
        return jax.lax.psum(xs.sum(), "dp")[None]

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"),
                          out_specs=P(), check_vma=False))
    x = np.zeros((8, 16), np.float32)
    rep = audit_step_program(f, (x,), hlo_text=f.lower(x).compile()
                             .as_text(), mesh=mesh)
    assert any("MX802 skipped" in n for n in rep.notes)
    assert rep.table, "collective table still collected without a plan"
