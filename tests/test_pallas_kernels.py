"""Pallas kernel layer tests (ISSUE 13 acceptance).

Covers: the shared interpret gate (+ env override), fused comm
quantize/dequantize bitwise wire parity vs the compression.py reference
codecs, the dp-8 exchange's HLO quantize-pass reduction with identical
collective wire bytes, fused-Adam/AdamW bitwise parity vs the per-leaf
optimizer (state layout unchanged, cross-path resume), int8 matmul error
bound + the Predictor serving path, the kernel registry's jaxpr/MFU
attribution (flash attention's FLOPs stop being invisible), and the
armed zero-recompile epoch with every kernel enabled.

Bitwise comparisons run both paths inside ONE jit: XLA's algebraic
rewrites (e.g. divide -> multiply-by-reciprocal on CPU) apply uniformly
within a program, which is exactly the context the kernels run in (the
fused train step) — eager-vs-jit is the comparison that isn't meaningful.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import mxnet_tpu as mx
import mxnet_tpu.optimizer as opt_mod
from mxnet_tpu import comm
from mxnet_tpu.analysis import jaxpr_audit
from mxnet_tpu.compat import shard_map
from mxnet_tpu.ops import pallas as pk
from mxnet_tpu.ops.pallas import comm_kernels as ck
from mxnet_tpu.ops.pallas.adam import fused_adam_apply
from mxnet_tpu.utils import compile as cm


def _mesh8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    return Mesh(np.array(devs[:8]), ("dp",))


def _ctx8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    return [mx.cpu(i) for i in range(8)]


def _blobs(n=160, d=10, k=4, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, k, n)
    X += (rng.randn(k, d) * 3.0)[y]
    return X.astype(np.float32), y.astype(np.int32)


def _mlp(hidden=64, classes=4):
    d = mx.symbol.Variable("data")
    h = mx.symbol.FullyConnected(d, num_hidden=hidden, name="fc1")
    h = mx.symbol.Activation(h, act_type="relu")
    h = mx.symbol.FullyConnected(h, num_hidden=classes, name="fc2")
    return mx.symbol.SoftmaxOutput(h, name="softmax")


# -- shared interpret gate -----------------------------------------------------

def test_interpret_gate_env_override(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_PALLAS_INTERPRET", raising=False)
    assert pk.use_interpret() == (jax.default_backend() != "tpu")
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    assert pk.use_interpret() is True
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "0")
    assert pk.use_interpret() is False
    assert pk.resolve_interpret(True) is True
    assert pk.resolve_interpret(None) is False  # env still forces compiled
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "on")
    assert pk.resolve_interpret(None) is True


def test_flash_attention_uses_shared_gate():
    # the hoisted helper is the one flash consults (satellite: no more
    # module-local default_backend() read)
    import importlib

    # the package re-exports the flash_attention FUNCTION under the
    # module's name, so resolve the module through importlib
    fa = importlib.import_module("mxnet_tpu.ops.pallas.flash_attention")
    from mxnet_tpu.ops.pallas import _common

    assert fa._use_interpret is _common.use_interpret


# -- fused comm kernels: bitwise wire parity -----------------------------------

@pytest.mark.parametrize("mode", ["int8", "twobit"])
def test_fused_quantize_bitwise_wire_parity(mode):
    """ACCEPTANCE: kernel payload == reference codec payload, bit for
    bit, for every wire array AND the error-feedback round-trip."""
    spec = comm.CompressionSpec(mode, chunk=256)
    rng = np.random.RandomState(0)
    rows = jnp.asarray(rng.randn(8, 2048).astype(np.float32))

    @jax.jit
    def both(x):
        ref = comm.encode(spec, x)
        ref_dq = comm.decode(spec, ref)
        pay, dq = ck.fused_quantize(spec, x, want_dequant=True,
                                    block_elems=512)
        sum_ref = jnp.sum(comm.decode(spec, ref), axis=0)
        sum_k = ck.fused_dequant_sum(spec, pay, block_elems=512)
        dec_k = ck.fused_dequant(spec, pay, block_elems=512)
        return ref, ref_dq, pay, dq, sum_ref, sum_k, dec_k

    ref, ref_dq, pay, dq, sum_ref, sum_k, dec_k = both(rows)
    assert set(pay) == set(ref)
    for k in ref:
        assert pay[k].dtype == ref[k].dtype
        assert pay[k].shape == ref[k].shape
        assert (np.asarray(pay[k]) == np.asarray(ref[k])).all(), (mode, k)
    # the fused decode round-trip IS the codec's (residual basis bitwise)
    assert (np.asarray(dq) == np.asarray(ref_dq)).all()
    assert (np.asarray(dec_k) == np.asarray(ref_dq)).all()
    # the accumulate fuses the sum: values agree to reduction order
    np.testing.assert_allclose(np.asarray(sum_k), np.asarray(sum_ref),
                               rtol=1e-6, atol=1e-6)


def test_fused_quantize_1d_and_block_picking():
    spec = comm.CompressionSpec("int8", chunk=4)
    v = jnp.asarray(np.random.RandomState(1).randn(64).astype(np.float32))
    pay, dq = jax.jit(lambda x: ck.fused_quantize(spec, x,
                                                  want_dequant=True))(v)
    ref = comm.encode(spec, v)
    assert pay["q"].shape == ref["q"].shape == (64,)
    assert pay["scale"].shape == ref["scale"].shape == (16,)
    assert dq.shape == (64,)
    # block picking: divides, unit-multiple, capped
    assert ck.pick_block(2048, 256, 512) == 512
    assert ck.pick_block(2048, 256, 700) == 512
    assert ck.pick_block(1280, 256, 512) == 256
    assert ck.pick_block(12, 4, 8) == 4
    with pytest.raises(mx.base.MXNetError):
        ck.pick_block(10, 4)


def test_exchange_kernel_path_hlo_and_values():
    """ACCEPTANCE: on the dp-8 mesh the kernel path (a) removes EVERY
    full-slab quantize-shaped HLO pass the codec path runs, (b) moves
    byte-identical collectives, (c) produces the same reduced gradients
    and residuals (to reduction order)."""
    mesh = _mesh8()
    ndev = 8
    spec = comm.CompressionSpec("int8", chunk=256)
    L = ndev * 2048
    rng = np.random.RandomState(0)
    tree = {"g": jnp.asarray(rng.randn(L).astype(np.float32))}
    resid = jnp.asarray(rng.randn(ndev, L).astype(np.float32) * 0.01)

    def build(cfg):
        def body(t, r):
            return comm.error_feedback_allreduce(
                t, r, spec, axis_name="dp", axis_size=ndev, kernels=cfg)
        return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(P(), P("dp")),
                                 out_specs=(P(), P("dp")),
                                 check_vma=False))

    f_ref = build(False)
    f_k = build(comm.CommKernelConfig(block_elems=512))
    hlo_ref = f_ref.lower(tree, resid).compile().as_text()
    hlo_k = f_k.lower(tree, resid).compile().as_text()

    passes_ref = comm.hlo_quantize_pass_count(hlo_ref, min_elements=L)
    passes_k = comm.hlo_quantize_pass_count(hlo_k, min_elements=L)
    assert passes_ref > 0
    assert passes_k == 0, (passes_k, passes_ref)

    wire_ref = sum(r["wire_bytes"] for r in
                   comm.hlo_collective_table(hlo_ref, default_group_size=8))
    wire_k = sum(r["wire_bytes"] for r in
                 comm.hlo_collective_table(hlo_k, default_group_size=8))
    assert wire_ref == wire_k > 0

    (out_ref, res_ref) = f_ref(tree, resid)
    (out_k, res_k) = f_k(tree, resid)
    # the fused accumulate's summation order is not the codec path's, so
    # a reduced value landing within an ulp of a round boundary can flip
    # one stage-2 quantization step — the difference is bounded by that
    # step (one scale unit) and must be RARE; the wire payloads of each
    # path against its own codec reference are bitwise (test above)
    o_ref, o_k = np.asarray(out_ref["g"]), np.asarray(out_k["g"])
    step = np.abs(o_ref).max() / 127.0
    diff = np.abs(o_k - o_ref)
    assert diff.max() <= step * 1.01, (diff.max(), step)
    assert (diff > step * 1e-3).mean() < 0.01  # full-step flips are rare
    r_diff = np.abs(np.asarray(res_k) - np.asarray(res_ref))
    assert r_diff.max() <= step * 1.01
    assert (r_diff > step * 1e-3).mean() < 0.01


def test_overlap_allreduce_kernel_path_matches_codec():
    """SATELLITE wiring: comm/overlap.py threads kernels= per bucket."""
    mesh = _mesh8()
    ndev = 8
    shapes = {"a": (64, 32), "b": (96,), "c": (32, 16)}
    plan = comm.plan_overlap(shapes, "int8", ndev, max_bytes=4096)
    rng = np.random.RandomState(2)
    tree = {k: jnp.asarray(rng.randn(*s).astype(np.float32))
            for k, s in shapes.items()}
    resid = comm.init_overlap_residuals(plan)

    def build(cfg):
        def body(t, r):
            return comm.overlap_allreduce(t, r, plan, axis_name="dp",
                                          kernels=cfg)
        rspec = {k: P("dp") for k in resid}
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(), rspec),
            out_specs=(P(), rspec), check_vma=False))

    out_ref, res_ref = build(False)(tree, resid)
    out_k, res_k = build(comm.CommKernelConfig(block_elems=256))(tree, resid)
    # same bound as test_exchange_kernel_path_hlo_and_values: the fused
    # accumulate's sum order can flip one stage-2 quantization step
    step = max(float(np.abs(np.asarray(out_ref[k])).max())
               for k in tree) / 127.0
    for k in tree:
        d = np.abs(np.asarray(out_k[k]) - np.asarray(out_ref[k]))
        assert d.max() <= step * 1.01, (k, d.max(), step)
    for k in res_ref:
        d = np.abs(np.asarray(res_k[k]) - np.asarray(res_ref[k]))
        assert d.max() <= step * 1.01, (k, d.max(), step)


def test_comm_kernel_config_resolve(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_COMM_KERNELS", raising=False)
    assert comm.CommKernelConfig.resolve(None) is None
    assert comm.CommKernelConfig.resolve(False) is None
    assert comm.CommKernelConfig.resolve(True).block_elems is None
    assert comm.CommKernelConfig.resolve(4096).block_elems == 4096
    cfg = comm.CommKernelConfig(block_elems=512)
    assert comm.CommKernelConfig.resolve(cfg) is cfg
    monkeypatch.setenv("MXNET_TPU_COMM_KERNELS", "1")
    assert comm.CommKernelConfig.resolve(None) is not None
    monkeypatch.setenv("MXNET_TPU_COMM_KERNELS", "8192")
    assert comm.CommKernelConfig.resolve(None).block_elems == 8192
    monkeypatch.setenv("MXNET_TPU_COMM_KERNELS", "off")
    assert comm.CommKernelConfig.resolve(None) is None
    with pytest.raises(mx.base.MXNetError):
        comm.CommKernelConfig(block_elems=0)


# -- fused Adam/AdamW ----------------------------------------------------------

def test_fused_adam_bitwise_parity():
    """ACCEPTANCE: fused kernel == Adam._apply_one per leaf, bitwise on
    f32 — params AND both moments, with rescale/clip/L2-wd active."""
    rng = np.random.RandomState(1)
    shapes = {"w1": (64, 33), "b1": (33,), "w2": (7, 5), "s": ()}
    params = {n: jnp.asarray(np.asarray(rng.randn(*s), np.float32))
              for n, s in shapes.items()}
    grads = {n: jnp.asarray(np.asarray(rng.randn(*s), np.float32))
             for n, s in shapes.items()}
    opt = opt_mod.Adam(lr=0.01, wd=0.02, clip_gradient=0.5,
                       rescale_grad=1.0 / 32)
    states = opt.init_state_tree(params)

    @jax.jit
    def both(p, g, s, lr):
        ref = opt_mod.Optimizer.apply(opt, p, g, s, lr)
        fz = fused_adam_apply(opt, p, g, s, lr, block=64)
        return ref, fz

    for step in range(3):  # bias correction moves with t
        (rp, rs), (fp, fs) = both(params, grads, states, jnp.float32(0.01))
        for n in shapes:
            assert (np.asarray(rp[n]) == np.asarray(fp[n])).all(), (step, n)
            for i in range(3):
                assert (np.asarray(rs[n][i]) == np.asarray(fs[n][i])).all()
        params, states = rp, rs


def test_fused_adamw_decay_filter_parity():
    rng = np.random.RandomState(2)
    shapes = {"w1": (48, 16), "b1": (16,), "ln_scale": (16,)}
    params = {n: jnp.asarray(rng.randn(*s).astype(np.float32))
              for n, s in shapes.items()}
    grads = {n: jnp.asarray(rng.randn(*s).astype(np.float32))
             for n, s in shapes.items()}
    flt = lambda n: n.startswith("w")  # noqa: E731
    ref_opt = opt_mod.AdamW(weight_decay=0.05, decay_filter=flt,
                            fused=False)
    fz_opt = opt_mod.AdamW(weight_decay=0.05, decay_filter=flt, fused=True)
    states = ref_opt.init_state_tree(params)

    @jax.jit
    def both(p, g, s, lr):
        return ref_opt.apply(p, g, s, lr), fz_opt.apply(p, g, s, lr)

    (rp, rs), (fp, fs) = both(params, grads, states, jnp.float32(0.003))
    for n in shapes:
        assert (np.asarray(rp[n]) == np.asarray(fp[n])).all(), n
        for i in range(3):
            assert (np.asarray(rs[n][i]) == np.asarray(fs[n][i])).all()


def test_fused_adam_state_layout_and_cross_path_resume():
    """SATELLITE: fused-Adam state layout == tree_state layout (no
    checkpoint migration), and a trajectory may switch paths mid-run:
    fused steps then per-leaf steps == per-leaf throughout, bitwise."""
    rng = np.random.RandomState(3)
    shapes = {"a": (32, 8), "b": (8,)}
    params0 = {n: jnp.asarray(rng.randn(*s).astype(np.float32))
               for n, s in shapes.items()}
    fused = opt_mod.Adam(lr=0.01, fused=True)
    plain = opt_mod.Adam(lr=0.01, fused=False)
    s_f = fused.init_state_tree(params0)
    s_p = plain.init_state_tree(params0)
    assert jax.tree_util.tree_structure(s_f) == \
        jax.tree_util.tree_structure(s_p)

    def grad_of(i):
        r = np.random.RandomState(100 + i)
        return {n: jnp.asarray(r.randn(*shapes[n]).astype(np.float32))
                for n in shapes}

    run_f = jax.jit(lambda p, g, s: fused.apply(p, g, s, jnp.float32(0.01)))
    run_p = jax.jit(lambda p, g, s: plain.apply(p, g, s, jnp.float32(0.01)))

    pa, sa = params0, s_f
    for i in range(2):
        pa, sa = run_f(pa, grad_of(i), sa)
    # state layout identical => the per-leaf path resumes it directly
    assert jax.tree_util.tree_structure(sa) == \
        jax.tree_util.tree_structure(s_p)
    for i in range(2, 4):
        pa, sa = run_p(pa, grad_of(i), sa)

    pb, sb = params0, s_p
    for i in range(4):
        pb, sb = run_p(pb, grad_of(i), sb)
    for n in shapes:
        assert (np.asarray(pa[n]) == np.asarray(pb[n])).all(), n
        for i in range(3):
            assert (np.asarray(sa[n][i]) == np.asarray(sb[n][i])).all()


def test_fused_adam_env_gate(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_FUSED_ADAM", raising=False)
    assert not opt_mod.Adam()._fused_active()
    assert opt_mod.Adam(fused=True)._fused_active()
    monkeypatch.setenv("MXNET_TPU_FUSED_ADAM", "1")
    assert opt_mod.Adam()._fused_active()
    assert not opt_mod.Adam(fused=False)._fused_active()


# -- int8 matmul ---------------------------------------------------------------

def test_int8_matmul_error_bound_and_shapes():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(37, 100).astype(np.float32))
    w = jnp.asarray(rng.randn(23, 100).astype(np.float32))
    y = pk.int8_matmul(x, w, block_m=16, block_n=16)
    ref = x @ w.T
    assert y.shape == (37, 23) and y.dtype == jnp.float32
    err = float(jnp.linalg.norm(y - ref) / jnp.linalg.norm(ref))
    assert err < 2e-2, err
    # pre-quantized weights path
    wq, sw = pk.quantize_channels(w)
    y2 = pk.int8_matmul(x, wq, w_scale=sw, block_m=16, block_n=16)
    assert (np.asarray(y2) == np.asarray(y)).all()
    with pytest.raises(ValueError):
        pk.int8_matmul(x, wq)


def test_predictor_int8_quantize_serving_path():
    """SATELLITE wiring: Predictor(quantize='int8') serves FC matmuls
    through the kernel — close to f32, and actually quantized."""
    from mxnet_tpu.predictor import Predictor

    X, y = _blobs(96)
    model = mx.FeedForward(_mlp(hidden=32), ctx=mx.cpu(), num_epoch=3,
                           learning_rate=0.5)
    model.fit(X, y, batch_size=32)
    args = {k: v for k, v in model.arg_params.items()}
    p32 = Predictor(model.symbol, args, model.aux_params)
    p8 = Predictor(model.symbol, args, model.aux_params, quantize="int8")
    out32 = p32.forward(data=X[:32]).get_output(0)
    out8 = p8.forward(data=X[:32]).get_output(0)
    np.testing.assert_allclose(out8, out32, rtol=0.1, atol=0.05)
    assert not (out8 == out32).all()  # the quantized program really ran
    assert (out8.argmax(axis=1) == out32.argmax(axis=1)).mean() > 0.9
    with pytest.raises(mx.base.MXNetError):
        Predictor(model.symbol, args, quantize="int4")


# -- kernel registry + jaxpr/MFU attribution -----------------------------------

def test_registry_catalog_covers_all_kernels():
    names = set(pk.kernel_names())
    assert {"flash_fwd", "flash_bwd_dq", "flash_bwd_dkv",
            "quant_int8", "quant_twobit", "dequant_sum_int8",
            "dequant_sum_twobit", "dequant_int8", "dequant_twobit",
            "fused_adam", "int8_matmul"} <= names
    cat = pk.catalog()
    assert all(r["doc"] and r["module"].startswith("mxnet_tpu.ops.pallas")
               for r in cat)


def test_jaxpr_audit_attributes_flash_flops():
    """SATELLITE: transformer-shaped forward with flash attention — the
    registry-attributed FLOP total strictly exceeds the unattributed
    baseline on the SAME trace, so MFU strictly increases (same peak,
    same wall time, bigger honest numerator)."""
    rng = np.random.RandomState(5)
    b, h, s, d = 2, 2, 128, 32
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    wo = jnp.asarray(rng.randn(h * d, h * d).astype(np.float32))

    def transformer_fwd(q, wo):
        attn = pk.flash_attention(q, q, q, causal=True,
                                  block_q=32, block_k=32)
        o = attn.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        return jnp.sum(o @ wo)

    closed = jax.make_jaxpr(transformer_fwd)(q, wo)
    with_reg = jaxpr_audit.audit_jaxpr(closed)
    without = jaxpr_audit.audit_jaxpr(closed, attribute_kernels=False)
    assert with_reg.totals["flops"] > without.totals["flops"]
    prows = {r["primitive"]: r for r in with_reg.rows
             if r["primitive"].startswith("pallas::")}
    assert "pallas::flash_fwd" in prows
    # the model: 4 * bh * sq * sk * d (padded dims here == logical dims)
    assert prows["pallas::flash_fwd"]["flops"] == 4 * b * h * s * s * d
    # baseline counted one grid cell at elementwise rates — the dense
    # matmul FLOPs must dominate it
    assert with_reg.totals["flops"] >= 4 * b * h * s * s * d


def test_mfu_accountant_counts_flash():
    """The PR 5 MFU path resolves FLOPs through the same audit — a flash
    program's flops_per_step now includes the attention FLOPs."""
    from mxnet_tpu.telemetry.mfu import MFUAccountant

    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(1, 2, 64, 32).astype(np.float32))
    step = jax.jit(lambda x: jnp.sum(
        pk.flash_attention(x, x, x, causal=False, block_q=32, block_k=32)))
    acct = MFUAccountant(num_devices=1, peak_flops=1e12)
    flops = acct.maybe_trace(step, (q,))
    assert flops is not None
    assert flops >= 4 * 1 * 2 * 64 * 64 * 32  # the flash_fwd model alone


def test_bench_roofline_jaxpr_table_shows_kernels():
    rows, totals = jaxpr_audit.cost_rows(
        lambda x: pk.flash_attention(x, x, x, causal=False,
                                     block_q=32, block_k=32),
        jnp.zeros((1, 1, 64, 32), jnp.float32))
    assert any(r["primitive"] == "pallas::flash_fwd" for r in rows)
    legacy_rows, legacy_totals = jaxpr_audit.cost_rows(
        lambda x: pk.flash_attention(x, x, x, causal=False,
                                     block_q=32, block_k=32),
        jnp.zeros((1, 1, 64, 32), jnp.float32), attribute_kernels=False)
    assert totals["flops"] > legacy_totals["flops"]


# -- end-to-end: the armed epoch with every kernel on --------------------------

def test_fit_with_kernels_convergence_and_zero_recompile():
    """ACCEPTANCE: compression='int8' + comm_kernels + fused Adam reach
    fp32-parity accuracy, and a RecompileTracker-armed epoch compiles
    nothing after epoch 0 (the kernel paths perturb neither donation nor
    the program signature)."""
    X, y = _blobs(160)

    def train(**kw):
        np.random.seed(0)
        mx.random.seed(0)
        model = mx.FeedForward(_mlp(), ctx=_ctx8(), num_epoch=4,
                               optimizer="adam", learning_rate=0.01,
                               initializer=mx.init.Xavier())
        model.fit(X, y, batch_size=32, **kw)
        return (model.predict(X, batch_size=32).argmax(axis=1) == y).mean()

    acc_fp32 = train()
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    np.random.seed(0)
    mx.random.seed(0)
    model = mx.FeedForward(_mlp(), ctx=_ctx8(), num_epoch=4,
                           optimizer="adam", learning_rate=0.01,
                           initializer=mx.init.Xavier(), fused=True)
    try:
        model.fit(X, y, batch_size=32, compression="int8",
                  comm_kernels=True, epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    assert tracker.recompiles == []
    acc_k = (model.predict(X, batch_size=32).argmax(axis=1) == y).mean()
    assert acc_fp32 > 0.9
    assert abs(acc_k - acc_fp32) < 0.08, (acc_fp32, acc_k)


def test_precompile_with_comm_kernels_then_fit_no_compiles():
    X, y = _blobs(120)
    model = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=2,
                           optimizer="adam", learning_rate=0.01,
                           fused=True)
    out = model.precompile(data_shapes={"data": (40, 10)},
                           label_shapes={"softmax_label": (40,)},
                           compression="int8", comm_kernels=True)
    assert out["programs"] == 1
    with cm.RecompileTracker(raise_on_recompile=True):
        model.fit(X, y, batch_size=40, compression="int8",
                  comm_kernels=True)
