"""Unit tests for mxnet_tpu.capi_support.CApi — the Python brain behind the
flat C API. The ctypes tests (test_c_api.py) prove the C boundary; these
cover marshaling paths and registered-function semantics directly, where
failures give readable diffs instead of -1s."""

import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.capi_support import CApi
from mxnet_tpu.ndarray import NDArray


@pytest.fixture(scope="module")
def api():
    return CApi()


def _nd(arr):
    return NDArray(np.asarray(arr, np.float32))


def test_func_invoke_set_value_and_copyto(api):
    a = _nd(np.zeros((2, 3)))
    api.func_invoke("_set_value", [], [7.5], [a])
    assert np.allclose(a.asnumpy(), 7.5)
    b = _nd(np.zeros((2, 3)))
    api.func_invoke("_copyto", [a], [], [b])
    assert np.allclose(b.asnumpy(), 7.5)


def test_func_invoke_random_fill_moments(api):
    api.random_seed(0)
    u = _nd(np.zeros((4000,)))
    api.func_invoke("_random_uniform", [], [-1.0, 1.0], [u])
    vals = u.asnumpy()
    assert -1.0 <= vals.min() and vals.max() <= 1.0
    assert abs(vals.mean()) < 0.1
    g = _nd(np.zeros((4000,)))
    api.func_invoke("_random_gaussian", [], [2.0, 0.5], [g])
    gv = g.asnumpy()
    assert abs(gv.mean() - 2.0) < 0.1 and abs(gv.std() - 0.5) < 0.1


def test_func_invoke_onehot_and_clip(api):
    # reference arity (2, 0, 1): use_vars=(indices, out), mutate=(out,) —
    # the exact call shape the C layer produces from MXFuncDescribe
    idx = _nd([0.0, 2.0, 1.0])
    out = _nd(np.zeros((3, 3)))
    api.func_invoke("_onehot_encode", [idx, out], [], [out])
    assert np.allclose(out.asnumpy(), np.eye(3)[[0, 2, 1]])

    src = _nd([-5.0, 0.5, 9.0])
    dst = _nd(np.zeros((3,)))
    api.func_invoke("clip", [src], [-1.0, 1.0], [dst])
    assert np.allclose(dst.asnumpy(), [-1.0, 0.5, 1.0])


def test_func_describe_matches_reference_arity(api):
    # reference registrations (ndarray.cc:601-652)
    assert api.func_describe("_plus")[:3] == (2, 0, 1)
    assert api.func_describe("_mul_scalar")[:3] == (1, 1, 1)
    assert api.func_describe("_random_uniform")[:3] == (0, 2, 1)
    assert api.func_describe("_set_value")[:3] == (0, 1, 1)


def test_iter_param_parsing(api):
    p = api._parse_iter_val
    assert p("8") == 8
    assert p("0.5") == 0.5
    assert p("true") is True and p("False") is False
    assert p("(3, 28, 28)") == (3, 28, 28)
    assert p("(3,)") == (3,)
    assert p("path/to.rec") == "path/to.rec"


def test_symbol_atomic_compose_roundtrip(api):
    atom = api.symbol_create_atomic("FullyConnected", ["num_hidden"], ["4"])
    assert atom[0] == "__atomic__"
    data = api.symbol_create_variable("data")
    sym = api.symbol_compose(atom, "fc", ["data"], [data])
    assert api.symbol_list_arguments(sym) == ["data", "fc_weight", "fc_bias"]
    with pytest.raises(MXNetError):
        api.symbol_create_atomic("NoSuchOp", [], [])
    with pytest.raises(MXNetError):
        api.symbol_compose(sym, "again", ["data"], [data])


def test_infer_shape_full_and_error_paths(api):
    atom = api.symbol_create_atomic("FullyConnected", ["num_hidden"], ["4"])
    data = api.symbol_create_variable("data")
    sym = api.symbol_compose(atom, "fc", ["data"], [data])
    args, outs, aux, complete = api.symbol_infer_shape(sym, ["data"],
                                                       [(5, 3)])
    assert complete == 1
    assert args[1] == (4, 3) and outs[0] == (5, 4)
    # error path crosses the boundary as MXNetError (C formats it to -1)
    with pytest.raises(MXNetError):
        api.symbol_infer_shape(("__atomic__", "FullyConnected", {}),
                               ["data"], [(5, 3)])


def test_host_view_refresh_and_drop(api):
    a = _nd(np.arange(4, dtype=np.float32))
    p1 = api.ndarray_data_ptr(a)
    a[:] = np.array([9.0, 8, 7, 6], np.float32)
    p2 = api.ndarray_data_ptr(a)
    assert p1 == p2, "repeat GetData must refresh the SAME buffer"
    import ctypes

    view = (ctypes.c_float * 4).from_address(p1)
    assert list(view) == [9.0, 8.0, 7.0, 6.0]
    api.ndarray_drop_host_view(a)
    assert id(a) not in api._host_views


def test_ndarray_raw_roundtrip_and_save_load(api, tmp_path):
    a = _nd(np.random.RandomState(0).randn(3, 4))
    raw = api.ndarray_save_raw(a)
    b = api.ndarray_load_raw(raw)
    assert np.allclose(b.asnumpy(), a.asnumpy())

    f = str(tmp_path / "x.nd")
    api.ndarray_save(f, [a], ["w"])
    arrs, names = api.ndarray_load(f)
    assert names == ["w"] and np.allclose(arrs[0].asnumpy(), a.asnumpy())
