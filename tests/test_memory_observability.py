"""Memory-observability acceptance (ISSUE 9).

Covers: static per-program memory plans (AOT registration, debug_str
reading the registry instead of re-compiling, Prometheus/table export),
the live-array ledger (weakref byte accounting, watermarks, the epoch
leak detector), OOM preflight (the fail-fast over-budget gate with its
ranked report), flight-recorder memory forensics, the memory CLI
(``mem`` table + ``diff`` peak-memory gate), the memory_stats
pass-through contract, and the zero-recompile armed epoch with tracking
enabled."""

import gc
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import memory as mem_mod
from mxnet_tpu.utils import compile as cm
from mxnet_tpu.utils.memory import memory_stats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    telemetry.reset()
    telemetry.track_arrays(False)
    mem_mod.detach_sampler()
    mem_mod.reset_leak_tracker()
    mem_mod.ledger().clear()
    yield
    telemetry.track_arrays(False)
    mem_mod.detach_sampler()
    mem_mod.ledger().clear()


def _mlp():
    data = mx.sym.Variable("data")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        data, name="fc", num_hidden=4), name="softmax")
    return out


def _digits(n=128, dim=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, dim).astype(np.float32),
            rng.randint(0, classes, (n,)).astype(np.float32))


# -- utils.memory_stats contract -----------------------------------------------

def test_memory_stats_passthrough_and_zero_contract():
    """Satellite: backend stats keys pass through instead of being
    dropped; the zeros-on-CPU contract holds when nothing is exposed."""

    class _Rich:
        def memory_stats(self):
            return {"bytes_in_use": 100, "peak_bytes_in_use": 200,
                    "bytes_limit": 1000, "largest_alloc_size": 64,
                    "num_allocs": 7, "pool_bytes": 4096}

        def __str__(self):
            return "FakeTPU:0"

    class _Bare:
        def memory_stats(self):
            return None

        def __str__(self):
            return "FakeCPU:0"

    rich = memory_stats(_Rich())["FakeTPU:0"]
    assert rich["largest_alloc_size"] == 64
    assert rich["num_allocs"] == 7
    assert rich["pool_bytes"] == 4096
    assert rich["bytes_in_use"] == 100
    bare = memory_stats(_Bare())["FakeCPU:0"]
    assert bare == {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                    "bytes_limit": 0}
    # the real local backend honors the same always-present contract
    for row in memory_stats().values():
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            assert key in row


# -- static memory plans -------------------------------------------------------

def test_precompile_registers_plan_and_exports():
    """AOT warmup registers the program's memory_analysis breakdown in
    the compile registry, publishes labeled hub gauges, emits a
    memory_plan event, and the plan table renders it."""
    import jax
    import jax.numpy as jnp

    tj = cm.tracked_jit(lambda x: (x @ x).sum(), label="memtest:fwd")
    tj.precompile(jax.ShapeDtypeStruct((64, 64), jnp.float32))
    plan = cm.registry().memory_plan_for("memtest:fwd")
    assert plan is not None
    assert plan["argument_bytes"] == 64 * 64 * 4
    assert plan["total_bytes"] == plan["temp_bytes"] + plan["output_bytes"]
    events = telemetry.hub().events(kind="memory_plan")
    assert any(e["program"] == "memtest:fwd" for e in events)
    dump = telemetry.prom_dump()
    assert 'mxtpu_memory_plan_total_bytes{program="memtest:fwd"' in dump
    assert "memtest:fwd" in telemetry.plan_table()


def test_plans_republished_to_fresh_hub():
    """telemetry.reset() must not lose the plan gauges (on_hub_create
    re-publishes; the registry stays the owner)."""
    import jax
    import jax.numpy as jnp

    tj = cm.tracked_jit(lambda x: x * 2.0, label="memtest:republish")
    tj.precompile(jax.ShapeDtypeStruct((8,), jnp.float32))
    telemetry.reset()
    dump = telemetry.prom_dump()
    assert 'program="memtest:republish"' in dump


def test_debug_str_reads_plan_without_recompiling():
    """Satellite: a warmed executor's debug_str reads the registered plan
    (zero compiles); a never-compiled executor pays the fallback ONCE and
    registers the plan for the next call. Printed MB == plan MB."""
    out = _mlp()
    exe = out.simple_bind(mx.cpu(), data=(32, 8))
    exe.precompile(is_train=False)
    before = cm.registry().snapshot()["compiles"]
    s = exe.debug_str()
    assert cm.registry().snapshot()["compiles"] == before, \
        "debug_str re-compiled a warmed program"
    label = exe._fwd_fns[False].label  # THIS executor's warmed program
    plan = cm.registry().memory_plan_for(label)
    assert plan is not None
    assert f"Total {plan['total_bytes'] / (1 << 20):.4f} MB allocated" in s

    # fallback path: fresh executor, no plan -> one compile, then cached
    exe2 = _mlp().simple_bind(mx.cpu(), data=(16, 8))
    cm.reset_compile_stats()
    s2 = exe2.debug_str()
    assert "MB allocated" in s2
    mid = cm.registry().snapshot()["compiles"]
    assert mid >= 1
    s3 = exe2.debug_str()
    assert cm.registry().snapshot()["compiles"] == mid
    assert s3 == s2


# -- live-array ledger ---------------------------------------------------------

def test_ledger_tracks_live_bytes_and_watermark():
    prev = telemetry.track_arrays(True)
    led = mem_mod.ledger()
    base = led.live_bytes()
    a = mx.nd.zeros((128, 128))
    stats = led.stats()
    assert stats["live_bytes"] - base >= 128 * 128 * 4
    assert any(row["bytes"] >= 128 * 128 * 4 for row in led.top_arrays(3))
    peak = led.watermark_bytes
    del a
    gc.collect()
    assert led.live_bytes() < peak  # freed arrays leave the ledger
    assert led.watermark_bytes == peak  # ...but not the watermark
    telemetry.track_arrays(prev)


def test_ledger_dedups_wrappers_of_one_buffer():
    """NDArray(existing) and same-device as_in_context share one
    jax.Array — the ledger must count the BUFFER once, and free it only
    when the last wrapper dies."""
    prev = telemetry.track_arrays(True)
    led = mem_mod.ledger()
    try:
        base = led.live_bytes()
        a = mx.nd.zeros((64, 64))
        once = led.live_bytes() - base
        b = mx.nd.NDArray(a)      # shares a._data
        c = a.as_in_context(a.context)  # same-device: returns a itself
        assert led.live_bytes() - base == once, "wrapper double-counted"
        del a, c
        gc.collect()
        assert led.live_bytes() - base == once, "freed while b holds it"
        del b
        gc.collect()
        assert led.live_bytes() == base
    finally:
        telemetry.track_arrays(prev)


def test_debug_str_distinguishes_shapes_of_one_symbol():
    """Two executors of the SAME symbol at different shapes share a
    program label; each debug_str must print ITS OWN totals, not the
    other's registered plan."""
    sym = _mlp()
    small = sym.simple_bind(mx.cpu(), data=(2, 8))
    big = sym.simple_bind(mx.cpu(), data=(512, 8))
    s_small = small.debug_str()
    s_big = big.debug_str()
    total_small = next(l for l in s_small.splitlines() if "Total" in l)
    total_big = next(l for l in s_big.splitlines() if "Total" in l)
    assert total_small != total_big
    # and re-printing the small one is not poisoned by big's plan
    assert next(l for l in small.debug_str().splitlines()
                if "Total" in l) == total_small


def test_phase_sampler_publishes_gauges():
    prev = telemetry.track_arrays(True)
    mem_mod.attach_sampler()
    try:
        keep = mx.nd.zeros((64, 64))
        tl = telemetry.StepTimeline()
        with tl.begin_step(0, 0) as span:
            span.mark("device")
        snap = telemetry.hub().snapshot()["gauges"]
        assert snap.get("live_array_bytes", 0) >= 64 * 64 * 4
        assert snap.get("live_array_watermark_bytes", 0) >= \
            snap["live_array_bytes"]
        del keep
    finally:
        mem_mod.detach_sampler()
        telemetry.track_arrays(prev)


def test_epoch_leak_detector_emits_incident():
    """Three epochs of >threshold watermark growth -> memory_leak event,
    and the flight recorder catches it in the incident ring."""
    prev = telemetry.track_arrays(True)
    mem_mod.reset_leak_tracker()
    hoard = []
    try:
        leaks = []
        for epoch in range(3):
            hoard.append(mx.nd.zeros((256, 256)))  # +256KB per epoch
            leak = mem_mod.epoch_mark(epoch, drift_bytes=1024,
                                      consecutive=2)
            leaks.append(leak)
        assert leaks[0] is None  # first epoch: no baseline to drift from
        assert leaks[2] is not None
        events = telemetry.hub().events(kind="memory_leak")
        assert events and events[-1]["epoch"] == 2
        _, _, incidents = telemetry.flight.recorder().snapshot()
        assert any(e["kind"] == "memory_leak" for e in incidents)
        marks = telemetry.hub().events(kind="memory_watermark")
        assert len(marks) == 3
    finally:
        telemetry.track_arrays(prev)


def test_steady_state_does_not_flag_leak():
    prev = telemetry.track_arrays(True)
    mem_mod.reset_leak_tracker()
    try:
        for epoch in range(4):
            a = mx.nd.zeros((64, 64))  # same transient every epoch
            del a
            gc.collect()
            assert mem_mod.epoch_mark(epoch, drift_bytes=1024,
                                      consecutive=2) is None
        assert telemetry.hub().events(kind="memory_leak") == []
    finally:
        telemetry.track_arrays(prev)


# -- OOM preflight -------------------------------------------------------------

def test_preflight_report_ranking_and_pass():
    report = mem_mod.preflight(
        [("param:small", 10), ("param:big", 1000), ("opt:mid", 100)],
        budget=10_000, what="test")
    assert report["fits"] is True
    assert report["entries"][0] == ("param:big", 1000)
    assert telemetry.hub().events(kind="memory_preflight")


def test_preflight_rejects_over_budget_fit_before_any_step(monkeypatch):
    """Acceptance: a synthetic over-budget model is rejected BEFORE any
    step runs, with a ranked byte report naming arrays/programs."""
    monkeypatch.setenv("MXNET_TPU_HBM_BYTES", "64")
    X, y = _digits()
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    before = cm.registry().snapshot()
    with pytest.raises(telemetry.MemoryPreflightError) as ei:
        model.fit(X, y, batch_size=32)
    msg = str(ei.value)
    assert "exceeds" in msg and "param:" in msg and "MB" in msg
    # ranked: first listed allocation is the largest
    first = float(msg.splitlines()[1].split("MB")[0])
    for line in msg.splitlines()[2:]:
        assert float(line.split("MB")[0]) <= first
    after = cm.registry().snapshot()
    assert after["misses"] == before["misses"], "a step program compiled"


def test_preflight_rejects_over_budget_precompile(monkeypatch):
    """precompile's gate uses the EXACT warmed program plans."""
    monkeypatch.setenv("MXNET_TPU_HBM_BYTES", "64")
    X, y = _digits()
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    with pytest.raises(telemetry.MemoryPreflightError) as ei:
        model.precompile(data_shapes={"data": (32, 8)},
                         label_shapes={"softmax_label": (32,)})
    assert "program temp+output" in str(ei.value)


def test_generous_budget_trains_and_reports(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_HBM_BYTES", str(1 << 30))
    X, y = _digits()
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    model.fit(X, y, batch_size=32)
    events = telemetry.hub().events(kind="memory_preflight")
    assert events and events[-1]["fits"] is True


# -- forensics -----------------------------------------------------------------

def test_flight_dump_carries_memory_snapshot(tmp_path):
    prev = telemetry.track_arrays(True)
    try:
        keep = mx.nd.zeros((64, 64))
        path = str(tmp_path / "flight.json")
        telemetry.flight.dump(path, reason="test")
        ok, payload = telemetry.validate_flight(path)
        assert ok, payload
        mem = payload["memory"]
        assert mem["tracking"] is True
        assert mem["ledger"]["live_bytes"] >= 64 * 64 * 4
        assert "allocator" in mem
        del keep
    finally:
        telemetry.track_arrays(prev)


def test_flight_show_renders_and_degrades_without_memory(tmp_path):
    """Satellite: `flight show` renders the memory section; a dump
    without one (pre-ISSUE-9, or a torn snapshot stripped by a tool)
    still validates and shows instead of failing."""
    import zlib

    from mxnet_tpu.telemetry.__main__ import main as cli

    prev = telemetry.track_arrays(True)
    try:
        mx.nd.zeros((32, 32)).wait_to_read()
        path = str(tmp_path / "flight.json")
        telemetry.flight.dump(path, reason="test")
    finally:
        telemetry.track_arrays(prev)
    assert cli(["flight", "show", path]) == 0

    # strip the memory section and re-seal the CRC: must still show clean
    blob = json.load(open(path))
    del blob["payload"]["memory"]
    body = json.dumps(blob["payload"], sort_keys=True, default=str)
    blob["crc32"] = zlib.crc32(body.encode()) & 0xFFFFFFFF
    bare = str(tmp_path / "bare.json")
    json.dump(blob, open(bare, "w"))
    assert cli(["flight", "validate", bare]) == 0
    assert cli(["flight", "show", bare]) == 0


# -- CLI -----------------------------------------------------------------------

def test_mem_cli_table_and_diff_gate(tmp_path):
    from mxnet_tpu.telemetry.__main__ import main as cli

    h = telemetry.hub()
    mem_mod.publish_plan("train_step:abc:bucket=16", {
        "argument_bytes": 1 << 20, "output_bytes": 1 << 18,
        "temp_bytes": 1 << 21, "generated_code_bytes": 0,
        "alias_bytes": 0, "total_bytes": (1 << 21) + (1 << 18)})
    h.emit("memory_watermark", epoch=0, watermark_bytes=1 << 20,
           live_bytes=1 << 19, live_count=12)
    a_path = str(tmp_path / "a.jsonl")
    telemetry.write_jsonl(a_path, h.events())
    assert cli(["mem", a_path]) == 0

    # diff: run B doubles the peak watermark -> peak_mem_mb regression
    telemetry.reset()
    h = telemetry.hub()
    h.emit("memory_watermark", epoch=0, watermark_bytes=2 << 20,
           live_bytes=1 << 19, live_count=12)
    b_path = str(tmp_path / "b.jsonl")
    telemetry.write_jsonl(b_path, h.events())
    assert cli(["diff", a_path, b_path, "--threshold", "50"]) == 3
    assert cli(["diff", a_path, a_path, "--threshold", "50"]) == 0


def test_mem_cli_no_events(tmp_path):
    from mxnet_tpu.telemetry.__main__ import main as cli

    path = str(tmp_path / "empty.jsonl")
    telemetry.write_jsonl(path, [{"kind": "span", "ts": 0.0}])
    assert cli(["mem", path]) == 1


# -- the zero-recompile invariant ----------------------------------------------

def test_zero_recompile_armed_epoch_with_memory_tracking():
    """Acceptance: the ledger + phase-boundary sampler are host-side
    bookkeeping — jit cache keys are untouched, the armed epoch stays
    green, and every epoch closes a watermark mark."""
    X, y = _digits()
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=3,
                           learning_rate=0.1)
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    try:
        model.fit(X, y, batch_size=32, telemetry=True,
                  epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    assert tracker.recompiles == []
    assert len(model.telemetry.steps("step")) == 12
    marks = telemetry.hub().events(kind="memory_watermark")
    assert [e["epoch"] for e in marks] == [0, 1, 2]
    assert not telemetry.memory.tracking_enabled()  # fit restored state


def test_warmed_fit_exports_plan_per_program(monkeypatch):
    """Acceptance: a precompile-warmed fit exposes the per-program plan
    through the CLI table and the Prometheus dump with rank/world
    labels."""
    X, y = _digits()
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    info = model.precompile(data_shapes={"data": (32, 8)},
                            label_shapes={"softmax_label": (32,)})
    plans = cm.registry().memory_plans()
    for label in info["labels"]:
        assert label in plans, f"no memory plan for warmed {label}"
        assert plans[label]["total_bytes"] > 0
    model.fit(X, y, batch_size=32)
    dump = telemetry.prom_dump()
    label = info["labels"][0]
    line = next(l for l in dump.splitlines()
                if "memory_plan_total_bytes" in l and label in l)
    assert 'rank="0"' in line and 'world_size="1"' in line
    assert label in telemetry.plan_table()
