"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

Mirrors the reference's approach to distributed testing without a cluster
(SURVEY.md §4: dmlc_local.py multi-process on one machine) — here a single
process with 8 XLA host devices exercises every sharding/collective path.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force CPU: the session environment pins JAX_PLATFORMS=axon (the real TPU
# tunnel), which must stay reserved for bench runs — unit tests run on the
# 8-device virtual CPU mesh. sitecustomize imports jax before this file runs,
# so the env var alone is too late; update the live config too.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# This JAX build mirrors TPU MXU semantics even on CPU: under jit, f32
# matmul operands are truncated to bf16 at default precision. Numeric tests
# need exact f32 contractions; the framework itself leaves precision at the
# backend default (the TPU fast path).
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running tier "
        "(reference: tests/python/train + multi-node)")


@pytest.fixture(autouse=True)
def _seed():
    """Deterministic tests: reseed numpy and the framework PRNG per test."""
    np.random.seed(0)
    import mxnet_tpu as mx

    mx.random.seed(0)
    yield
