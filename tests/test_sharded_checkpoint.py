"""Sharded checkpoint/resume tests (reference capability: SURVEY.md §5
checkpoint tier 4 — trainer save/resume — rebuilt as Orbax-style sharded
pytree checkpoints that restore onto arbitrary mesh layouts)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.utils import (latest_step, load_sharded, save_sharded,
                             validate_step)


def _params(mesh):
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("dp"))
    rng = np.random.RandomState(0)
    return {
        "fc1_weight": jax.device_put(
            rng.randn(16, 8).astype(np.float32), row),
        "fc1_bias": jax.device_put(rng.randn(16).astype(np.float32), repl),
    }


def test_save_load_roundtrip_host(tmp_path):
    mesh = make_mesh(dp=8)
    params = _params(mesh)
    sym = mx.sym.FullyConnected(data=mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    save_sharded(tmp_path, 3, params, aux={"m": jnp.ones((2,))}, symbol=sym,
                 extra_meta={"epoch": 7})
    assert latest_step(tmp_path) == 3
    loaded, aux, symbol, meta, _ = load_sharded(tmp_path)
    assert meta["epoch"] == 7
    assert symbol.list_arguments() == sym.list_arguments()
    np.testing.assert_allclose(loaded["fc1_weight"],
                               np.asarray(params["fc1_weight"]))
    np.testing.assert_allclose(aux["m"], np.ones((2,)))


def test_restore_onto_mesh(tmp_path):
    """Restore re-shards directly onto a (different) mesh layout."""
    mesh = make_mesh(dp=8)
    params = _params(mesh)
    save_sharded(tmp_path, 1, params)
    mesh2 = make_mesh(dp=2, tp=4)
    shardings = {"params": {
        "fc1_weight": NamedSharding(mesh2, P("tp", None)),
        "fc1_bias": NamedSharding(mesh2, P()),
    }}
    loaded, _, _, _, _ = load_sharded(tmp_path, shardings=shardings)
    w = loaded["fc1_weight"]
    assert isinstance(w, jax.Array)
    assert w.sharding.spec == P("tp", None)
    np.testing.assert_allclose(np.asarray(w), np.asarray(params["fc1_weight"]))


def test_multiple_steps_and_latest(tmp_path):
    mesh = make_mesh(dp=8)
    params = _params(mesh)
    for step in (1, 5, 10):
        save_sharded(tmp_path, step, params)
    assert latest_step(tmp_path) == 10
    p5, _, _, _, _ = load_sharded(tmp_path, step=5)
    np.testing.assert_allclose(p5["fc1_bias"],
                               np.asarray(params["fc1_bias"]))


def test_latest_step_skips_torn_checkpoints(tmp_path):
    """Regression (ISSUE 2 satellite): latest_step used to return the max
    numeric dir even when its write was torn; every torn shape must now be
    skipped in favor of the newest VALID step."""
    mesh = make_mesh(dp=8)
    params = _params(mesh)
    save_sharded(tmp_path, 1, params)
    save_sharded(tmp_path, 2, params)
    assert latest_step(tmp_path) == 2

    # torn shape 1: a bare numeric dir (killed before any state landed)
    os.makedirs(tmp_path / "7")
    # torn shape 2: state dir present, metadata truncated mid-json-write
    os.makedirs(tmp_path / "8" / "state")
    (tmp_path / "8" / "metadata.json").write_text('{"step": ')
    # torn shape 3: manifest lists a file whose bytes never fully landed
    save_sharded(tmp_path, 9, params)
    victim = None
    for dirpath, _d, files in os.walk(tmp_path / "9" / "state"):
        for f in sorted(files):
            full = os.path.join(dirpath, f)
            if os.path.getsize(full) > 0:
                victim = full
                break
        if victim:
            break
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) - 1)

    assert not validate_step(tmp_path, 8)
    assert not validate_step(tmp_path, 9)
    assert validate_step(tmp_path, 2)
    assert latest_step(tmp_path) == 2  # all three torn steps skipped
    # and loading the latest actually works
    loaded, _, _, _, _ = load_sharded(tmp_path)
    np.testing.assert_allclose(loaded["fc1_bias"],
                               np.asarray(params["fc1_bias"]))


def test_save_is_atomic_no_tmp_left_behind(tmp_path):
    """The commit point is one rename: after a successful save there is no
    temp dir, and the manifest covers every state file with its CRC."""
    import json

    mesh = make_mesh(dp=8)
    save_sharded(tmp_path, 4, _params(mesh))
    assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp.")]
    with open(tmp_path / "4" / "manifest.json") as f:
        manifest = json.load(f)
    assert manifest["step"] == 4 and manifest["files"]
    for rel, info in manifest["files"].items():
        assert os.path.getsize(tmp_path / "4" / rel) == info["size"]


def test_crash_and_relaunch_resumes(tmp_path):
    """The recovery story end-to-end (SURVEY §5: checkpoint/restore +
    re-launch IS the failure-recovery design, matching TPU practice): a
    training process hard-killed mid-run (os._exit, no cleanup) is
    relaunched and auto-resumes from the newest complete sharded step."""
    import subprocess
    import sys as _sys

    script = os.path.join(os.path.dirname(__file__), "..", "examples",
                          "distributed", "crash_resume_train.py")
    d = str(tmp_path / "ckpt")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXTPU_CRASH_AFTER_EPOCH="2")
    env.pop("XLA_FLAGS", None)
    r1 = subprocess.run([_sys.executable, script, d], env=env,
                        capture_output=True, text=True, timeout=300)
    assert r1.returncode == 137, (r1.returncode, r1.stderr[-1000:])
    assert "simulated preemption" in r1.stdout
    assert latest_step(d) == 2  # epoch 2's checkpoint survived the kill

    env.pop("MXTPU_CRASH_AFTER_EPOCH")
    r2 = subprocess.run([_sys.executable, script, d], env=env,
                        capture_output=True, text=True, timeout=300)
    assert r2.returncode == 0, (r2.stdout + r2.stderr)[-1500:]
    assert "resumed from epoch 2" in r2.stdout, r2.stdout
    assert latest_step(d) == 5


def test_fit_sharded_checkpoint_and_resume(tmp_path):
    """fit(sharded_checkpoint_dir=...) writes per-epoch sharded state and a
    fresh fit() on the same dir resumes from the newest step."""
    from mxnet_tpu.models import mlp

    rng = np.random.RandomState(0)
    X = rng.rand(64, 8).astype(np.float32)
    y = (X.sum(axis=1) > 4).astype(np.float32)
    d = str(tmp_path / "ckpt")

    m1 = mx.FeedForward(mlp(num_classes=2, hidden=(16,)), num_epoch=2,
                        optimizer="sgd", learning_rate=0.1,
                        initializer=mx.init.Xavier())
    m1.fit(X, y, batch_size=16, sharded_checkpoint_dir=d)
    assert latest_step(d) == 2

    m2 = mx.FeedForward(mlp(num_classes=2, hidden=(16,)), num_epoch=4,
                        optimizer="sgd", learning_rate=0.1,
                        initializer=mx.init.Xavier())
    m2.fit(X, y, batch_size=16, sharded_checkpoint_dir=d)
    # resumed at epoch 2, trained to 4, checkpoints advanced
    assert m2.begin_epoch == 2
    assert latest_step(d) == 4
    _, _, symbol, meta, _ = load_sharded(d, step=2)
    assert meta["epoch"] == 2 and symbol is not None
