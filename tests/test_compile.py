"""Compile-management subsystem tests (ISSUE 3 acceptance): zero-recompile
steady state with the tracker ARMED (including the tail batch), per-bucket
exactly-one-compile, persistent-cache reuse across a subprocess, pad-policy
numerical parity vs unpadded, AOT warmup, and the registry counters."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import DataBatch, DataIter
from mxnet_tpu.ndarray import NDArray
from mxnet_tpu.utils import compile as cm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(num_classes=2):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=16)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=num_classes)
    return sym.SoftmaxOutput(data=net, name="softmax")


def _blobs(n=100, dim=10, seed=0):
    rng = np.random.RandomState(seed)
    X = np.concatenate([rng.randn(n // 2, dim) + 1,
                        rng.randn(n - n // 2, dim) - 1]).astype(np.float32)
    y = np.concatenate([np.zeros(n // 2), np.ones(n - n // 2)]).astype(
        np.float32)
    order = rng.permutation(n)
    return X[order], y[order]


class ShortTailIter(DataIter):
    """Yields full batches then one genuinely SHORT tail batch (the shape
    that silently compiles a second program without a pad policy)."""

    def __init__(self, X, y, batch_size):
        super().__init__()
        self.X, self.y = X, y
        self.batch_size = batch_size
        self.reset()

    def reset(self):
        self._i = 0

    @property
    def provide_data(self):
        return [("data", (self.batch_size,) + self.X.shape[1:])]

    @property
    def provide_label(self):
        return [("softmax_label", (self.batch_size,))]

    def next(self):
        s = self._i * self.batch_size
        if s >= len(self.X):
            raise StopIteration
        e = min(s + self.batch_size, len(self.X))
        self._i += 1
        return DataBatch([NDArray(self.X[s:e])], [NDArray(self.y[s:e])],
                         pad=0)


# -- PadPolicy units -----------------------------------------------------------

def test_pad_policy_round_rows_and_lengths():
    bucket = cm.PadPolicy("bucket")
    assert bucket.round_rows(20, 40) == 40
    assert bucket.round_rows(40, 40) == 40
    assert bucket.round_rows(50, 40) == 50  # never truncates
    assert bucket.round_length(5, [4, 8, 16]) == 8
    assert bucket.round_length(17, [4, 8, 16]) is None  # too long -> dropped

    pow2 = cm.PadPolicy("pow2")
    assert pow2.round_rows(20, 40) == 32
    assert pow2.round_rows(33, 40) == 40  # clamped to the batch size
    assert pow2.round_length(5) == 8
    assert pow2.round_length(8) == 8
    assert pow2.round_length(9, [4, 8, 16]) == 16
    assert pow2.round_length(30, [4, 8, 16]) is None

    with pytest.raises(mx.base.MXNetError):
        cm.PadPolicy("nope")


def test_pad_policy_resolve_and_env(monkeypatch):
    assert cm.PadPolicy.resolve(None) is None
    assert cm.PadPolicy.resolve(True).mode == "bucket"
    assert cm.PadPolicy.resolve("pow2").mode == "pow2"
    p = cm.PadPolicy("bucket")
    assert cm.PadPolicy.resolve(p) is p
    monkeypatch.setenv("MXNET_TPU_PAD_POLICY", "pow2")
    assert cm.PadPolicy.resolve(None).mode == "pow2"
    monkeypatch.setenv("MXNET_TPU_PAD_POLICY", "0")
    assert cm.PadPolicy.resolve(None) is None


def test_pad_policy_pad_arrays():
    p = cm.PadPolicy("bucket")
    arrays = {"data": np.arange(12, dtype=np.float32).reshape(3, 4),
              "label": np.array([1.0, 2.0, 3.0], np.float32)}
    out, valid = p.pad_arrays(arrays, 5, pad=1)
    assert valid == 2  # 3 rows minus 1 iterator-reported wrap row
    assert out["data"].shape == (5, 4)
    np.testing.assert_array_equal(out["data"][3], out["data"][2])
    np.testing.assert_array_equal(out["label"], [1, 2, 3, 3, 3])
    # already full: unchanged, same objects
    same, valid2 = p.pad_arrays(arrays, 3)
    assert same is arrays and valid2 == 3


# -- tracked jit + registry ----------------------------------------------------

def test_tracked_jit_counters_and_aot():
    import jax
    import jax.numpy as jnp

    cm.reset_compile_stats()
    f = cm.tracked_jit(lambda x: (x * 2).sum(), label="unit:double")
    f(jnp.ones((8,)))           # miss (compiles)
    f(jnp.ones((8,)))           # hit
    f(jnp.ones((4,)))           # miss (new shape)
    stats = cm.compile_stats()["per_function"]["unit:double"]
    assert stats["misses"] == 2 and stats["hits"] == 1

    # AOT: precompile a third shape, then dispatch it — no jit-cache miss
    f.precompile(jax.ShapeDtypeStruct((2,), jnp.float32))
    assert f.aot_programs == 1
    out = f(jnp.ones((2,)))
    assert float(out) == 4.0
    stats = cm.compile_stats()["per_function"]["unit:double"]
    assert stats["misses"] == 2  # unchanged: the AOT executable served it
    assert stats["aot_hits"] == 1 and stats["precompiles"] == 1


def test_recompile_tracker_raises_when_armed():
    import jax.numpy as jnp

    f = cm.tracked_jit(lambda x: x + 1, label="unit:inc")
    f(jnp.ones((3,)))  # warm
    with cm.RecompileTracker(raise_on_recompile=True):
        f(jnp.ones((3,)))  # cached: fine
        with pytest.raises(cm.RecompileError):
            f(jnp.ones((5,)))  # new shape while armed
    # disarmed again: new shapes are fine
    f(jnp.ones((7,)))

    tr = cm.RecompileTracker().arm()
    f(jnp.ones((9,)))
    tr.disarm()
    assert len(tr.recompiles) == 1
    with pytest.raises(cm.RecompileError):
        tr.assert_no_recompiles()


def test_graph_fingerprint_tracks_fusion_flags(monkeypatch):
    net = _mlp()
    fp1 = cm.graph_fingerprint(net)
    assert fp1 == cm.graph_fingerprint(net)
    monkeypatch.setenv("MXNET_TPU_FUSE", "0")
    assert cm.graph_fingerprint(net) != fp1


# -- the armed steady-state invariant (acceptance criterion) -------------------

def test_fit_zero_recompiles_steady_state_with_tail_batch():
    """THE acceptance test: a steady-state epoch — including a genuinely
    short tail batch — performs ZERO tracked compiles once warm, enforced
    by an armed RecompileTracker that raises on violation."""
    X, y = _blobs(100)
    it = ShortTailIter(X, y, 40)  # 40 + 40 + 20-row tail
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=3,
                           learning_rate=0.5)
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()  # epoch 0 warmed every program; none may compile

    try:
        model.fit(it, batch_size=40, pad_policy="bucket",
                  epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    assert tracker.recompiles == []
    acc = (model.predict(X, batch_size=40).argmax(axis=1) == y).mean()
    assert acc > 0.9, acc


def test_fit_without_pad_policy_does_recompile_tail():
    """Control: the same short-tail epoch WITHOUT the policy compiles a
    second program for the odd shape (the bug the policy fixes)."""
    cm.reset_compile_stats()
    X, y = _blobs(100)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.5)
    model.fit(ShortTailIter(X, y, 40), batch_size=40)
    per = cm.compile_stats()["per_function"]
    train = [c for label, c in per.items() if label.startswith("train_step:")]
    assert train and train[0]["misses"] == 2  # 40-shape AND 20-shape


def test_pad_policy_numerical_parity_vs_unpadded():
    """Padded+masked tail batch == genuinely short tail batch, exactly:
    same parameter trajectory (masked loss heads inject zero gradient for
    pad rows), same final metric."""
    X, y = _blobs(100, seed=3)

    def train(pad_policy):
        np.random.seed(0)
        mx.random.seed(0)
        model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                               learning_rate=0.5,
                               initializer=mx.init.Xavier())
        model.fit(ShortTailIter(X, y, 40), batch_size=40,
                  pad_policy=pad_policy)
        return model

    a = train("bucket")
    b = train(None)
    for k in a.arg_params:
        np.testing.assert_allclose(
            a.arg_params[k].asnumpy(), b.arg_params[k].asnumpy(),
            rtol=1e-5, atol=1e-6, err_msg=k)
    sa = a.score(mx.io.NDArrayIter(X, y, batch_size=50))
    sb = b.score(mx.io.NDArrayIter(X, y, batch_size=50))
    assert abs(sa - sb) < 1e-6


def test_masked_loss_grads_match_unpadded():
    """Direct gradient check: grads from a padded batch with a validity
    mask equal grads from the unpadded batch, for every maskable loss head."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.executor import _build_graph_fn

    rng = np.random.RandomState(0)
    for head in ("SoftmaxOutput", "LinearRegressionOutput",
                 "MAERegressionOutput", "LogisticRegressionOutput"):
        data = sym.Variable("data")
        net = sym.FullyConnected(data=data, name="fc", num_hidden=2)
        net = getattr(sym, head)(data=net, name="out")
        fn = _build_graph_fn(net, is_train=True)
        w = jnp.asarray(rng.randn(2, 6).astype(np.float32))
        b = jnp.asarray(np.zeros(2, np.float32))
        x4 = jnp.asarray(rng.randn(4, 6).astype(np.float32))
        lab4 = jnp.asarray(rng.randint(0, 2, (4, 2)).astype(np.float32))
        if head == "SoftmaxOutput":
            lab4 = jnp.asarray(rng.randint(0, 2, (4,)).astype(np.float32))
        zero = jnp.zeros((2,), jnp.uint32)

        def loss(w, b, x, lab, mask=None):
            args = {"data": x, "fc_weight": w, "fc_bias": b,
                    "out_label": lab}
            outs, _ = fn(args, {}, zero, mask)
            return sum(jnp.sum(o) for o in outs)

        g_ref = jax.grad(loss, argnums=(0, 1))(w, b, x4, lab4)
        # pad to 8 rows (repeat last) + mask out the pad
        x8 = jnp.concatenate([x4, jnp.tile(x4[-1:], (4,) + (1,) * (x4.ndim - 1))])
        lab8 = jnp.concatenate([lab4, jnp.tile(lab4[-1:],
                                               (4,) + (1,) * (lab4.ndim - 1))])
        mask = jnp.asarray([1, 1, 1, 1, 0, 0, 0, 0], jnp.float32)
        g_pad = jax.grad(loss, argnums=(0, 1))(w, b, x8, lab8, mask)
        for gr, gp in zip(g_ref, g_pad):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gp),
                                       rtol=1e-5, atol=1e-6, err_msg=head)


def test_fit_pad_policy_with_guards():
    """Pad policy composes with the resilience step guards (both extend the
    step signature; ordering must hold)."""
    X, y = _blobs(60)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                           learning_rate=0.5)
    model.fit(ShortTailIter(X, y, 25), batch_size=25, pad_policy="bucket",
              guards=True)
    acc = (model.predict(X, batch_size=25).argmax(axis=1) == y).mean()
    assert acc > 0.9, acc


# -- bucketing: per-bucket exactly-one-compile + pow2 assignment ---------------

def test_bucketing_exactly_one_compile_per_bucket():
    from mxnet_tpu.models import lstm_unroll

    def sentences(n=48):
        rng = np.random.RandomState(0)
        out = []
        for _ in range(n):
            length = int(rng.choice([3, 4, 6, 7]))
            start = int(rng.randint(1, 8))
            s = [start]
            for _ in range(length - 1):
                s.append(s[-1] % 7 + 1)
            out.append(s)
        return out

    def sym_gen(seq_len):
        return lstm_unroll(num_layers=1, seq_len=seq_len, input_size=8,
                           num_hidden=8, num_embed=4, num_label=8)

    cm.reset_compile_stats()
    init_states = [("l0_init_c", (8, 8)), ("l0_init_h", (8, 8))]
    it = mx.BucketSentenceIter(sentences(), buckets=[4, 8], batch_size=8,
                               init_states=init_states, shuffle=True)
    model = mx.BucketingFeedForward(sym_gen, default_bucket_key=8,
                                    num_epoch=3, optimizer="adam",
                                    learning_rate=0.02,
                                    initializer=mx.init.Xavier())
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    try:
        model.fit(it, batch_size=8, epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    per = cm.compile_stats()["per_function"]
    train = {label: c for label, c in per.items()
             if label.startswith("train_step:")}
    assert len(train) == 2, sorted(train)  # one program per bucket
    for label, c in train.items():
        assert c["misses"] == 1, (label, c)  # compiled exactly once
        assert c["programs"] == 1, (label, c)


def test_bucket_sentence_iter_pow2_policy():
    sents = [[1] * 3, [1] * 5, [1] * 9, [1] * 15, [1] * 16]
    it = mx.BucketSentenceIter(sents, buckets=None, batch_size=2,
                               pad_policy="pow2")
    assert it.buckets == [4, 8, 16]
    assert it.discarded == 0
    # smallest pow2 bucket >= each length
    sizes = {b: len(m) for b, m in it._data.items()}
    assert sizes == {4: 1, 8: 1, 16: 3}
    # explicit buckets still honored under pow2 (clamped into the list)
    it2 = mx.BucketSentenceIter(sents, buckets=[4, 16], batch_size=2,
                                pad_policy="pow2")
    assert {b: len(m) for b, m in it2._data.items()} == {4: 1, 16: 4}
    # without a policy, buckets=None is an error
    with pytest.raises(ValueError):
        mx.BucketSentenceIter(sents, buckets=None, batch_size=2)


# -- AOT warmup ----------------------------------------------------------------

def test_feedforward_precompile_then_fit_no_compiles():
    X, y = _blobs(80)
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                           learning_rate=0.5)
    out = model.precompile(data_shapes={"data": (40, 10)},
                           label_shapes={"softmax_label": (40,)})
    assert out["programs"] == 1
    with cm.RecompileTracker(raise_on_recompile=True):
        model.fit(X, y, batch_size=40)
    acc = (model.predict(X, batch_size=40).argmax(axis=1) == y).mean()
    assert acc > 0.9, acc


def test_bucketing_precompile_from_iterator():
    from mxnet_tpu.models import lstm_unroll

    sents = [[1, 2, 3], [2, 3, 4, 5, 6, 7], [3, 4], [1] * 7] * 4

    def sym_gen(seq_len):
        return lstm_unroll(num_layers=1, seq_len=seq_len, input_size=8,
                           num_hidden=8, num_embed=4, num_label=8)

    init_states = [("l0_init_c", (4, 8)), ("l0_init_h", (4, 8))]
    it = mx.BucketSentenceIter(sents, buckets=[4, 8], batch_size=4,
                               init_states=init_states, shuffle=False)
    shapes = it.bucket_shapes()
    assert [b for b, _, _ in shapes] == [4, 8]
    assert shapes[0][1]["t0_data"] == ((4,), np.int32)
    assert shapes[0][1]["l0_init_c"] == (4, 8)
    model = mx.BucketingFeedForward(sym_gen, default_bucket_key=8,
                                    num_epoch=1, learning_rate=0.1,
                                    initializer=mx.init.Xavier())
    out = model.precompile(data=it)
    assert out["programs"] == 2
    with cm.RecompileTracker(raise_on_recompile=True):
        model.fit(it, batch_size=4)


def test_executor_precompile():
    cm.reset_compile_stats()
    net = _mlp()
    exe = net.simple_bind(mx.cpu(), data=(4, 10), softmax_label=(4,))
    secs = exe.precompile(is_train=False)
    assert secs >= 0.0
    exe.arg_dict["data"][:] = np.random.randn(4, 10)
    exe.arg_dict["fc1_weight"][:] = np.random.uniform(-1, 1, (16, 10))
    exe.arg_dict["fc2_weight"][:] = np.random.uniform(-1, 1, (2, 16))
    with cm.RecompileTracker(raise_on_recompile=True):
        exe.forward()
    label = exe._label("fwd_eval")
    stats = cm.compile_stats()["per_function"][label]
    assert stats["precompiles"] == 1 and stats["aot_hits"] == 1
    # train path (residual capture) precompiles too, then backward works
    exe.precompile(is_train=True)
    with cm.RecompileTracker(raise_on_recompile=True):
        exe.forward(is_train=True)
    exe.backward()
    assert exe.grad_dict["fc1_weight"].asnumpy().any()


# -- persistent cache across processes (acceptance criterion) ------------------

_CHILD = r"""
import json, os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.utils import compile as cm

data = sym.Variable("data")
net = sym.FullyConnected(data=data, name="fc1", num_hidden=37)
net = sym.Activation(data=net, name="r", act_type="relu")
net = sym.FullyConnected(data=net, name="fc2", num_hidden=2)
net = sym.SoftmaxOutput(data=net, name="softmax")
X = np.random.RandomState(0).randn(64, 11).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
model = mx.FeedForward(net, ctx=mx.cpu(), num_epoch=1, learning_rate=0.1)
model.fit(X, y, batch_size=32)
s = cm.compile_stats()
print(json.dumps({"cache_dir": cm.persistent_cache_dir(),
                  "compiles": s["compiles"],
                  "persistent_hits": s["persistent_cache_hits"],
                  "saved_s": s["persistent_cache_saved_seconds"]}))
"""


def test_persistent_cache_reused_across_subprocess(tmp_path):
    cache = str(tmp_path / "xla_cache")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "MXNET_TPU_COMPILE_CACHE": cache,
           "MXNET_TPU_COMPILE_CACHE_MIN_SEC": "0"}

    def run():
        r = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                           capture_output=True, text=True, cwd=REPO,
                           timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        return json.loads(r.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["cache_dir"] == cache  # env wiring reached jax config
    entries = [f for f in os.listdir(cache) if f.endswith("-cache")]
    assert entries, "cold run wrote nothing to the persistent cache"
    warm = run()
    # the warm process deserialized executables instead of compiling
    assert warm["persistent_hits"] > 0
    assert warm["persistent_hits"] >= cold["persistent_hits"]


def test_masked_device_metrics_multi_position_labels():
    """(batch, T) labels ravel to batch*T rows inside device_update; the
    (batch,) validity mask must expand per position (regression: the mask
    broadcast against the flattened rows raised a shape error)."""
    import jax
    import jax.numpy as jnp

    # batch=2 rows x T=3 positions, flattened; row 2 is padding
    labels = jnp.asarray([0, 1, 2, 3, 3, 3], jnp.float32)
    preds = jax.nn.one_hot(jnp.asarray([0, 1, 0, 2, 2, 2]), 8,
                           dtype=jnp.float32) * 0.9 + 0.0125
    valid = jnp.asarray([1.0, 0.0])
    for name in ("accuracy", "perplexity", "ce", "top_k_accuracy"):
        masked = mx.metric.create(name)
        state = masked.device_update(masked.device_init(), [labels], [preds],
                                     valid=valid)
        masked.absorb_device_state(state)
        ref = mx.metric.create(name)
        state = ref.device_update(ref.device_init(), [labels[:3]],
                                  [preds[:3]])
        ref.absorb_device_state(state)
        assert abs(masked.get()[1] - ref.get()[1]) < 1e-5, name


# -- surfacing: profiler + monitor ---------------------------------------------

def test_profile_step_reports_compiles():
    import jax.numpy as jnp

    from mxnet_tpu.utils import profiler

    f = cm.tracked_jit(lambda x: jnp.tanh(x).sum(), label="unit:profiled")
    x = jnp.asarray(np.random.randn(32, 32).astype(np.float32))
    stats, log_dir, delta = profiler.profile_step(f, x, iters=2,
                                                  return_compile=True)
    assert os.path.isdir(log_dir)
    assert {"compiles", "compile_seconds", "hits", "misses"} <= set(delta)
    report = profiler.compile_report()
    assert "unit:profiled" in report


def test_monitor_collects_compile_stats():
    import jax.numpy as jnp

    mon = mx.Monitor(interval=1, track_compiles=True)
    rows = mon.collect_compiles()  # snapshot baseline
    f = cm.tracked_jit(lambda x: x * 3, label="unit:mon")
    f(jnp.ones((6,)))
    rows = mon.collect_compiles()
    by_name = {name: v for _, name, v in rows}
    assert by_name["compile/jit_misses"] >= 1
    assert any(name == "compile/unit:mon" for _, name, _ in rows)
    # a tracker wired to the monitor mirrors recompiles into its stat rows
    # (drained at the next collection, surviving toc()'s queue rebind)
    tr = cm.RecompileTracker(monitor=mon).arm()
    f(jnp.ones((9,)))
    tr.disarm()
    rows = mon.collect_compiles()
    assert any(str(name).startswith("recompile/unit:mon")
               for _, name, _ in rows)
    assert mon._recompile_events == []  # drained, not duplicated
