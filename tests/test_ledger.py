"""Cross-run telemetry ledger acceptance (ISSUE 20).

Covers: run_id identity (minted per hub, stamped on every event and
flight dump, fresh across reset), the RunRecord append/read roundtrip
through the atomic CRC'd store, concurrent multi-process appends,
corrupt-record skip-not-fatal reads, the trend gate (exit 3 on an
injected regression through the CLI), knob attribution across record
pairs differing in exactly one knob, the FleetController warm-start
sensor picking the historically best tier, bench publishing through the
one writer (BENCH_LEDGER_r20.json), and the e2e acceptance: two dp-8
fits differing only in compression tier land as two comparable records
while the armed zero-recompile epoch stays green with the ledger on.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import ledger
from mxnet_tpu.telemetry.__main__ import main as cli
from mxnet_tpu.utils import compile as cm


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    # the store must stay off unless a test points it somewhere; reset
    # gives each test its own hub (and so its own run_id)
    monkeypatch.delenv("MXNET_TPU_LEDGER_DIR", raising=False)
    telemetry.reset()
    yield


def _mlp():
    data = mx.sym.Variable("data")
    return mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        data, name="fc", num_hidden=4), name="softmax")


def _digits(n=64, dim=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, dim).astype(np.float32),
            rng.randint(0, classes, (n,)).astype(np.float32))


def _mk_record(directory, fingerprint="fp-test", p50=10.0, world=8,
               knobs=None, completed=True, **outcomes):
    """Hand-build + append one record through the real writer (tests are
    MX316-exempt, but going through distill/append keeps the schema
    honest)."""
    rec = ledger.distill("fit", fingerprint=fingerprint, world_size=world,
                         knobs=knobs or {}, completed=completed,
                         since_ts=float("inf"))
    rec["outcomes"]["step_ms_p50"] = p50
    rec["outcomes"].update(outcomes)
    ledger.append_record(rec, directory=directory)
    return rec


# -- run identity --------------------------------------------------------------

def test_run_id_minted_stamped_and_reset():
    h = telemetry.hub()
    assert isinstance(h.run_id, str) and len(h.run_id) == 12
    h.emit("retry", op="push", attempt=1)
    ev = h.events(kind="retry")[-1]
    assert ev["run_id"] == h.run_id
    first = h.run_id
    telemetry.reset()
    assert telemetry.hub().run_id != first  # a new hub is a new run


def test_flight_dump_carries_run_id(tmp_path):
    path = str(tmp_path / "flight.json")
    telemetry.flight.dump(path, reason="test")
    ok, payload = telemetry.validate_flight(path)
    assert ok and payload["run_id"] == telemetry.hub().run_id


# -- store: append/read/corruption/concurrency ---------------------------------

def test_append_read_roundtrip(tmp_path):
    d = str(tmp_path / "ledger")
    h = telemetry.hub()
    t0 = h.now()
    for i in range(5):  # deterministic percentile fodder
        h.emit("span", name="step", epoch=0, step=i, dur_ms=10.0 + i)
    rec = ledger.distill("fit", fingerprint="fp-abc", world_size=8,
                         knobs={"compression": "int8"}, since_ts=t0)
    path = ledger.append_record(rec, directory=d)
    assert os.path.exists(path) and os.path.exists(path + ".crc32")
    # the append announced itself on the hub
    ann = h.events(kind="run_summary")[-1]
    assert ann["record_id"] == rec["record_id"]
    assert ann["fingerprint"] == "fp-abc"

    rows = ledger.read_ledger(d)
    assert len(rows) == 1
    r = rows[0]
    assert r["ledger_schema"] == ledger.LEDGER_SCHEMA
    assert r["run_id"] == h.run_id
    assert r["kind"] == "fit" and r["world_size"] == 8
    assert r["knobs"]["compression"] == "int8"
    # absent knobs read as None so compare() can pair across versions
    assert r["knobs"]["fused_adam"] is None
    assert r["outcomes"]["steps"] == 5
    assert r["outcomes"]["step_ms_p50"] == 12.0


def test_record_run_noop_without_dir(tmp_path):
    assert ledger.record_run("fit", fingerprint="fp") is None
    assert list(tmp_path.iterdir()) == []


def test_corrupt_record_skipped_not_fatal(tmp_path):
    d = str(tmp_path / "ledger")
    good = _mk_record(d, p50=10.0)
    bad = _mk_record(d, p50=11.0)
    # bit-flip the second record's body: CRC sidecar must fail it closed
    path = ledger.read_ledger(d)[1]["_path"]
    with open(path, "r+") as f:
        body = f.read()
        f.seek(0)
        f.write(body.replace("11.0", "99.0", 1))
        f.truncate()
    rows = ledger.read_ledger(d)
    assert [r["record_id"] for r in rows] == [good["record_id"]]
    # a torn (half-written) file without a parsable body skips too
    with open(os.path.join(d, "run-0000000000000-1-torn-001.json"),
              "w") as f:
        f.write('{"ledger_schema": 1, "record_')
    assert [r["record_id"] for r in ledger.read_ledger(d)] == \
        [good["record_id"]]
    del bad


def test_concurrent_multiprocess_appends(tmp_path):
    """One file per record through atomic_write: N processes appending
    at once never tear or drop a record."""
    d = str(tmp_path / "ledger")
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from mxnet_tpu.telemetry import ledger\n"
        "for i in range(4):\n"
        "    rec = ledger.distill('fit', fingerprint='fp-mp',\n"
        "                         world_size=8, since_ts=float('inf'))\n"
        "    rec['outcomes']['step_ms_p50'] = float(i)\n"
        f"    ledger.append_record(rec, directory={d!r})\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs = [subprocess.Popen([sys.executable, "-c", code], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE)
             for _ in range(3)]
    for p in procs:
        _, err = p.communicate(timeout=180)
        assert p.returncode == 0, err.decode()
    rows = ledger.read_ledger(d)
    assert len(rows) == 12  # 3 processes x 4 records, none torn
    assert len({r["record_id"] for r in rows}) == 12
    assert len({r["pid"] for r in rows}) == 3


# -- gates + attribution -------------------------------------------------------

def test_trend_gate_catches_injected_regression(tmp_path):
    d = str(tmp_path / "ledger")
    for p50 in (10.0, 10.2, 9.8, 10.1):
        _mk_record(d, p50=p50)
    _mk_record(d, p50=20.0)  # the injected regression
    report = ledger.trend_gate(ledger.read_ledger(d),
                               metric="step_ms_p50", n=8, threshold=10.0)
    assert report["regressed"] is True
    assert report["baseline"] == 10.05  # median of the 4 predecessors
    assert report["latest"] == 20.0

    # CLI: trend exits 3 on the breach, 0 once the latest run recovers
    argv = ["ledger", "trend", "--dir", d, "--fingerprint", "fp-test",
            "--threshold", "10"]
    assert cli(argv) == 3
    _mk_record(d, p50=10.0)
    assert cli(argv) == 0
    # higher-is-better metrics gate in the other direction
    for mfu in (50.0, 50.0, 30.0):
        _mk_record(d, fingerprint="fp-mfu", p50=1.0, mfu_pct=mfu)
    assert cli(["ledger", "trend", "--dir", d, "--fingerprint", "fp-mfu",
                "--metric", "mfu_pct", "--threshold", "10"]) == 3


def test_trend_gate_needs_history(tmp_path):
    d = str(tmp_path / "ledger")
    _mk_record(d, p50=10.0)
    report = ledger.trend_gate(ledger.read_ledger(d))
    assert report["regressed"] is False and "reason" in report
    assert cli(["ledger", "trend", "--dir", d]) == 0


def test_compare_attributes_single_knob_delta(tmp_path):
    d = str(tmp_path / "ledger")
    base = {"compression": "fp32", "comm_kernels": False}
    _mk_record(d, p50=20.0, knobs=base, wire_bytes=1000.0)
    _mk_record(d, p50=8.0, knobs={**base, "compression": "int8"},
               wire_bytes=250.0)
    # two knobs differ -> NOT a comparable pair
    _mk_record(d, p50=7.0, knobs={"compression": "int8",
                                  "comm_kernels": True,
                                  "overlap_bytes": 1 << 20})
    rows = ledger.knob_attribution(ledger.read_ledger(d),
                                   metrics=("step_ms_p50", "wire_bytes"))
    assert len(rows) == 1
    row = rows[0]
    assert row["knob"] == "compression"
    assert (row["a_value"], row["b_value"]) == ("fp32", "int8")
    assert row["deltas"]["step_ms_p50"]["delta_pct"] == -60.0
    assert row["deltas"]["wire_bytes"]["delta_pct"] == -75.0
    assert cli(["ledger", "compare", "--dir", d]) == 0


def test_cli_list_show_and_errors(tmp_path):
    d = str(tmp_path / "ledger")
    assert cli(["ledger", "list", "--dir", d]) == 1          # empty store
    assert cli(["ledger", "list"]) == 2                      # no dir at all
    rec = _mk_record(d, p50=10.0)
    assert cli(["ledger", "list", "--dir", d]) == 0
    assert cli(["ledger", "show", rec["record_id"], "--dir", d]) == 0
    # prefix match on the shared run_id resolves too
    assert cli(["ledger", "show", rec["run_id"][:6], "--dir", d]) == 0
    assert cli(["ledger", "show", "nope", "--dir", d]) == 1
    assert cli(["ledger", "show", "--dir", d]) == 2          # missing arg


# -- controller warm start -----------------------------------------------------

def test_warm_start_picks_historically_best_tier(tmp_path, monkeypatch):
    from mxnet_tpu.resilience.controller import FleetController

    d = str(tmp_path / "ledger")
    for mode, p50 in (("fp32", 20.0), ("int8", 8.0), ("bf16", 12.0)):
        _mk_record(d, fingerprint="model-a", p50=p50,
                   knobs={"compression": mode,
                          "overlap_bytes": 4 << 20 if mode == "int8"
                          else None})
    # an incomplete (crashed) run with a better number must NOT win
    _mk_record(d, fingerprint="model-a", p50=1.0, completed=False,
               knobs={"compression": "twobit"})
    monkeypatch.setenv("MXNET_TPU_LEDGER_DIR", d)

    hist = ledger.warm_start_tier("model-a", 8)
    assert hist["mode"] == "int8"
    assert hist["bucket_bytes"] == 4 << 20
    assert hist["runs"] == 3  # completed runs only

    ctl = FleetController(dry_run=True)
    ctl.bind(model_key="model-a", world_size=8, comm_mode="none",
             can_retier=True)
    try:
        assert ctl._tier_cache[("model-a", 8)] == "int8"
        warm = [dec for dec in ctl.decisions
                if dec["outcome"] == "warm_start"]
        assert len(warm) == 1 and warm[0]["mode"] == "int8"
    finally:
        ctl.unbind()
    # no history for this shape -> no seed, no decision
    ctl2 = FleetController(dry_run=True)
    ctl2.bind(model_key="model-b", world_size=8, comm_mode="none",
              can_retier=True)
    try:
        assert ("model-b", 8) not in ctl2._tier_cache
        assert not [dec for dec in ctl2.decisions
                    if dec["outcome"] == "warm_start"]
    finally:
        ctl2.unbind()


# -- bench publishing ----------------------------------------------------------

def test_publish_bench_full_and_smoke(tmp_path, monkeypatch):
    d = str(tmp_path / "ledger")
    bench_dir = str(tmp_path / "bench")
    os.makedirs(bench_dir)
    monkeypatch.setenv("MXNET_TPU_LEDGER_DIR", d)
    result = {"metric": "widget_bench_ms", "value": 3.5, "unit": "ms",
              "vs_baseline": 1.2, "detail": {"x": 1}}
    out = ledger.publish_bench(result, filename="BENCH_WIDGET_r99.json",
                               bench_dir=bench_dir)
    assert json.load(open(out["bench_path"]))["value"] == 3.5
    assert out["record"]["kind"] == "bench"
    assert out["record"]["outcomes"]["metric"] == "widget_bench_ms"
    assert out["ledger_path"] is not None
    combined = json.load(open(out["bench_ledger_path"]))
    assert os.path.dirname(out["bench_ledger_path"]) == bench_dir
    assert combined["records"][-1]["outcomes"]["value"] == 3.5

    # smoke: no per-bench artifact; the trajectory regenerates into the
    # ledger dir (so CI gating can still read it) and marks the record
    out2 = ledger.publish_bench({"metric": "widget_bench_ms",
                                 "value": 4.0, "unit": "ms"},
                                filename="BENCH_WIDGET_r99.json",
                                bench_dir=bench_dir, smoke=True)
    assert out2["bench_path"] is None
    assert os.path.dirname(out2["bench_ledger_path"]) == d
    assert out2["record"]["outcomes"]["smoke"] is True
    rows = [r for r in ledger.read_ledger(d) if r["kind"] == "bench"]
    assert len(rows) == 2


# -- e2e acceptance ------------------------------------------------------------

def test_e2e_two_fits_differing_only_in_tier(tmp_path, monkeypatch):
    """Two dp-8 fits, identical but for the compression tier, with the
    ledger armed: two complete records land, compare() attributes the
    wire-byte delta to the tier knob, and the armed zero-recompile epoch
    stays green — the ledger distills at run END, off the step path."""
    d = str(tmp_path / "ledger")
    monkeypatch.setenv("MXNET_TPU_LEDGER_DIR", d)
    X, y = _digits()
    ctx = [mx.cpu(i) for i in range(8)]
    for tier in ("int8", "fp16"):
        # the invariant is per-fit: each tier is its own program, so the
        # tracker arms after the fit's first epoch and disarms at its end
        tracker = cm.RecompileTracker(raise_on_recompile=True)

        def arm_after_first(epoch, *_):
            if epoch == 0:
                tracker.arm()

        try:
            model = mx.FeedForward(_mlp(), ctx=ctx, num_epoch=2,
                                   learning_rate=0.1)
            model.fit(X, y, batch_size=16, compression=tier,
                      telemetry=True, epoch_end_callback=arm_after_first)
        finally:
            tracker.disarm()
        assert tracker.recompiles == []

    rows = [r for r in ledger.read_ledger(d) if r["kind"] == "fit"]
    assert len(rows) == 2
    assert all(r["completed"] and r["world_size"] == 8 for r in rows)
    assert rows[0]["fingerprint"] == rows[1]["fingerprint"]
    assert {r["knobs"]["compression"] for r in rows} == {"int8", "bf16"}
    assert all(r["outcomes"]["steps"] == 8 for r in rows)
    assert all((r["outcomes"]["wire_bytes"] or 0) > 0 for r in rows)
    # each tier's bytes are ITS plan's — a second fit must not retro-
    # price the first (the registry plan-overwrite hazard distill dodges
    # by pricing per-label step deltas at run end)
    by_tier = {r["knobs"]["compression"]: r for r in rows}
    assert by_tier["int8"]["outcomes"]["wire_bytes"] != \
        by_tier["bf16"]["outcomes"]["wire_bytes"]

    pairs = ledger.knob_attribution(rows)
    assert [p["knob"] for p in pairs] == ["compression"]
    assert pairs[0]["deltas"]["wire_bytes"]["delta_pct"] != 0

    assert cli(["ledger", "list", "--dir", d]) == 0
    assert cli(["ledger", "compare", "--dir", d]) == 0


def test_predict_lands_a_record(tmp_path, monkeypatch):
    d = str(tmp_path / "ledger")
    X, y = _digits()
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                           learning_rate=0.1)
    model.fit(X, y, batch_size=16)
    monkeypatch.setenv("MXNET_TPU_LEDGER_DIR", d)
    model.predict(X, batch_size=16, telemetry=True)
    rows = ledger.read_ledger(d)
    assert [r["kind"] for r in rows] == ["predict"]
    assert rows[0]["completed"] is True
    assert rows[0]["outcomes"]["steps"] == 4
    assert rows[0]["outcomes"]["step_ms_p50"] > 0


def test_failed_fit_records_incomplete(tmp_path, monkeypatch):
    d = str(tmp_path / "ledger")
    monkeypatch.setenv("MXNET_TPU_LEDGER_DIR", d)
    X, y = _digits()
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                           learning_rate=0.1)

    def boom(*_):
        raise RuntimeError("injected epoch-end failure")

    with pytest.raises(RuntimeError, match="injected"):
        model.fit(X, y, batch_size=16, epoch_end_callback=boom)
    rows = ledger.read_ledger(d)
    assert len(rows) == 1 and rows[0]["completed"] is False
