"""Pallas flash-attention kernel vs the dense reference (interpret mode on
CPU — the same kernel code that compiles for TPU runs here interpreted).

Mirrors the reference's cpu-vs-gpu consistency pattern
(tests/python/gpu/test_operator_gpu.py: same test, different context)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops.pallas import flash_attention
from mxnet_tpu.parallel.sequence import attention_reference


def _rand_qkv(b, h, s, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.5)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 2, 64, 32), (2, 1, 128, 64)])
def test_flash_forward_matches_dense(causal, shape):
    b, h, s, d = shape
    q, k, v = _rand_qkv(b, h, s, d)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_forward_unpadded_tail():
    # seq not a multiple of the block: padding + key masking path
    q, k, v = _rand_qkv(1, 2, 48, 24, seed=3)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_dense(causal):
    q, k, v = _rand_qkv(1, 2, 64, 32, seed=1)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(o * o)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_under_jit_and_grad():
    q, k, v = _rand_qkv(2, 2, 32, 16, seed=2)

    @jax.jit
    def step(q, k, v):
        return jax.value_and_grad(
            lambda q: jnp.sum(flash_attention(q, k, v, causal=True,
                                              block_q=16, block_k=16))
        )(q)

    loss, dq = step(q, k, v)
    assert np.isfinite(float(loss))
    assert dq.shape == q.shape
