"""Comm/compute overlap scheduler tests (ISSUE 7).

Covers: the OverlapConfig knob, reverse-topological bucket scheduling
from the Symbol graph, per-bucket wire plans summing EXACTLY to the
fused plan, the overlapped in-jit sync (correctness + per-bucket error
feedback + independent HLO collective pairs), fit(overlap=...)
convergence parity vs the fused single bucket (int8 + twobit) with the
armed zero-recompile steady state, per-bucket EF-residual checkpoint/
resume round-trip + invalidation on a bucket-plan change, the
stale-sync AsyncKVStore pipeline (one-round staleness + flush), and the
satellites: axis_size==1 short-circuit (0-byte plan), symmetric
HostCodec wire accounting, GradBucketer.from_layout exact rebuild.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import comm
from mxnet_tpu import parallel as par
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.compat import shard_map
from mxnet_tpu.utils import compile as cm


def _mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    return par.make_mesh(dp=8, devices=jax.devices()[:8])


def _ctx8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    return [mx.cpu(i) for i in range(8)]


def _mlp(hidden=64, num_classes=2):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=hidden)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=num_classes)
    return sym.SoftmaxOutput(data=net, name="softmax")


def _blobs(n=160, dim=10, seed=0):
    rng = np.random.RandomState(seed)
    X = np.concatenate([rng.randn(n // 2, dim) + 1,
                        rng.randn(n - n // 2, dim) - 1]).astype(np.float32)
    y = np.concatenate([np.zeros(n // 2), np.ones(n - n // 2)]).astype(
        np.float32)
    order = rng.permutation(n)
    return X[order], y[order]


# -- config + schedule planning ------------------------------------------------

def test_overlap_config_resolve(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_COMM_OVERLAP", raising=False)
    assert comm.OverlapConfig.resolve(None) is None
    assert comm.OverlapConfig.resolve(False) is None
    cfg = comm.OverlapConfig.resolve(True)
    assert cfg.bucket_bytes == comm.DEFAULT_BUCKET_BYTES
    assert comm.OverlapConfig.resolve(1 << 20).bucket_bytes == 1 << 20
    assert comm.OverlapConfig.resolve(cfg) is cfg
    monkeypatch.setenv("MXNET_TPU_COMM_OVERLAP", "1")
    assert comm.OverlapConfig.resolve(None).bucket_bytes == \
        comm.DEFAULT_BUCKET_BYTES
    monkeypatch.setenv("MXNET_TPU_COMM_OVERLAP", "65536")
    assert comm.OverlapConfig.resolve(None).bucket_bytes == 65536
    with pytest.raises(MXNetError):
        comm.OverlapConfig.resolve("garbage")
    with pytest.raises(MXNetError):
        comm.OverlapConfig(0)


def test_reverse_topo_param_order():
    """Last layers first: fc2's params (consumed latest in the forward
    graph) lead the schedule — backward produces their gradients first."""
    net = _mlp()
    names = ["fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"]
    order = comm.reverse_topo_param_order(net, names)
    assert set(order) == set(names)
    assert order.index("fc2_weight") < order.index("fc1_weight")
    assert order.index("fc2_bias") < order.index("fc1_bias")
    # ties (a layer's weight+bias feed the same op) keep caller order
    assert order.index("fc2_weight") < order.index("fc2_bias")
    # names the graph never consumes go last
    order2 = comm.reverse_topo_param_order(net, names + ["orphan"])
    assert order2[-1] == "orphan"


def test_plan_overlap_buckets_and_layout_key():
    shapes = {"a": (1000,), "b": (1000,), "c": (1000,)}
    spec = comm.CompressionSpec.resolve("int8")
    plan = comm.plan_overlap(shapes, spec, 8, max_bytes=4096)  # 1024 f32 cap
    assert plan.num_buckets == 3
    assert sorted(plan.param_keys()) == ["a", "b", "c"]
    # without a symbol: sorted names, reversed (canonical on both sides
    # of a traced boundary)
    assert plan.buckets[0]["keys"] == ["c"]
    one = comm.plan_overlap(shapes, spec, 8, max_bytes=1 << 30)
    assert one.num_buckets == 1
    assert plan.layout_key() != one.layout_key()
    assert plan.layout_key() == comm.plan_overlap(
        shapes, spec, 8, max_bytes=4096).layout_key()
    assert plan.layout_key() != comm.plan_overlap(
        shapes, comm.CompressionSpec.resolve("twobit"), 8,
        max_bytes=4096).layout_key()
    with pytest.raises(MXNetError):
        comm.plan_overlap(shapes, None, 8)  # overlap needs compression


def test_overlap_plan_sums_exactly_to_fused():
    """ACCEPTANCE: per-bucket closed-form plans sum EXACTLY (==, not
    approx) to the fused single-bucket plan over the same padded total."""
    for mode in ("bf16", "int8", "twobit"):
        for elems in ([("b0", 4096)], [("b0", 1000), ("b1", 517)],
                      [("b0", 100), ("b1", 33), ("b2", 7), ("b3", 70000)]):
            p = comm.overlap_plan(elems, 8, mode)
            assert p["matches_fused"], (mode, elems, p)
            assert p["wire_bytes"] == p["fused_wire_bytes"]
            assert p["num_buckets"] == len(elems)
            assert p["padded_elements"] >= p["num_elements"]
    # fp32 (no compression) merges to the plain psum arithmetic
    p = comm.overlap_plan([("b0", 256), ("b1", 256)], 4, None)
    assert p["wire_bytes"] == comm.allreduce_plan(512, 4, None)["wire_bytes"]


def test_axis_size_one_short_circuit():
    """SATELLITE: the degenerate single-device mesh is a no-op sync — no
    encode/all_to_all/all_gather, no quantization error — and the wire
    plan prices it at 0 bytes."""
    tree = {"w": jnp.arange(7, dtype=jnp.float32)}
    out = comm.compressed_allreduce(tree, "int8", axis_size=1)
    assert out is tree  # identical object: nothing ran
    resid = jnp.zeros((1, 8))
    out2, r2 = comm.error_feedback_allreduce(tree, resid, "int8",
                                             axis_size=1)
    assert out2 is tree and r2 is resid
    for mode in ("bf16", "int8", "twobit"):
        assert comm.allreduce_plan(4096, 1, mode)["wire_bytes"] == 0.0
        assert comm.overlap_plan([("b0", 4096)], 1, mode)["wire_bytes"] \
            == 0.0


# -- the overlapped in-jit sync ------------------------------------------------

def _overlap_sync(mesh, grads_by_dev, mode, cap):
    """Run overlap_allreduce inside shard_map over dp-8; returns the
    synced tree (average semantics) on host."""
    spec = comm.CompressionSpec.resolve(mode)
    shapes = {k: tuple(v.shape[1:]) for k, v in grads_by_dev.items()}
    plan = comm.plan_overlap(shapes, spec, 8, max_bytes=cap)
    resid = comm.init_overlap_residuals(plan)

    def body(tree, *res):
        local = {k: v[0] for k, v in tree.items()}
        synced, new_res = comm.overlap_allreduce(
            local, res[0] if res else None, plan, average=True)
        out = {k: v[None] for k, v in synced.items()}
        return (out, new_res) if res else out

    has_ef = resid is not None
    in_specs = (P("dp"),) + ((P("dp"),) if has_ef else ())
    out_specs = (P("dp"), P("dp")) if has_ef else P("dp")
    fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    dev = {k: jax.device_put(v, NamedSharding(mesh, P("dp")))
           for k, v in grads_by_dev.items()}
    if has_ef:
        rdev = {k: jax.device_put(v, NamedSharding(mesh, P("dp")))
                for k, v in resid.items()}
        out, _ = fn(dev, rdev)
    else:
        out = fn(dev)
    return {k: np.asarray(v[0]) for k, v in out.items()}


def test_overlap_allreduce_matches_mean():
    mesh = _mesh8()
    rng = np.random.RandomState(3)
    grads = {"a": rng.randn(8, 500).astype(np.float32),
             "b": rng.randn(8, 40, 10).astype(np.float32),
             "c": rng.randn(8, 90).astype(np.float32)}
    want = {k: v.mean(axis=0) for k, v in grads.items()}
    for mode, tol in (("bf16", 2e-2), ("int8", 2e-2)):
        got = _overlap_sync(mesh, grads, mode, cap=1200 * 4)
        for k in want:
            err = np.abs(got[k] - want[k]).max()
            scale = np.abs(want[k]).max()
            assert err <= tol * max(scale, 1.0), (mode, k, err)


def test_overlap_allreduce_rejects_key_mismatch():
    spec = comm.CompressionSpec.resolve("int8")
    plan = comm.plan_overlap({"a": (8,)}, spec, 8)
    with pytest.raises(MXNetError, match="do not match the plan"):
        comm.overlap_allreduce({"b": jnp.zeros((8,))}, None, plan)


def test_residuals_match_plan_and_invalidation():
    spec = comm.CompressionSpec.resolve("int8")
    shapes = {"a": (1000,), "b": (600,)}
    plan = comm.plan_overlap(shapes, spec, 8, max_bytes=4096)
    res = comm.init_overlap_residuals(plan)
    assert comm.residuals_match_plan(res, plan)
    assert set(res) == {b["name"] for b in plan.buckets}
    # a cap change re-slabs the params -> saved ledgers are meaningless
    plan2 = comm.plan_overlap(shapes, spec, 8, max_bytes=1 << 30)
    assert not comm.residuals_match_plan(res, plan2)
    assert not comm.residuals_match_plan(None, plan)
    assert not comm.residuals_match_plan({"bucket0": res["bucket0"]}, plan)
    # bf16 needs no feedback: None is the only valid state
    bplan = comm.plan_overlap(shapes, "bf16", 8)
    assert comm.init_overlap_residuals(bplan) is None
    assert comm.residuals_match_plan(None, bplan)
    # fused path key: layout identity for the single-bucket residual
    k1 = comm.fused_layout_key(1600, spec, 8)
    assert k1 == comm.fused_layout_key(1600, spec, 8)
    assert k1 != comm.fused_layout_key(1600, spec, 4)
    assert k1 != comm.fused_layout_key(1601, spec, 8)


def test_overlap_hlo_has_independent_collective_pairs():
    """ACCEPTANCE: the compiled overlapped step contains one independent
    reduce-scatter/all-gather pair group PER BUCKET (>= 2), not the one
    fused pair."""
    mesh = _mesh8()
    rng = np.random.RandomState(0)
    params0 = {f"w{i}": (rng.randn(256, 256) * 0.05).astype(np.float32)
               for i in range(3)}
    num = sum(v.size for v in params0.values())

    def loss_fn(params, data):
        h = data["x"]
        for k in sorted(params):
            h = jnp.tanh(h @ params[k])
        return jnp.mean((h - data["y"]) ** 2)

    def update(params, s, grads):
        return {k: params[k] - 0.01 * grads[k] for k in params}, s

    x = rng.randn(64, 256).astype(np.float32)
    data = par.shard_batch({"x": x, "y": x}, mesh)
    spec = comm.CompressionSpec.resolve("int8")
    params = par.replicate_params(
        {k: jnp.asarray(v) for k, v in params0.items()}, mesh)

    def hlo_counts(step, call):
        hlo = step.lower(*call).compile().as_text()
        table = comm.hlo_collective_table(hlo, default_group_size=8)
        a2a = sum(r["count"] for r in table if "all-to-all" in r["op"])
        ag = sum(r["count"] for r in table if "all-gather" in r["op"])
        wire = sum(r["wire_bytes"] for r in table)
        return a2a, ag, wire

    step_f = par.make_data_parallel_step(loss_fn, update, mesh,
                                         donate=False, compression="int8")
    resid_f = jax.device_put(comm.init_error_feedback(params, spec, 8),
                             NamedSharding(mesh, P("dp")))
    f_a2a, f_ag, _ = hlo_counts(step_f, (params, {}, data, resid_f))

    cap = num * 4 // 3 + 4  # 3 slabs
    step_o = par.make_data_parallel_step(loss_fn, update, mesh,
                                         donate=False, compression="int8",
                                         overlap=cap)
    plan = comm.plan_overlap({k: v.shape for k, v in params0.items()},
                             spec, 8, max_bytes=cap)
    assert plan.num_buckets == 3
    resid_o = {k: jax.device_put(v, NamedSharding(mesh, P("dp")))
               for k, v in comm.init_overlap_residuals(plan).items()}
    o_a2a, o_ag, o_wire = hlo_counts(step_o, (params, {}, data, resid_o))
    # one pair group per bucket: the compiled op counts are the fused
    # counts multiplied by the bucket count — nothing re-fused them
    assert o_a2a == plan.num_buckets * f_a2a, (o_a2a, f_a2a)
    assert o_ag == plan.num_buckets * f_ag, (o_ag, f_ag)
    assert plan.num_buckets >= 2
    # and the compiled wire agrees with the closed-form per-bucket plan
    # (int8 payloads survive CPU lowering faithfully; the bf16 stage-2
    # all-gather upcasts on CPU, so compare the int8 stage-1 rows only)
    plan_a2a = sum(r["payload_bytes"] for r in plan.wire_plan()["collectives"]
                   if r["op"] == "all-to-all")
    hlo = step_o.lower(params, {}, data, resid_o).compile().as_text()
    hlo_a2a_payload = sum(
        r["payload_bytes"] for r in
        comm.hlo_collective_table(hlo, default_group_size=8)
        if "all-to-all" in r["op"])
    assert hlo_a2a_payload == pytest.approx(plan_a2a, rel=0.05)


# -- fit(overlap=...) ----------------------------------------------------------

def test_fit_overlap_convergence_parity_int8_and_twobit():
    """SATELLITE: overlap-mode convergence parity vs the fused single
    bucket for both lossy modes (per-bucket EF residuals recover the
    quantization error exactly like the fused ledger does)."""
    X, y = _blobs(160)

    def train(compression, overlap):
        np.random.seed(0)
        mx.random.seed(0)
        model = mx.FeedForward(_mlp(), ctx=_ctx8(), num_epoch=5,
                               learning_rate=0.5,
                               initializer=mx.init.Xavier())
        model.fit(X, y, batch_size=32, compression=compression,
                  overlap=overlap)
        return (model.predict(X, batch_size=32).argmax(axis=1) == y).mean()

    comm.reset_comm_stats()
    for mode in ("int8", "twobit"):
        acc_fused = train(mode, None)
        acc_over = train(mode, 2048)  # small cap -> multiple buckets
        assert acc_fused > 0.9, (mode, acc_fused)
        assert abs(acc_over - acc_fused) < 0.05, (mode, acc_fused, acc_over)
    # the registered per-step plan is the per-bucket overlapped one and
    # its totals carry the exact fused arithmetic
    per = comm.comm_stats()["per_program"]
    over = [p for p in per.values() if p.get("num_buckets")]
    assert over and all(p["num_buckets"] >= 2 for p in over)
    assert all(p["matches_fused"] for p in over)


def test_fit_overlap_zero_recompiles_steady_state():
    """SATELLITE: a RecompileTracker-armed epoch with overlap= on stays
    at zero recompiles — per-bucket residual dicts thread through the
    donated carry without perturbing the program signature."""
    X, y = _blobs(160)
    model = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=3,
                           learning_rate=0.5)
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    cm.reset_compile_stats()
    try:
        model.fit(X, y, batch_size=32, compression="int8", overlap=8192,
                  epoch_end_callback=arm_after_first)
    finally:
        tracker.disarm()
    assert tracker.recompiles == []
    per = cm.compile_stats()["per_function"]
    train = [c for lbl, c in per.items() if lbl.startswith("train_step:")]
    assert train and train[0]["misses"] == 1


def test_precompile_overlap_then_fit_no_compiles():
    X, y = _blobs(120)
    model = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=2,
                           learning_rate=0.5)
    out = model.precompile(data_shapes={"data": (40, 10)},
                           label_shapes={"softmax_label": (40,)},
                           compression="int8", overlap=8192)
    assert out["programs"] == 1
    with cm.RecompileTracker(raise_on_recompile=True):
        model.fit(X, y, batch_size=40, compression="int8", overlap=8192)


def _capture_logger(name):
    logger = logging.getLogger(name)
    logger.setLevel(logging.INFO)
    records = []

    class _H(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger.addHandler(_H())
    return logger, records


def test_overlap_residual_checkpoint_resume_and_invalidation(tmp_path):
    """SATELLITE: per-bucket EF residuals round-trip through the sharded
    checkpoint (layout-keyed), and a bucket-plan change on resume DROPS
    them instead of cross-injecting stale error."""
    X, y = _blobs(96)
    d = str(tmp_path / "ckpt")

    m1 = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=2,
                        learning_rate=0.5)
    m1.fit(X, y, batch_size=32, compression="int8", overlap=2048,
           sharded_checkpoint_dir=d)

    from mxnet_tpu.utils import checkpoint as ckpt
    step = ckpt.latest_step(d)
    assert step == 2
    *_, meta, _, comm_state = ckpt.load_sharded(d, step, with_comm=True)
    assert comm_state is not None and len(comm_state) >= 2  # >=2 ledgers
    assert meta["comm_layout"].startswith("overlap:")
    names = set(comm_state)
    assert all(n.startswith("bucket") for n in names)

    # same plan on resume -> ledgers adopted
    log1, rec1 = _capture_logger("test_overlap_resume1")
    m2 = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=3,
                        learning_rate=0.5)
    m2.fit(X, y, batch_size=32, compression="int8", overlap=2048,
           sharded_checkpoint_dir=d, logger=log1)
    assert any("resumed" in m and "ledger" in m for m in rec1), rec1

    # different bucket cap -> plan changed -> ledgers dropped, fresh start
    log2, rec2 = _capture_logger("test_overlap_resume2")
    m3 = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=4,
                        learning_rate=0.5)
    m3.fit(X, y, batch_size=32, compression="int8", overlap=32768,
           sharded_checkpoint_dir=d, logger=log2)
    assert any("dropped on resume" in m for m in rec2), rec2
    acc = (m3.predict(X, batch_size=32).argmax(axis=1) == y).mean()
    assert acc > 0.9


def test_fused_residual_checkpoint_resume(tmp_path):
    """The non-overlap EF residual gets the same layout-keyed round-trip
    (saved under the __fused__ slot)."""
    X, y = _blobs(96)
    d = str(tmp_path / "ckpt")
    m1 = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=2,
                        learning_rate=0.5)
    m1.fit(X, y, batch_size=32, compression="int8",
           sharded_checkpoint_dir=d)
    from mxnet_tpu.utils import checkpoint as ckpt
    *_, meta, _, comm_state = ckpt.load_sharded(d, 2, with_comm=True)
    assert set(comm_state) == {"__fused__"}
    assert meta["comm_layout"].startswith("fused:")
    log1, rec1 = _capture_logger("test_fused_resume")
    m2 = mx.FeedForward(_mlp(hidden=64), ctx=_ctx8(), num_epoch=3,
                        learning_rate=0.5)
    m2.fit(X, y, batch_size=32, compression="int8",
           sharded_checkpoint_dir=d, logger=log1)
    assert any("resumed fused EF residual" in m for m in rec1), rec1


# -- stale-sync kvstore pipeline -----------------------------------------------

def test_push_pull_stale_one_round_staleness_and_flush():
    """The pipelined push lags exactly one round behind compute: call k
    returns the weights as of push k-1; flush_stale drains and returns
    the truth."""
    from mxnet_tpu.kvstore_async import AsyncKVStore

    kv = AsyncKVStore()
    try:
        kv.init("w", mx.nd.zeros((4,)))
        kv.set_optimizer(mx.optimizer.create("test", rescale_grad=1.0))
        g = {"w": np.ones((4,), np.float32)}
        r1 = kv.push_pull_stale(g)   # pull-before-push: pre-push state
        np.testing.assert_allclose(r1["w"], 0.0)
        r2 = kv.push_pull_stale(g)   # result of push #1
        np.testing.assert_allclose(r2["w"], 1.0)
        r3 = kv.push_pull_stale(g)   # result of push #2
        np.testing.assert_allclose(r3["w"], 2.0)
        out = kv.flush_stale(["w"])  # drains push #3
        np.testing.assert_allclose(out["w"], 3.0)
        assert kv._stale_round is None
        # flush with nothing in flight is a plain pull
        out2 = kv.flush_stale(["w"])
        np.testing.assert_allclose(out2["w"], 3.0)
    finally:
        del kv


def test_fit_overlap_dist_async_stale_sync():
    """fit(kvstore='dist_async', overlap=True) arms the stale-sync
    pipeline and still converges (weights one round stale)."""
    X, y = _blobs(120)
    model = mx.FeedForward(_mlp(hidden=32), ctx=mx.cpu(), num_epoch=4,
                           learning_rate=0.5)
    log, rec = _capture_logger("test_stale_sync_fit")
    model.fit(X, y, batch_size=40, kvstore="dist_async", overlap=True,
              logger=log)
    assert any("stale-sync armed" in m for m in rec), rec
    acc = (model.predict(X, batch_size=40).argmax(axis=1) == y).mean()
    assert acc > 0.85, acc


# -- satellites ----------------------------------------------------------------

def test_host_codec_symmetric_wire_accounting():
    """SATELLITE: decode records RECEIVED bytes — comm_stats() sees both
    ends of the host transport, and they balance for a loopback pair."""
    comm.reset_comm_stats()
    spec = comm.CompressionSpec.resolve("int8")
    codec = comm.HostCodec(spec)
    rng = np.random.RandomState(0)
    flat = rng.randn(4096).astype(np.float32)
    payload = codec.encode("slab0", flat)
    assert codec.bytes_encoded > 0 and codec.bytes_decoded == 0
    out = codec.decode(payload)
    assert out.shape == flat.shape
    assert codec.bytes_decoded == codec.bytes_encoded
    host = comm.comm_stats()["host_bytes"]
    assert host["sent"] == host["received"] > 0
    # the stateless receiving end (decode_payload) also records
    comm.reset_comm_stats()
    comm.decode_payload(spec, payload)
    host = comm.comm_stats()["host_bytes"]
    assert host["received"] > 0 and host["sent"] == 0


def test_from_layout_exact_rebuild():
    """SATELLITE: from_layout reconstructs the producer's layout exactly
    — same bucket names, key->slab assignment, offsets, sizes — without
    the old discard-and-rebuild dance."""
    shapes = [("a", (100, 10)), ("b", (5000,)), ("c", (300, 300)),
              ("d", ()), ("e", (7,))]
    b1 = comm.GradBucketer(shapes, max_bytes=40_000)
    b2 = comm.GradBucketer.from_layout(b1.layout())
    assert [bk["name"] for bk in b2.buckets] == \
        [bk["name"] for bk in b1.buckets]
    for x, ycol in zip(b1.buckets, b2.buckets):
        assert x["keys"] == ycol["keys"]
        assert x["shapes"] == ycol["shapes"]
        assert x["offsets"] == ycol["offsets"]
        assert x["size"] == ycol["size"]
    # max_bytes reflects the actual largest reconstructed slab
    assert b2.max_bytes == max(4 * bk["size"] for bk in b2.buckets)
    # pack/unpack works through the rebuilt layout
    rng = np.random.RandomState(1)
    kvs = {k: rng.randn(*s).astype(np.float32) if s
           else np.float32(rng.randn()) for k, s in shapes}
    flats = b2.pack({k: np.asarray(v) for k, v in kvs.items()})
    back = b2.unpack(flats)
    for k, s in shapes:
        np.testing.assert_allclose(back[k], np.asarray(kvs[k]).reshape(s))
    with pytest.raises(MXNetError):
        comm.GradBucketer.from_layout([])
