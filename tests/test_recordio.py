"""RecordIO + ImageRecordIter tests (reference: python/mxnet/recordio.py use
and tests/python/unittest/test_io.py Cifar10Rec; data is synthesized)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import recordio as rio


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    w = rio.MXRecordIO(path, "w")
    payloads = [bytes([i]) * (i + 1) for i in range(20)]
    for p in payloads:
        w.write(p)
    w.close()
    r = rio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()


def test_indexed_recordio(tmp_path):
    path, idx = str(tmp_path / "t.rec"), str(tmp_path / "t.idx")
    w = rio.MXIndexedRecordIO(idx, path, "w")
    for i in range(10):
        w.write_idx(i, f"record{i}".encode())
    w.close()
    r = rio.MXIndexedRecordIO(idx, path, "r")
    assert r.read_idx(7) == b"record7"
    assert r.read_idx(2) == b"record2"
    assert r.keys == list(range(10))
    r.close()


def test_pack_unpack():
    header = rio.IRHeader(0, 3.0, 42, 0)
    s = rio.pack(header, b"payload")
    h2, data = rio.unpack(s)
    assert h2.label == 3.0 and h2.id == 42
    assert data == b"payload"
    # multi-label
    header = rio.IRHeader(4, [1, 2, 3, 4], 7, 0)
    h3, data = rio.unpack(rio.pack(header, b"xy"))
    np.testing.assert_allclose(h3.label, [1, 2, 3, 4])
    assert data == b"xy"


def test_pack_img_roundtrip():
    img = np.random.randint(0, 255, (32, 32, 3), np.uint8)
    s = rio.pack_img(rio.IRHeader(0, 1.0, 0, 0), img, img_fmt=".png")
    header, decoded = rio.unpack_img(s)
    assert header.label == 1.0
    np.testing.assert_array_equal(decoded, img)  # png is lossless


def _make_imgrec(tmp_path, n=24, size=36):
    path = str(tmp_path / "images.rec")
    w = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    labels = []
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        label = float(i % 10)
        labels.append(label)
        w.write(rio.pack_img(rio.IRHeader(0, label, i, 0), img, img_fmt=".png"))
    w.close()
    return path, labels


def test_image_record_iter(tmp_path):
    path, labels = _make_imgrec(tmp_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=8, shuffle=False)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (8, 3, 32, 32)
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), labels[:8])
    # epoch determinism without shuffle
    again = list(it)
    np.testing.assert_allclose(
        batches[1].data[0].asnumpy(), again[1].data[0].asnumpy()
    )


def test_image_record_iter_mean_compute_and_cache(tmp_path):
    """Cold path computes the dataset mean at data_shape and caches it to
    disk; warm path loads the cached file (reference: iter_normalize.h
    compute-then-save on first pass)."""
    path, _ = _make_imgrec(tmp_path, n=12, size=32)
    mean_path = str(tmp_path / "mean.bin")
    assert not os.path.exists(mean_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=4, shuffle=False, mean_img=mean_path)
    assert os.path.exists(mean_path)  # cold path wrote the cache
    from mxnet_tpu.ndarray import load as nd_load

    mean = nd_load(mean_path)["mean_img"].asnumpy()
    assert mean.shape == (3, 32, 32)
    # verify it really is the dataset mean (no resize/crop at matching size)
    r = rio.MXRecordIO(path, "r")
    imgs = []
    while True:
        raw = r.read()
        if raw is None:
            break
        imgs.append(rio.unpack_img(raw)[1].astype(np.float64))
    r.close()
    expect = np.stack(imgs).mean(axis=0).transpose(2, 0, 1)
    np.testing.assert_allclose(mean, expect, atol=1e-2)
    # batches are mean-subtracted
    b = next(iter(it)).data[0].asnumpy()
    assert abs(b.mean()) < 2.0
    # warm path: loads (mtime unchanged) and produces identical batches
    mtime = os.path.getmtime(mean_path)
    it2 = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                              batch_size=4, shuffle=False, mean_img=mean_path)
    assert os.path.getmtime(mean_path) == mtime
    np.testing.assert_allclose(next(iter(it2)).data[0].asnumpy(), b)


def test_image_record_iter_augment(tmp_path):
    path, _ = _make_imgrec(tmp_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 28, 28),
                             batch_size=6, rand_crop=True, rand_mirror=True,
                             shuffle=True, mean_r=128, mean_g=128, mean_b=128,
                             scale=1.0 / 128)
    b = next(iter(it))
    arr = b.data[0].asnumpy()
    assert arr.shape == (6, 3, 28, 28)
    assert abs(arr.mean()) < 0.5  # roughly centered after mean/scale


def test_image_record_iter_sharding(tmp_path):
    path, labels = _make_imgrec(tmp_path)
    p0 = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=6, num_parts=2, part_index=0)
    p1 = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=6, num_parts=2, part_index=1)
    l0 = np.concatenate([b.label[0].asnumpy() for b in p0])
    l1 = np.concatenate([b.label[0].asnumpy() for b in p1])
    assert set(zip(l0, l0)) != set(zip(l1, l1)) or not np.allclose(l0, l1)


def test_image_record_iter_extended_augment(tmp_path):
    """Extended ImageAugmentParam surface (reference: image_augmenter.h):
    rotation, shear, random-sized/aspect crops, HSL jitter — python path."""
    path, _ = _make_imgrec(tmp_path)
    it = mio.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 28, 28), batch_size=6,
        rand_crop=True, rand_mirror=True, max_rotate_angle=15,
        max_shear_ratio=0.1, min_crop_size=28, max_crop_size=34,
        max_aspect_ratio=0.2, random_h=20, random_s=20, random_l=20,
        seed=5)
    # extended augments force the python pipeline
    assert it._native is None
    b = next(iter(it))
    arr = b.data[0].asnumpy()
    assert arr.shape == (6, 3, 28, 28)
    assert np.isfinite(arr).all()
    assert arr.min() >= 0.0 and arr.max() <= 255.0

    # same seed -> identical augmented stream; different seed -> different
    it_same = mio.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 28, 28), batch_size=6,
        rand_crop=True, rand_mirror=True, max_rotate_angle=15,
        max_shear_ratio=0.1, min_crop_size=28, max_crop_size=34,
        max_aspect_ratio=0.2, random_h=20, random_s=20, random_l=20,
        seed=5)
    np.testing.assert_allclose(next(iter(it_same)).data[0].asnumpy(), arr)
    it_diff = mio.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 28, 28), batch_size=6,
        rand_crop=True, max_rotate_angle=15, seed=6)
    assert not np.allclose(next(iter(it_diff)).data[0].asnumpy(), arr)


def test_hsl_jitter_identity_and_bounds(tmp_path):
    path, _ = _make_imgrec(tmp_path, n=4)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=2, random_h=1)
    rng = np.random.RandomState(0)
    img = rng.randint(0, 255, (8, 8, 3)).astype(np.float32)
    # zero-delta jitter returns (numerically) the same image
    it.random_h = it.random_s = it.random_l = 0
    class _Z:
        def uniform(self, a, b):
            return 0.0
    out = it._hsl_jitter(img, _Z())
    np.testing.assert_allclose(out, img, atol=1.0)
