"""Telemetry subsystem acceptance (ISSUE 5).

Covers: hub metric semantics, histogram percentile math (property-tested
against numpy), JSONL schema stability (golden keys per event kind),
Prometheus exposition incl. the compile/comm registry adapters, the
background HTTP endpoint, the Speedometer warm-up-skew fix, MFU/goodput
arithmetic, and the end-to-end contract — ``fit(telemetry=True)`` yields
exactly one span per step with non-overlapping phases, per-epoch MFU/
Goodput log lines, a loadable Chrome trace, and hub overhead under 2% of
step time.
"""

import json
import logging
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_hub():
    telemetry.reset()
    yield
    telemetry.stop_http()


# -- hub basics ----------------------------------------------------------------

def test_counter_gauge_observe_with_labels():
    h = telemetry.hub()
    h.counter("reqs_total")
    h.counter("reqs_total", 2)
    h.counter("reqs_total", 1, store="dist")
    h.gauge("depth", 7)
    h.gauge("depth", 3)          # gauges overwrite
    h.observe("lat_seconds", 0.5)
    h.observe("lat_seconds", 1.5)
    snap = h.snapshot()
    assert snap["counters"]["reqs_total"] == 3
    assert snap["counters"]["reqs_total{store=dist}"] == 1
    assert snap["gauges"]["depth"] == 3
    hist = snap["histograms"]["lat_seconds"]
    assert hist["count"] == 2 and hist["sum"] == 2.0
    assert hist["min"] == 0.5 and hist["max"] == 1.5


def test_default_counter_families_preregistered():
    """A fresh process exposes the full wired-subsystem schema at zero —
    'no traffic' and 'not instrumented' must look different to a scrape."""
    snap = telemetry.hub().snapshot()
    for name in telemetry.DEFAULT_COUNTERS:
        assert name in snap["counters"], name
    dump = telemetry.prom_dump()
    for family in ("resilience_step_retries_total", "io_prefetch_batches",
                   "kvstore_push_pull_total", "checkpoint_saves_total"):
        assert family in dump, family


def test_event_ring_and_sink(tmp_path):
    h = telemetry.hub()
    for i in range(5):
        h.emit("tick", i=i)
    assert len(h.events("tick")) == 5
    assert h.events("tick", limit=2)[-1]["i"] == 4
    sink = h.add_sink(telemetry.JsonlWriter(str(tmp_path / "s.jsonl")))
    h.emit("tock", x=1)
    h.remove_sink(sink)
    sink.close()
    h.emit("tock", x=2)  # after removal: not written
    rows = telemetry.read_jsonl(str(tmp_path / "s.jsonl"))
    assert len(rows) == 1 and rows[0]["kind"] == "tock" and rows[0]["x"] == 1
    assert rows[0]["v"] == telemetry.SCHEMA_VERSION


def test_histogram_percentile_matches_numpy():
    """Property test: for windows smaller than the reservoir the hub's
    percentile must equal numpy's linear-interpolation percentile."""
    rng = np.random.RandomState(7)
    for trial in range(20):
        n = int(rng.randint(1, 500))
        values = rng.randn(n) * rng.uniform(0.1, 100.0)
        hist = telemetry.Histogram()
        for v in values:
            hist.observe(v)
        for q in (0.0, 10.0, 50.0, 90.0, 99.0, 100.0):
            expect = np.percentile(values, q)  # default 'linear'
            got = hist.percentile(q)
            assert got == pytest.approx(expect, rel=1e-9, abs=1e-9), \
                (trial, n, q)


def test_histogram_reservoir_window():
    hist = telemetry.Histogram(maxlen=100)
    for v in range(1000):
        hist.observe(float(v))
    assert hist.count == 1000 and hist.max == 999.0
    # percentiles are over the most recent window only
    assert hist.percentile(0) == 900.0


# -- exporters -----------------------------------------------------------------

def test_jsonl_schema_golden_keys(tmp_path):
    """Schema-stability: every declared event kind carries its golden keys
    (v/kind/ts + the per-kind contract in EVENT_GOLDEN_KEYS)."""
    h = telemetry.hub()
    tl = telemetry.StepTimeline()
    span = tl.begin_step(0, 0)
    span.mark("dispatch")
    span.event("step_retry")
    span.end()                                   # -> span + step_event
    h.emit("badput", reason="compile", seconds=1.0, epoch=0)
    h.emit("epoch_summary", epoch=0, steps=4, seconds=2.0, goodput_pct=90.0)
    h.emit("checkpoint", step=3, seconds=0.5, tier="t2")
    h.emit("retry", op="push", attempt=1)
    h.emit("circuit_open", op="kvstore")
    h.emit("monitor", rows=7)
    # distributed-tracing kinds (schema v2)
    h.emit("server_span", op="push", dur_ms=0.2, origin_rank=1,
           start_ts=h.now(), parent_span="t-r1-e0-s0", dedup=False)
    h.emit("server_dedup", op="push", origin_rank=1)
    telemetry.record_clock_beacon("server", h.now(), h.now(), h.now())
    h.emit("server_stats", update_count=3)
    h.emit("flight_dump", reason="manual", path="/tmp/f.json")
    h.emit("watchdog", deadline=5.0)
    h.emit("chaos", site="kvstore.push")
    # elastic-training kind (ISSUE 10)
    h.emit("resize", from_world=8, to_world=6, reason="kill:7:chaos",
           membership_epoch=1, resize_kind="shrink")
    # memory-observability kinds (ISSUE 9)
    telemetry.memory.publish_plan("train_step:abc", {
        "argument_bytes": 1024, "output_bytes": 128, "temp_bytes": 2048,
        "generated_code_bytes": 0, "alias_bytes": 0, "total_bytes": 2176})
    h.emit("memory_watermark", epoch=0, watermark_bytes=4096,
           live_bytes=2048, live_count=7)
    h.emit("memory_leak", epoch=3, drift_bytes=1 << 20, epochs=2,
           watermark_bytes=8 << 20)
    h.emit("memory_preflight", what="fit", total_bytes=4096,
           budget_bytes=None, fits=True)
    # concurrency watchdog kind (ISSUE 11)
    h.emit("lockwatch", what="cycle", cycle="a->b", closing_edge="b->a",
           thread="mx-kv-serve-1")
    # fleet-controller kinds (ISSUE 12)
    h.emit("controller", lever="evict", action="evict rank 7",
           outcome="actuated", rank=7, votes=3, dry_run=False)
    h.emit("breaker", breaker="controller", state="open",
           from_state="closed", failures=2)
    # training-health kinds (ISSUE 14)
    h.emit("health", epoch=0, step=3, loss=1.25, finite=True,
           stats={"fc1": {"grad_norm": 0.5, "weight_norm": 1.0,
                          "update_ratio": 1e-3, "nonfinite": 0}})
    h.emit("health_anomaly", reason="grad_explosion", layer="fc1",
           epoch=0, step=3, value=1e7, threshold=1e6)
    # device-time profiler kind (ISSUE 15): capture lifecycle + summary
    h.emit("profile", phase="start", owner="fit", log_dir="/tmp/t",
           steps=0, device_ms=0.0, coverage_pct=None)
    h.emit("profile", phase="summary", owner="fit", steps=4,
           device_ms=12.5, coverage_pct=91.2, window_seconds=0.05,
           unattributed_ms=1.1,
           top=[{"layer": "fc1", "op": "dot_general", "us": 9000.0}])
    # cross-run ledger kind (ISSUE 20): append_record announces each
    # persisted RunRecord through the hub itself
    rec = telemetry.ledger.distill("fit", fingerprint="fp-golden",
                                   world_size=1)
    telemetry.ledger.append_record(rec, directory=str(tmp_path / "ledger"))
    path = str(tmp_path / "events.jsonl")
    telemetry.write_jsonl(path, h.events())
    rows = telemetry.read_jsonl(path)
    seen = set()
    for row in rows:
        assert row["v"] == telemetry.SCHEMA_VERSION
        assert "ts" in row and "kind" in row
        # the v2 envelope: every event carries its rank identity
        assert "rank" in row and "world_size" in row, row
        kind = row["kind"]
        for key in telemetry.EVENT_GOLDEN_KEYS.get(kind, ()):
            assert key in row, (kind, key, row)
        seen.add(kind)
    assert set(telemetry.EVENT_GOLDEN_KEYS) <= seen, \
        f"kinds never emitted: {set(telemetry.EVENT_GOLDEN_KEYS) - seen}"


def test_read_events_v1_backward_compat(tmp_path):
    """Schema v1 files (PR 5, pre-distributed-tracing) stay readable:
    read_events fills the v2 identity defaults (rank 0 of world 1)."""
    import json

    path = str(tmp_path / "v1.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "kind": "span", "ts": 1.0, "name":
                            "step", "epoch": 0, "step": 0, "dur_ms": 2.0,
                            "phases": []}) + "\n")
        f.write(json.dumps({"v": 1, "kind": "retry", "ts": 2.0,
                            "op": "push", "attempt": 0}) + "\n")
    rows = telemetry.read_events(path)
    assert all(r["rank"] == 0 and r["world_size"] == 1 for r in rows)
    # pre-ledger files (ISSUE 20): every row backfills run_id=None
    assert all(r["run_id"] is None for r in rows)
    span = rows[0]
    assert span["span_id"] is None and span["trace_id"] is None
    assert span["wall_ts"] == span["ts"]


def test_prom_dump_format_and_adapters():
    h = telemetry.hub()
    h.counter("widgets_total", 3, kind="a b")
    h.gauge("depth", 2.5)
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe("lat_seconds", v)
    dump = telemetry.prom_dump()
    assert "# TYPE mxtpu_widgets_total counter" in dump
    # every family carries the rank/world identity labels (ISSUE 6)
    assert ('mxtpu_widgets_total{kind="a b",rank="0",world_size="1"} 3'
            in dump)
    assert 'mxtpu_depth{rank="0",world_size="1"} 2.5' in dump
    assert "# TYPE mxtpu_lat_seconds summary" in dump
    assert 'mxtpu_lat_seconds_count{rank="0",world_size="1"} 4' in dump
    assert 'quantile="0.5"' in dump
    # registry adapters: compile + comm families present via collectors
    assert "mxtpu_compile_compiles_total" in dump
    assert "mxtpu_comm_sync_steps_total" in dump
    assert "mxtpu_comm_wire_bytes_total" in dump


def test_http_endpoint_serves_metrics():
    port = telemetry.serve_http(0)
    telemetry.counter("http_probe_total", 5)
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    assert 'mxtpu_http_probe_total{rank="0",world_size="1"} 5' in body
    health = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10).read().decode()
    assert health == "ok\n"
    telemetry.stop_http()


def test_config_resolution(monkeypatch):
    assert telemetry.TelemetryConfig.resolve(False) is None
    monkeypatch.delenv("MXNET_TPU_TELEMETRY", raising=False)
    assert telemetry.TelemetryConfig.resolve(None) is None
    monkeypatch.setenv("MXNET_TPU_TELEMETRY", "1")
    cfg = telemetry.TelemetryConfig.resolve(None)
    assert cfg is not None and cfg.timeline and cfg.mfu
    cfg = telemetry.TelemetryConfig.resolve("/tmp/x.jsonl")
    assert cfg.jsonl == "/tmp/x.jsonl"
    assert telemetry.TelemetryConfig.resolve(cfg) is cfg


# -- timeline primitives -------------------------------------------------------

def test_phase_attaches_to_current_span_and_histogram():
    tl = telemetry.StepTimeline()
    span = tl.begin_step(0, 0)
    with telemetry.phase("kvstore_push_pull"):
        time.sleep(0.002)
    span.end()
    assert [s[0] for s in span.subs] == ["kvstore_push_pull"]
    assert span.subs[0][2] >= 0.002
    p = telemetry.hub().percentile("kvstore_push_pull_seconds", 50)
    assert p is not None and p >= 0.002
    # without a span: histogram only, no crash
    with telemetry.phase("kvstore_push_pull"):
        pass


def test_mfu_epoch_report_arithmetic(caplog):
    acct = telemetry.MFUAccountant(num_devices=2, peak_flops=1e9)
    acct.flops_per_step = 1e6
    with caplog.at_level(logging.INFO):
        rep = acct.epoch_report(3, steps=100, wall_seconds=2.0,
                                compile_seconds=0.5, data_wait_seconds=0.25,
                                skipped_steps=2, step_retries=3)
    # achieved = 1e6*100/2 = 5e7 -> 5% of 1e9
    assert rep["mfu_pct"] == pytest.approx(5.0)
    # wasted: 5 steps at 20ms mean = 0.1s; badput total 0.85 of 2.0
    assert rep["badput"]["wasted_steps"] == pytest.approx(0.1)
    assert rep["goodput_pct"] == pytest.approx(100.0 * (2.0 - 0.85) / 2.0)
    assert any("MFU:" in r.message for r in caplog.records)
    assert any("Goodput:" in r.message for r in caplog.records)
    gauges = telemetry.hub().snapshot()["gauges"]
    assert gauges["mfu_pct"] == pytest.approx(5.0)
    assert gauges["goodput_pct"] == pytest.approx(rep["goodput_pct"])


# -- Speedometer warm-up skew fix ---------------------------------------------

def test_speedometer_skips_compile_polluted_window(caplog):
    from mxnet_tpu.callback import BatchEndParam, Speedometer
    from mxnet_tpu.utils import compile as compile_mod

    metric = mx.metric.create("accuracy")
    speedo = Speedometer(batch_size=32, frequent=2)
    reg = compile_mod.registry()
    with caplog.at_level(logging.INFO):
        speedo(BatchEndParam(epoch=0, nbatch=1, eval_metric=metric))
        # a compile lands inside the first window (what warm-up looks like)
        with reg.attribute("fake_prog"):
            reg._on_duration("/jax/backend_compile_duration_sec", 0.75)
        speedo(BatchEndParam(epoch=0, nbatch=2, eval_metric=metric))
        # steady-state window: no compiles -> a real throughput line
        speedo(BatchEndParam(epoch=0, nbatch=3, eval_metric=metric))
        speedo(BatchEndParam(epoch=0, nbatch=4, eval_metric=metric))
    msgs = [r.getMessage() for r in caplog.records]
    assert any("window skipped" in m and "badput/compile" in m
               for m in msgs), msgs
    assert any("samples/sec" in m and "window skipped" not in m
               for m in msgs), msgs
    counters = telemetry.hub().snapshot()["counters"]
    assert counters["badput_compile_seconds_total"] >= 0.75
    badput = telemetry.hub().events("badput")
    assert badput and badput[-1]["reason"] == "compile"


# -- end to end ----------------------------------------------------------------

def _mlp(classes=4, hidden=64):
    data = mx.sym.Variable("data")
    h1 = mx.sym.Activation(mx.sym.FullyConnected(
        data, name="fc1", num_hidden=hidden), name="a1", act_type="relu")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        h1, name="fc2", num_hidden=classes), name="softmax")
    return out


def test_fit_telemetry_end_to_end(tmp_path, caplog):
    rng = np.random.RandomState(0)
    n_rows, batch, epochs = 256, 64, 2
    X = rng.randn(n_rows, 16).astype(np.float32)
    y = rng.randint(0, 4, (n_rows,)).astype(np.float32)
    jsonl = str(tmp_path / "run.jsonl")
    model = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=epochs,
                           optimizer="sgd", learning_rate=0.1)
    with caplog.at_level(logging.INFO):
        model.fit(X, y, eval_data=(X[:64], y[:64]), batch_size=batch,
                  telemetry=telemetry.TelemetryConfig(jsonl=jsonl))
    tl = model.telemetry
    steps_per_epoch = n_rows // batch

    # exactly one span per train step
    steps = tl.steps("step")
    assert len(steps) == epochs * steps_per_epoch
    for i, span in enumerate(steps):
        assert span.epoch == i // steps_per_epoch
        assert span.step == i % steps_per_epoch
        phases = span.phases()
        names = [n for n, _, _ in phases]
        assert "dispatch" in names and "device" in names \
            and "host" in names
        # non-overlapping and ordered: each phase ends where the next starts
        for (_, t0, d0), (_, t1, _) in zip(phases, phases[1:]):
            assert t0 + d0 == pytest.approx(t1, abs=1e-6)
        assert phases[-1][1] + phases[-1][2] <= span.end_ts + 1e-6
        assert span.duration > 0
    # eval ran under the same timeline
    assert len(tl.steps("eval_step")) == epochs * (64 // batch)

    # per-epoch MFU/Goodput lines
    msgs = [r.getMessage() for r in caplog.records]
    for epoch in range(epochs):
        assert any(m.startswith(f"Epoch[{epoch}] MFU:") for m in msgs), msgs
        assert any(m.startswith(f"Epoch[{epoch}] Goodput:") for m in msgs)
    assert any("MFU: n/a" not in m for m in msgs if "MFU" in m)

    # chrome trace: loads as JSON, complete events carry the required keys
    trace_path = str(tmp_path / "trace.json")
    tl.dump_chrome_trace(trace_path)
    with open(trace_path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    complete = [e for e in events if e.get("ph") == "X"]
    assert complete, "no complete events"
    for e in complete:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert key in e, e
        assert e["ts"] >= 0 and e["dur"] >= 0
    assert sum(1 for e in complete
               if e["name"].startswith("step[")) == len(steps)

    # streamed JSONL: span events arrived as the run progressed
    rows = telemetry.read_jsonl(jsonl)
    kinds = {r["kind"] for r in rows}
    assert "span" in kinds and "epoch_summary" in kinds
    span_rows = [r for r in rows if r["kind"] == "span" and r["name"] == "step"]
    assert len(span_rows) == len(steps)
    for r in span_rows[:3]:
        for key in telemetry.EVENT_GOLDEN_KEYS["span"]:
            assert key in r

    # dump_jsonl round-trips the timeline itself
    tl_path = str(tmp_path / "tl.jsonl")
    tl.dump_jsonl(tl_path)
    assert len([r for r in telemetry.read_jsonl(tl_path)
                if r["name"] == "step"]) == len(steps)

    # prometheus exposition covers the four registries' families
    dump = telemetry.prom_dump()
    for family in ("mxtpu_compile_compiles_total", "mxtpu_comm_wire_bytes",
                   "mxtpu_resilience_step_retries_total",
                   "mxtpu_io_prefetch_batches_total",
                   "mxtpu_step_seconds", "mxtpu_mfu_pct"):
        assert family in dump, family

    # hub overhead: the per-step hub traffic must cost <2% of a
    # steady-state step (epoch 1+: compile amortized)
    h = telemetry.hub()
    reps = 5000
    batches = []
    for _ in range(3):  # best-of-3: full-suite CPU contention de-noised
        t0 = time.perf_counter()
        for i in range(reps):
            h.emit("bench", i=i)
        batches.append((time.perf_counter() - t0) / reps)
    emit_s = min(batches)
    steady = [s.duration for s in steps[steps_per_epoch:]]
    mean_step = sum(steady) / len(steady)
    hub_ops_per_step = 10
    overhead = hub_ops_per_step * emit_s / mean_step
    assert overhead < 0.02, \
        f"hub overhead {overhead:.2%} of {mean_step * 1e3:.2f}ms step"


def test_fit_telemetry_off_leaves_no_timeline():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 4, (64,)).astype(np.float32)
    model = mx.FeedForward(_mlp(hidden=16), ctx=mx.cpu(), num_epoch=1,
                           optimizer="sgd", learning_rate=0.1)
    model.fit(X, y, batch_size=32)
    assert getattr(model, "telemetry", None) is None
    assert telemetry.hub().events("span") == []


def test_predict_telemetry_spans():
    rng = np.random.RandomState(0)
    X = rng.randn(96, 8).astype(np.float32)
    y = rng.randint(0, 4, (96,)).astype(np.float32)
    model = mx.FeedForward(_mlp(hidden=16), ctx=mx.cpu(), num_epoch=1,
                           optimizer="sgd", learning_rate=0.1)
    model.fit(X, y, batch_size=32)
    model.predict(X, batch_size=32, telemetry=True)
    spans = model.telemetry.steps("predict_step")
    assert len(spans) == 3
    assert all(s.kind == "predict_step" for s in spans)


def test_fit_telemetry_with_guards_counts_retries():
    """Guard retries surface as hub counters + span instant events."""
    from mxnet_tpu.resilience import chaos as chaos_mod

    rng = np.random.RandomState(0)
    X = rng.randn(128, 8).astype(np.float32)
    y = rng.randint(0, 4, (128,)).astype(np.float32)
    model = mx.FeedForward(_mlp(hidden=16), ctx=mx.cpu(), num_epoch=1,
                           optimizer="sgd", learning_rate=0.1)
    base = telemetry.hub().snapshot()["counters"][
        "resilience_step_retries_total"]
    with chaos_mod.chaos_scope(seed=3, rules={"step.raise": 0.5}):
        model.fit(X, y, batch_size=32, guards=True, telemetry=True)
    counters = telemetry.hub().snapshot()["counters"]
    retried = counters["resilience_step_retries_total"] - base
    assert retried == model.guard_stats["step_retries"]
    assert retried > 0  # p=0.5 over 4 steps: ~0.94 chance; seed-pinned
    retry_events = [e for s in model.telemetry.steps("step")
                    for e in s.events if e["name"] == "step_retry"]
    assert len(retry_events) == retried


# -- CLI -----------------------------------------------------------------------

def test_cli_tail_and_summarize(tmp_path):
    h = telemetry.hub()
    tl = telemetry.StepTimeline()
    for i in range(3):
        s = tl.begin_step(0, i)
        s.mark("dispatch")
        s.mark("device")
        s.end()
    h.emit("badput", reason="compile", seconds=1.25, epoch=0)
    h.emit("epoch_summary", epoch=0, steps=3, seconds=0.5,
           goodput_pct=88.0, mfu_pct=12.5)
    path = str(tmp_path / "run.jsonl")
    telemetry.write_jsonl(path, h.events())
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, "-m", "mxnet_tpu.telemetry",
                        "tail", path, "-n", "5"], capture_output=True,
                       text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "epoch_summary" in r.stdout
    r = subprocess.run([sys.executable, "-m", "mxnet_tpu.telemetry",
                        "summarize", path], capture_output=True,
                       text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "spans: 3" in r.stdout
    assert "goodput 88.0%" in r.stdout and "MFU 12.5%" in r.stdout
    assert "compile" in r.stdout  # badput bucket listed


def test_record_compile_badput_dedupes_overlapping_observers():
    """Speedometer (window) and MFU epoch accounting (epoch) see the same
    compile-registry delta; the watermark must count it exactly once."""
    total0 = 1000.0  # pretend cumulative registry seconds
    before = telemetry.hub().snapshot()["counters"].get(
        "badput_compile_seconds_total", 0.0)
    first = telemetry.record_compile_badput(total0, 2.0, epoch=0)
    again = telemetry.record_compile_badput(total0, 2.0, epoch=0)
    assert first == pytest.approx(2.0) and again == 0.0
    # a later, larger window overlapping the counted region only adds the
    # uncounted tail
    tail = telemetry.record_compile_badput(total0 + 0.5, 2.5, epoch=0)
    assert tail == pytest.approx(0.5)
    counters = telemetry.hub().snapshot()["counters"]
    assert counters["badput_compile_seconds_total"] - before == \
        pytest.approx(2.5)


def test_score_after_fit_does_not_extend_fit_timeline():
    """fit() must clear the active timeline on exit: a later score() is
    not part of the traced run and must not sync per batch or append
    spans to the finished timeline."""
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 4, (64,)).astype(np.float32)
    model = mx.FeedForward(_mlp(hidden=16), ctx=mx.cpu(), num_epoch=1,
                           optimizer="sgd", learning_rate=0.1)
    model.fit(X, y, batch_size=32, telemetry=True)
    n_before = len(model.telemetry.spans)
    model.score(X, y=y, batch_size=32)
    assert len(model.telemetry.spans) == n_before
    assert telemetry.current_span() is None
