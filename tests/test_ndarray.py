"""NDArray tests (reference: tests/python/unittest/test_ndarray.py —
elementwise/negate/choose/copy/scalar/pickle/saveload/slice/clip/dot)."""

import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _same(a, b, tol=1e-5):
    np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


def check_with_uniform(uf, arg_shapes, dim=None, npuf=None, rmin=-10, type_list=None):
    """Random-input consistency vs numpy (mirrors the reference helper)."""
    for _ in range(3):
        if isinstance(arg_shapes, int):
            assert dim
            shape = tuple(np.random.randint(1, int(9), size=dim))
            arg_shapes = [shape] * arg_shapes
        ndarray_arg = []
        numpy_arg = []
        for s in arg_shapes:
            npy = np.random.uniform(rmin, 10, s).astype(np.float32)
            ndarray_arg.append(mx.nd.array(npy))
            numpy_arg.append(npy)
        out1 = uf(*ndarray_arg)
        out2 = (npuf or uf)(*numpy_arg)
        assert out1.shape == out2.shape
        _same(out1.asnumpy(), out2)


def test_ndarray_elementwise():
    check_with_uniform(lambda a, b: a + b, 2, 3)
    check_with_uniform(lambda a, b: a - b, 2, 3)
    check_with_uniform(lambda a, b: a * b, 2, 3)
    check_with_uniform(lambda a, b: a / b, 2, 3, rmin=1)
    check_with_uniform(lambda a: a + 3.0, 1, 3)
    check_with_uniform(lambda a: 3.0 - a, 1, 3)
    check_with_uniform(lambda a: a * 4.5, 1, 3)
    check_with_uniform(lambda a: a / 3.3, 1, 3)
    check_with_uniform(lambda a: 2.0 / a, 1, 3, rmin=1)


def test_ndarray_negate():
    npy = np.random.uniform(-10, 10, (2, 3, 4)).astype(np.float32)
    arr = mx.nd.array(npy)
    _same(npy, arr.asnumpy())
    _same(-npy, (-arr).asnumpy())
    # negation is out-of-place: arr unchanged
    _same(npy, arr.asnumpy())


def test_ndarray_inplace():
    npy = np.ones((4, 5), np.float32)
    arr = mx.nd.array(npy)
    arr += 2.0
    _same(arr.asnumpy(), npy + 2.0)
    arr *= 3.0
    _same(arr.asnumpy(), (npy + 2.0) * 3.0)
    other = mx.nd.ones((4, 5))
    arr -= other
    _same(arr.asnumpy(), (npy + 2.0) * 3.0 - 1.0)


def test_ndarray_scalar_ops_functions():
    a = mx.nd.ones((3, 4))
    out = mx.nd.empty((3, 4))
    nd._plus_scalar(a, 5.0, out=out)
    _same(out.asnumpy(), np.ones((3, 4)) + 5.0)
    nd._rminus_scalar(a, 5.0, out=out)
    _same(out.asnumpy(), 5.0 - np.ones((3, 4)))


def test_ndarray_choose():
    npy = np.arange(20).reshape(4, 5).astype(np.float32)
    arr = mx.nd.array(npy)
    idx = mx.nd.array([1, 3, 2, 0])
    out = nd.choose_element_0index(arr, idx)
    _same(out.asnumpy(), npy[np.arange(4), [1, 3, 2, 0]])


def test_ndarray_onehot():
    idx = mx.nd.array([1, 0, 2])
    out = mx.nd.zeros((3, 4))
    # reference signature: the second argument IS the output buffer
    nd.onehot_encode(idx, out)
    expect = np.zeros((3, 4), np.float32)
    expect[np.arange(3), [1, 0, 2]] = 1
    _same(out.asnumpy(), expect)


def test_ndarray_copy():
    c = mx.nd.array(np.random.uniform(-10, 10, (10, 10)))
    d = c.copyto(mx.cpu(0))
    _same(c.asnumpy(), d.asnumpy())
    e = mx.nd.zeros((10, 10))
    c.copyto(e)
    _same(c.asnumpy(), e.asnumpy())
    assert e is not c


def test_ndarray_slice():
    shape = (10,)
    npy = np.random.uniform(-10, 10, shape).astype(np.float32)
    arr = mx.nd.array(npy)
    _same(arr[3:8].asnumpy(), npy[3:8])
    arr[3:8] = npy[3:8] + 1
    npy[3:8] += 1
    _same(arr.asnumpy(), npy)
    sl = arr.slice(2, 5)
    _same(sl.asnumpy(), npy[2:5])


def test_ndarray_setitem_full():
    arr = mx.nd.zeros((3, 4))
    arr[:] = 7.5
    _same(arr.asnumpy(), np.full((3, 4), 7.5))
    arr[:] = np.arange(4)
    _same(arr.asnumpy(), np.broadcast_to(np.arange(4), (3, 4)))


def test_ndarray_reshape_transpose():
    npy = np.random.uniform(size=(2, 3, 4)).astype(np.float32)
    arr = mx.nd.array(npy)
    _same(arr.reshape((3, 8)).asnumpy(), npy.reshape(3, 8))
    m = mx.nd.array(npy.reshape(6, 4))
    _same(m.T.asnumpy(), npy.reshape(6, 4).T)


def test_ndarray_dot():
    a = np.random.uniform(size=(4, 5)).astype(np.float32)
    b = np.random.uniform(size=(5, 6)).astype(np.float32)
    out = nd.dot(mx.nd.array(a), mx.nd.array(b))
    _same(out.asnumpy(), a @ b, tol=1e-4)


def test_ndarray_unary():
    a = np.random.uniform(0.5, 10, (3, 4)).astype(np.float32)
    _same(nd.square(mx.nd.array(a)).asnumpy(), np.square(a))
    _same(nd.sqrt(mx.nd.array(a)).asnumpy(), np.sqrt(a), tol=1e-4)
    _same(nd.exp(mx.nd.array(a * 0.1)).asnumpy(), np.exp(a * 0.1), tol=1e-4)
    _same(nd.log(mx.nd.array(a)).asnumpy(), np.log(a), tol=1e-4)
    norm = nd.norm(mx.nd.array(a))
    assert norm.shape == (1,)
    _same(norm.asnumpy(), [np.sqrt((a ** 2).sum())], tol=1e-4)


def test_ndarray_clip():
    a = np.random.uniform(-10, 10, (4, 4)).astype(np.float32)
    out = nd.clip(mx.nd.array(a), -2.0, 2.0)
    _same(out.asnumpy(), np.clip(a, -2, 2))


def test_ndarray_pickle():
    a = mx.nd.array(np.random.uniform(size=(4, 5)))
    data = pickle.dumps(a)
    b = pickle.loads(data)
    _same(a.asnumpy(), b.asnumpy())


def test_ndarray_saveload(tmp_path):
    fname = str(tmp_path / "nd.bin")
    data = [mx.nd.array(np.random.uniform(size=(3, 4))) for _ in range(4)]
    nd.save(fname, data)
    loaded = nd.load(fname)
    assert len(loaded) == 4
    for x, y in zip(data, loaded):
        _same(x.asnumpy(), y.asnumpy())
    named = {"w": data[0], "b": data[1]}
    nd.save(fname, named)
    loaded = nd.load(fname)
    assert set(loaded.keys()) == {"w", "b"}
    _same(loaded["w"].asnumpy(), data[0].asnumpy())


def test_ndarray_saveload_dtypes(tmp_path):
    fname = str(tmp_path / "nd_dt.bin")
    arrs = {
        "f32": mx.nd.array(np.random.uniform(size=(3,)), dtype=np.float32),
        "i32": mx.nd.array(np.arange(5), dtype=np.int32),
        "u8": mx.nd.array(np.arange(5), dtype=np.uint8),
    }
    nd.save(fname, arrs)
    loaded = nd.load(fname)
    for k, v in arrs.items():
        assert loaded[k].dtype == v.dtype
        _same(loaded[k].asnumpy(), v.asnumpy())


def test_ndarray_creation():
    z = mx.nd.zeros((2, 3))
    _same(z.asnumpy(), np.zeros((2, 3)))
    o = mx.nd.ones((2, 3))
    _same(o.asnumpy(), np.ones((2, 3)))
    f = mx.nd.full((2, 2), 3.14)
    _same(f.asnumpy(), np.full((2, 2), 3.14, np.float32))
    r = mx.nd.arange(0, 10, 2)
    _same(r.asnumpy(), np.arange(0, 10, 2, dtype=np.float32))


def test_ndarray_context():
    a = mx.nd.zeros((2, 2), ctx=mx.cpu(1))
    assert a.context.device_id == 1
    b = a.as_in_context(mx.cpu(0))
    assert b.context.device_id == 0
    assert a.context.device_id == 1


def test_ndarray_asscalar_wait():
    a = mx.nd.ones((1,))
    assert float(a) == 1.0
    a.wait_to_read()
    mx.nd.waitall()
