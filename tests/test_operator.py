"""Operator forward/backward tests (reference:
tests/python/unittest/test_operator.py — forward AND analytic-vs-numeric
gradients for elementwise_sum, concat, slice_channel, regression, NumpyOp)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _same(a, b, tol=1e-4):
    np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


def _numeric_grad(f, x, eps=1e-3):
    """Central-difference gradient of scalar-valued f at x."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x[idx]
        x[idx] = orig + eps
        fp = f(x)
        x[idx] = orig - eps
        fm = f(x)
        x[idx] = orig
        g[idx] = (fp - fm) / (2 * eps)
        it.iternext()
    return g


def _check_numeric_gradient(symbol, location, check_args, tol=1e-2):
    """Bind, backward with ones cotangent, compare to numeric grad of sum(out)."""
    exe = symbol.simple_bind(mx.cpu(), **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        exe.arg_dict[k][:] = v
    exe.forward(is_train=True)
    exe.backward()
    for name in check_args:
        x0 = location[name].copy()

        def f(x, name=name):
            args = dict(location)
            args[name] = x
            for k, v in args.items():
                exe.arg_dict[k][:] = v
            out = exe.forward(is_train=True)
            return sum(float(o.asnumpy().astype(np.float64).sum()) for o in out)

        expected = _numeric_grad(f, x0)
        # restore and recompute analytic grad at the original point
        for k, v in location.items():
            exe.arg_dict[k][:] = v
        exe.forward(is_train=True)
        exe.backward()
        got = exe.grad_dict[name].asnumpy()
        np.testing.assert_allclose(got, expected, rtol=tol, atol=tol)


def test_elementwise_sum():
    shape = (5, 5)
    n = 4
    inputs = [sym.Variable(f"arg{i}") for i in range(n)]
    out = sym.ElementWiseSum(*inputs, name="esum")
    arrs = {f"arg{i}": np.random.uniform(-10, 10, shape).astype(np.float32)
            for i in range(n)}
    exe = out.simple_bind(mx.cpu(), **{k: shape for k in arrs})
    for k, v in arrs.items():
        exe.arg_dict[k][:] = v
    (o,) = exe.forward(is_train=True)
    _same(o.asnumpy(), sum(arrs.values()))
    exe.backward([mx.nd.array(np.ones(shape) * 2)])
    for i in range(n):
        _same(exe.grad_dict[f"arg{i}"].asnumpy(), np.ones(shape) * 2)


def test_concat_and_grad():
    a = sym.Variable("a")
    b = sym.Variable("b")
    out = sym.Concat(a, b, dim=1, name="cat")
    av = np.random.uniform(size=(2, 3)).astype(np.float32)
    bv = np.random.uniform(size=(2, 4)).astype(np.float32)
    exe = out.simple_bind(mx.cpu(), a=(2, 3), b=(2, 4))
    exe.arg_dict["a"][:] = av
    exe.arg_dict["b"][:] = bv
    (o,) = exe.forward(is_train=True)
    _same(o.asnumpy(), np.concatenate([av, bv], axis=1))
    og = np.random.uniform(size=(2, 7)).astype(np.float32)
    exe.backward([mx.nd.array(og)])
    _same(exe.grad_dict["a"].asnumpy(), og[:, :3])
    _same(exe.grad_dict["b"].asnumpy(), og[:, 3:])


def test_slice_channel():
    data = sym.Variable("data")
    outs = sym.SliceChannel(data=data, num_outputs=3, name="slice")
    dv = np.random.uniform(size=(2, 6, 2)).astype(np.float32)
    exe = outs.simple_bind(mx.cpu(), data=dv.shape)
    exe.arg_dict["data"][:] = dv
    result = exe.forward()
    assert len(result) == 3
    for i, r in enumerate(result):
        _same(r.asnumpy(), dv[:, i * 2:(i + 1) * 2])


def test_regression_grad():
    for op, transform in [(sym.LinearRegressionOutput, lambda x: x),
                          (sym.LogisticRegressionOutput,
                           lambda x: 1 / (1 + np.exp(-x)))]:
        data = sym.Variable("data")
        label = sym.Variable("label")
        out = op(data=data, label=label, name="reg")
        dv = np.random.uniform(-1, 1, (4, 1)).astype(np.float32)
        lv = np.random.uniform(0, 1, (4, 1)).astype(np.float32)
        exe = out.simple_bind(mx.cpu(), data=(4, 1), label=(4, 1))
        exe.arg_dict["data"][:] = dv
        exe.arg_dict["label"][:] = lv
        (o,) = exe.forward(is_train=True)
        _same(o.asnumpy(), transform(dv), tol=1e-4)
        exe.backward()
        _same(exe.grad_dict["data"].asnumpy(), transform(dv) - lv, tol=1e-4)


def test_softmax_output_grad():
    data = sym.Variable("data")
    label = sym.Variable("label")
    out = sym.SoftmaxOutput(data=data, label=label, name="sm")
    dv = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    lv = np.array([0, 2, 4, 1], np.float32)
    exe = out.simple_bind(mx.cpu(), data=(4, 5), label=(4,))
    exe.arg_dict["data"][:] = dv
    exe.arg_dict["label"][:] = lv
    (o,) = exe.forward(is_train=True)
    e = np.exp(dv - dv.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    _same(o.asnumpy(), p, tol=1e-4)
    exe.backward()
    onehot = np.zeros((4, 5), np.float32)
    onehot[np.arange(4), lv.astype(int)] = 1
    _same(exe.grad_dict["data"].asnumpy(), p - onehot, tol=1e-4)


def test_fullyconnected_numeric_grad():
    data = sym.Variable("data")
    out = sym.FullyConnected(data=data, name="fc", num_hidden=4)
    loc = {
        "data": np.random.uniform(-1, 1, (3, 5)).astype(np.float32),
        "fc_weight": np.random.uniform(-1, 1, (4, 5)).astype(np.float32),
        "fc_bias": np.random.uniform(-1, 1, (4,)).astype(np.float32),
    }
    _check_numeric_gradient(out, loc, ["data", "fc_weight", "fc_bias"])


def test_convolution_forward_vs_numpy():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, name="c", kernel=(3, 3), num_filter=2,
                           stride=(2, 2), pad=(1, 1))
    dv = np.random.uniform(-1, 1, (1, 3, 7, 7)).astype(np.float32)
    wv = np.random.uniform(-1, 1, (2, 3, 3, 3)).astype(np.float32)
    bv = np.random.uniform(-1, 1, (2,)).astype(np.float32)
    exe = conv.simple_bind(mx.cpu(), data=dv.shape)
    exe.arg_dict["data"][:] = dv
    exe.arg_dict["c_weight"][:] = wv
    exe.arg_dict["c_bias"][:] = bv
    (o,) = exe.forward()
    # direct convolution reference
    padded = np.pad(dv, ((0, 0), (0, 0), (1, 1), (1, 1)))
    expect = np.zeros(o.shape, np.float32)
    for f in range(2):
        for i in range(o.shape[2]):
            for j in range(o.shape[3]):
                patch = padded[0, :, i * 2:i * 2 + 3, j * 2:j * 2 + 3]
                expect[0, f, i, j] = (patch * wv[f]).sum() + bv[f]
    _same(o.asnumpy(), expect, tol=1e-3)


def test_convolution_numeric_grad():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, name="c", kernel=(2, 2), num_filter=2)
    loc = {
        "data": np.random.uniform(-1, 1, (2, 2, 4, 4)).astype(np.float32),
        "c_weight": np.random.uniform(-1, 1, (2, 2, 2, 2)).astype(np.float32),
        "c_bias": np.random.uniform(-1, 1, (2,)).astype(np.float32),
    }
    _check_numeric_gradient(conv, loc, ["data", "c_weight"])


def test_pooling_forward():
    data = sym.Variable("data")
    dv = np.random.uniform(-1, 1, (1, 2, 4, 4)).astype(np.float32)
    for pool_type, npf in [("max", np.max), ("avg", np.mean), ("sum", np.sum)]:
        p = sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2), pool_type=pool_type)
        exe = p.simple_bind(mx.cpu(), data=dv.shape)
        exe.arg_dict["data"][:] = dv
        (o,) = exe.forward()
        expect = np.zeros((1, 2, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                expect[:, :, i, j] = npf(dv[:, :, i * 2:i * 2 + 2, j * 2:j * 2 + 2],
                                         axis=(2, 3))
        _same(o.asnumpy(), expect, tol=1e-5)


def test_activation_grads():
    data = sym.Variable("data")
    dv = np.random.uniform(-2, 2, (3, 4)).astype(np.float32)
    for act in ["relu", "sigmoid", "tanh", "softrelu"]:
        net = sym.Activation(data=data, act_type=act)
        _check_numeric_gradient(net, {"data": dv.copy()}, ["data"])


def test_batchnorm_train_eval():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, name="bn", momentum=0.5)
    dv = np.random.uniform(-2, 2, (8, 3)).astype(np.float32)
    exe = bn.simple_bind(mx.cpu(), data=dv.shape)
    exe.arg_dict["data"][:] = dv
    exe.arg_dict["bn_gamma"][:] = 1.0
    exe.arg_dict["bn_beta"][:] = 0.0
    exe.aux_dict["bn_moving_var"][:] = 1.0
    (o,) = exe.forward(is_train=True)
    expect = (dv - dv.mean(0)) / np.sqrt(dv.var(0) + 1e-3)
    _same(o.asnumpy(), expect, tol=1e-3)
    # moving stats updated: mean momentum 0.5
    _same(exe.aux_dict["bn_moving_mean"].asnumpy(), 0.5 * dv.mean(0), tol=1e-4)
    # eval mode uses moving stats
    (o2,) = exe.forward(is_train=False)
    mm = exe.aux_dict["bn_moving_mean"].asnumpy()
    mv = exe.aux_dict["bn_moving_var"].asnumpy()
    _same(o2.asnumpy(), (dv - mm) / np.sqrt(mv + 1e-3), tol=1e-3)


def test_dropout():
    data = sym.Variable("data")
    net = sym.Dropout(data=data, p=0.5)
    dv = np.ones((200, 200), np.float32)
    exe = net.simple_bind(mx.cpu(), data=dv.shape)
    exe.arg_dict["data"][:] = dv
    (o,) = exe.forward(is_train=True)
    out = o.asnumpy()
    frac_kept = (out > 0).mean()
    assert 0.45 < frac_kept < 0.55
    _same(out[out > 0], np.full((out > 0).sum(), 2.0))  # inverted scaling
    (o_eval,) = exe.forward(is_train=False)
    _same(o_eval.asnumpy(), dv)


def test_leakyrelu():
    data = sym.Variable("data")
    net = sym.LeakyReLU(data=data, act_type="leaky", slope=0.1)
    dv = np.array([[-1.0, 2.0], [-3.0, 4.0]], np.float32)
    exe = net.simple_bind(mx.cpu(), data=dv.shape)
    exe.arg_dict["data"][:] = dv
    (o,) = exe.forward()
    _same(o.asnumpy(), np.where(dv > 0, dv, 0.1 * dv))


def test_blockgrad():
    data = sym.Variable("data")
    net = sym.BlockGrad(data=data)
    dv = np.random.uniform(size=(3, 3)).astype(np.float32)
    exe = net.simple_bind(mx.cpu(), data=dv.shape)
    exe.arg_dict["data"][:] = dv
    (o,) = exe.forward(is_train=True)
    _same(o.asnumpy(), dv)
    exe.backward()
    _same(exe.grad_dict["data"].asnumpy(), np.zeros_like(dv))


def test_embedding():
    data = sym.Variable("data")
    net = sym.Embedding(data=data, input_dim=10, output_dim=4, name="emb")
    ids = np.array([[1, 2], [3, 4]], np.float32)
    exe = net.simple_bind(mx.cpu(), data=ids.shape)
    exe.arg_dict["data"][:] = ids
    wv = np.random.uniform(size=(10, 4)).astype(np.float32)
    exe.arg_dict["emb_weight"][:] = wv
    (o,) = exe.forward()
    _same(o.asnumpy(), wv[ids.astype(int)])


def test_numpy_op():
    """NumpyOp custom softmax (reference: test_operator.py check_softmax
    via the python NumpyOp bridge)."""

    class NumpySoftmax(mx.operator.NumpyOp):
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ["data", "label"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            data_shape = in_shape[0]
            label_shape = (in_shape[0][0],)
            return [data_shape, label_shape], [data_shape]

        def forward(self, in_data, out_data):
            x = in_data[0]
            y = out_data[0]
            y[:] = np.exp(x - x.max(axis=1, keepdims=True))
            y /= y.sum(axis=1, keepdims=True)

        def backward(self, out_grad, in_data, out_data, in_grad):
            l = in_data[1].astype(int)
            y = out_data[0]
            dx = in_grad[0]
            dx[:] = y
            dx[np.arange(l.shape[0]), l] -= 1.0

        def need_top_grad_(self):
            return False

    npsm = NumpySoftmax()
    data = sym.Variable("data")
    net = npsm(data=data, name="nps")
    dv = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    lv = np.array([0, 1, 2, 3], np.float32)
    exe = net.simple_bind(mx.cpu(), data=(4, 5), nps_label=(4,))
    exe.arg_dict["data"][:] = dv
    exe.arg_dict["nps_label"][:] = lv
    (o,) = exe.forward(is_train=True)
    e = np.exp(dv - dv.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    _same(o.asnumpy(), p, tol=1e-4)
    exe.backward()
    onehot = np.zeros((4, 5), np.float32)
    onehot[np.arange(4), lv.astype(int)] = 1
    _same(exe.grad_dict["data"].asnumpy(), p - onehot, tol=1e-4)


def test_layout_nhwc_parity():
    """NHWC ops must match NCHW numerics exactly (weights stay OIHW in both
    layouts, so the same param values drive both graphs)."""
    np.random.seed(3)
    x_nchw = np.random.uniform(-1, 1, (2, 3, 8, 8)).astype(np.float32)
    w = np.random.uniform(-1, 1, (4, 3, 3, 3)).astype(np.float32)
    b = np.random.uniform(-1, 1, (4,)).astype(np.float32)

    def run(layout):
        data = sym.Variable("data")
        conv = sym.Convolution(data=data, name="c", kernel=(3, 3), pad=(1, 1),
                               stride=(2, 2), num_filter=4, layout=layout)
        bn = sym.BatchNorm(data=conv, name="b",
                           axis=3 if layout == "NHWC" else 1)
        act = sym.Activation(data=bn, act_type="relu")
        pool = sym.Pooling(data=act, name="p", kernel=(2, 2), stride=(2, 2),
                           pool_type="max", layout=layout)
        gp = sym.Pooling(data=pool, name="g", kernel=(1, 1), pool_type="avg",
                         global_pool=True, layout=layout)
        net = sym.Flatten(data=gp)
        x = x_nchw if layout == "NCHW" else x_nchw.transpose(0, 2, 3, 1)
        exe = net.simple_bind(mx.cpu(), data=x.shape)
        exe.arg_dict["data"][:] = x
        exe.arg_dict["c_weight"][:] = w
        exe.arg_dict["c_bias"][:] = b
        exe.arg_dict["b_gamma"][:] = np.ones(4, np.float32)
        exe.arg_dict["b_beta"][:] = np.zeros(4, np.float32)
        (o,) = exe.forward(is_train=True)
        exe.backward()
        gw = exe.grad_dict["c_weight"].asnumpy()
        return o.asnumpy(), gw, exe.aux_dict["b_moving_mean"].asnumpy()

    o1, gw1, mm1 = run("NCHW")
    o2, gw2, mm2 = run("NHWC")
    _same(o1, o2, tol=1e-4)
    _same(gw1, gw2, tol=1e-4)
    _same(mm1, mm2, tol=1e-4)


def test_deconvolution_nhwc_parity():
    np.random.seed(4)
    x_nchw = np.random.uniform(-1, 1, (2, 3, 5, 5)).astype(np.float32)
    w = np.random.uniform(-1, 1, (3, 4, 3, 3)).astype(np.float32)

    def run(layout):
        data = sym.Variable("data")
        net = sym.Deconvolution(data=data, name="d", kernel=(3, 3),
                                stride=(2, 2), pad=(1, 1), num_filter=4,
                                layout=layout)
        x = x_nchw if layout == "NCHW" else x_nchw.transpose(0, 2, 3, 1)
        exe = net.simple_bind(mx.cpu(), data=x.shape)
        exe.arg_dict["data"][:] = x
        exe.arg_dict["d_weight"][:] = w
        (o,) = exe.forward(is_train=False)
        out = o.asnumpy()
        return out if layout == "NCHW" else out.transpose(0, 3, 1, 2)

    _same(run("NCHW"), run("NHWC"), tol=1e-4)
