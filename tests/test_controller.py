"""Fleet-controller tests (ISSUE 12).

Covers: the pure policy functions (tier/overlap-cap selection, world
choice), the streaming straggler detector's agreement contract with the
batch detector (satellite), circuit-breaker observability (satellite),
blame-preferring shrink victims + eviction-reason labels on resize
events (satellite), the controller's safety rails (K-of-N hysteresis,
cooldowns, rate limits, dry-run, quarantine, breaker freeze), and the
e2e acceptance scenario: an armed dp-8 fit with an injected persistent
straggler + a flaky rank — the controller evicts the blamed rank,
backfills the recovered one, auto-picks a compression tier, survives
its own actuation failures frozen-not-dead, and the whole story is in
CRC-valid flight dumps. The chaos soak (kill/slow a random rank every
N steps under the armed controller) is tier-2 (`slow`).
"""

import os
import threading
import time

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.resilience import (CircuitBreaker, ElasticCoordinator,
                                  FleetController)
from mxnet_tpu.resilience.controller import (choose_world,
                                             select_overlap_bytes,
                                             select_tier)


@pytest.fixture(autouse=True)
def _fresh_hub():
    """Controller tests count events/gauges: isolate the hub, and keep
    commit()'s world relabeling from leaking into later tests."""
    prev = (telemetry.current_rank(), telemetry.world_size())
    telemetry.reset()
    yield
    telemetry.set_world(*prev)
    telemetry.reset()


def _ctx(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return [mx.cpu(i) for i in range(n)]


def _mlp(hidden=16, classes=2):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=hidden)
    net = mx.sym.Activation(data=net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(data=net, name="fc2", num_hidden=classes)
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def _blobs(n=840, dim=10, seed=0):
    rng = np.random.RandomState(seed)
    X = np.concatenate([rng.randn(n // 2, dim) + 1,
                        rng.randn(n - n // 2, dim) - 1]).astype(np.float32)
    y = np.concatenate([np.zeros(n // 2), np.ones(n - n // 2)]).astype(
        np.float32)
    order = rng.permutation(n)
    return X[order], y[order]


def _span(rank, step, device_ms, epoch=0, wire_ms=None):
    phases = [{"name": "device", "dur_ms": device_ms}]
    if wire_ms is not None:
        phases.append({"name": "wire", "dur_ms": wire_ms})
    return {"kind": "span", "name": "step", "epoch": epoch, "step": step,
            "rank": rank, "dur_ms": device_ms + (wire_ms or 0.0),
            "phases": phases}


def _emit_fleet_step(step, world=8, slow_rank=None, slow_ms=30.0,
                     base_ms=2.0, alive=None):
    for r in (alive if alive is not None else range(world)):
        d = slow_ms if r == slow_rank else base_ms
        telemetry.emit("span", rank=r, name="step", epoch=0, step=step,
                       dur_ms=d, phases=[{"name": "device", "dur_ms": d}])


def _controller(co=None, **kw):
    defaults = dict(interval=0.0, window=8, min_report_steps=8,
                    evict_k=2, evict_n=3, rejoin_after=1000.0,
                    evaluate_after=1000.0,
                    cooldowns={"evict": 0.0, "backfill": 0.0,
                               "retier": 0.0, "world": 0.0})
    defaults.update(kw)
    ctl = FleetController(**defaults)
    if co is not None:
        ctl.bind(coordinator=co, model_key="m", world_size=co.world_size,
                 can_retier=True, fp32_wire_bytes=1e6)
    return ctl


# -- pure policy ---------------------------------------------------------------

def test_select_tier_thresholds():
    assert select_tier(None) is None
    assert select_tier(0.0) == "none"
    assert select_tier(0.05) == "none"
    assert select_tier(0.2) == "bf16"
    assert select_tier(0.8) == "int8"
    assert select_tier(3.0) == "twobit"


def test_select_overlap_bytes_monotone():
    assert select_overlap_bytes(None) is None
    assert select_overlap_bytes(0.05) is None  # wire negligible
    caps = [select_overlap_bytes(r) for r in (0.2, 0.4, 0.8, 2.0)]
    assert all(c >= (1 << 20) for c in caps)
    # more comm-bound -> buckets no larger (wire starts earlier)
    assert all(a >= b for a, b in zip(caps, caps[1:]))


def test_choose_world_needs_margin_and_measurement():
    # unmeasured current world: never move blind
    assert choose_world({6: 2.0}, 8, 2, 8) == 8
    # measured better world past the margin: move
    assert choose_world({8: 1.0, 6: 1.5}, 8, 2, 8) == 6
    # inside the margin: hysteresis holds
    assert choose_world({8: 1.0, 6: 1.05}, 8, 2, 8, margin=0.1) == 8
    # outside [lo, hi]: not a candidate
    assert choose_world({8: 1.0, 2: 9.0}, 8, 4, 8) == 8


# -- streaming straggler detector (satellite) ----------------------------------

def test_streaming_detector_agrees_with_batch():
    """The contract: report() == detect_stragglers on the same window."""
    det = telemetry.StreamingStragglerDetector(window=16)
    events = {r: [] for r in range(4)}
    for step in range(16):
        for r in range(4):
            e = _span(r, step, 25.0 if r == 2 else 5.0)
            events[r].append(e)
            det.write_event(e)
    batch = telemetry.detect_stragglers(events, window=16, publish=False)
    streaming = det.report(publish=False)
    assert streaming == batch
    assert [s["rank"] for s in streaming["stragglers"]] == [2]


def test_streaming_detector_windows_incrementally():
    """Only the trailing `window` fleet steps are retained/judged — the
    point of the sensor: report cost is bounded by the window, never by
    run length, and old-world history ages out."""
    det = telemetry.StreamingStragglerDetector(window=8)
    # 30 early steps where rank 0 is slow...
    for step in range(30):
        for r in range(3):
            det.write_event(_span(r, step, 25.0 if r == 0 else 5.0))
    # ...then 8 healthy steps: the window forgets the old blame
    for step in range(30, 38):
        for r in range(3):
            det.write_event(_span(r, step, 5.0))
    snap = det.snapshot()
    keys = sorted({(e["epoch"], e["step"]) for evs in snap.values()
                   for e in evs})
    assert len(keys) == 8 and keys[0] == (0, 30)
    report = det.report(publish=False)
    assert report["stragglers"] == []
    assert report == telemetry.detect_stragglers(snap, window=8,
                                                 publish=False)


def test_streaming_detector_is_a_hub_sink():
    det = telemetry.StreamingStragglerDetector(window=4).attach()
    try:
        _emit_fleet_step(0, world=2)
        assert det.steps_seen == 2
        telemetry.emit("retry", op="x", attempt=0)  # filtered out
        assert det.steps_seen == 2
    finally:
        det.detach()


# -- circuit-breaker observability (satellite) ---------------------------------

def test_breaker_transitions_are_observable():
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=2, reset_after=5.0,
                        clock=lambda: clock[0], name="testbrk")
    br.record_failure()
    assert br.state == br.CLOSED and br.failures == 1
    br.record_failure()             # trip: closed -> open
    assert br.state == br.OPEN and br.last_transition is not None
    clock[0] = 6.0
    assert br.allow()               # open -> half_open probe
    assert br.state == br.HALF_OPEN
    br.record_success()             # half_open -> closed
    assert br.state == br.CLOSED and br.failures == 0

    events = telemetry.hub().events(kind="breaker")
    transitions = [(e["from_state"], e["state"]) for e in events
                   if e["breaker"] == "testbrk"]
    assert transitions == [("closed", "open"), ("open", "half_open"),
                           ("half_open", "closed")]
    for e in events:
        for key in telemetry.EVENT_GOLDEN_KEYS["breaker"]:
            assert key in e, (key, e)
    snap = telemetry.hub().snapshot()["gauges"]
    assert snap["circuit_breaker_state{breaker=testbrk}"] == 0.0
    assert snap["circuit_breaker_failures{breaker=testbrk}"] == 0.0
    assert snap["circuit_breaker_last_transition{breaker=testbrk}"] > 0


def test_breaker_incidents_reach_flight_recorder():
    telemetry.flight.reset()
    br = CircuitBreaker(failure_threshold=1, name="flightbrk")
    br.record_failure()
    _, _, incidents = telemetry.flight.recorder().snapshot()
    kinds = {e["kind"] for e in incidents}
    assert "breaker" in kinds and "circuit_open" in kinds


# -- blame-preferring shrink victims (satellite) -------------------------------

def test_request_world_prefers_blamed_rank():
    co = ElasticCoordinator(8)
    co.record_blame(3)
    co.request_world(7, reason="goodput")
    assert co.poll().ranks == (0, 1, 2, 4, 5, 6, 7)  # 3, not 7, left
    co.commit(co.poll())
    # blame gone (or departed): falls back to the highest rank
    co.record_blame(None)
    co.request_world(6)
    assert co.poll().ranks == (0, 1, 2, 4, 5, 6)


def test_resize_event_carries_eviction_reason_kinds():
    co = ElasticCoordinator(4)
    co.kill(3, reason="evicted")
    co.commit(co.poll())
    co.kill(2, reason="failure")
    co.commit(co.poll())
    resizes = telemetry.hub().events(kind="resize")
    assert resizes[0]["reason_kinds"] == "evicted"
    assert resizes[1]["reason_kinds"] == "failure"
    counters = telemetry.hub().snapshot()["counters"]
    assert counters["elastic_resizes_total{reason=evicted}"] == 1.0
    assert counters["elastic_resizes_total{reason=failure}"] == 1.0


# -- controller safety rails ---------------------------------------------------

def test_hysteresis_one_off_spike_never_evicts():
    co = ElasticCoordinator(8)
    ctl = _controller(co, evict_k=3, evict_n=5)
    # one window blames rank 7, then the fleet is healthy again
    for s in range(8):
        _emit_fleet_step(s, slow_rank=7)
    ctl.tick(now=1.0)
    for s in range(8, 24):
        _emit_fleet_step(s)
    for i in range(4):
        ctl.tick(now=2.0 + i)
    assert co.poll() is None            # nobody evicted
    assert not [d for d in ctl.decisions if d["outcome"] == "actuated"]

    # persistent blame crosses K-of-N: evicted
    for s in range(24, 48):
        _emit_fleet_step(s, slow_rank=7)
        ctl.tick(now=10.0 + s)
    ev = co.poll()
    assert ev is not None and 7 not in ev.ranks
    acts = [d for d in ctl.decisions if d["outcome"] == "actuated"]
    assert [d["lever"] for d in acts] == ["evict"]
    assert acts[0]["rank"] == 7 and acts[0]["blame"] == "device"


def test_dry_run_recommends_but_never_actuates():
    co = ElasticCoordinator(8)
    ctl = _controller(co, dry_run=True, wire_gbps=1e-6)  # comm-bound too
    assert ctl.state == "dry_run"
    for s in range(32):
        _emit_fleet_step(s, slow_rank=5)
        ctl.tick(now=float(s))
    assert co.poll() is None                      # nothing actuated
    assert ctl.take_retier() is None
    outcomes = {d["outcome"] for d in ctl.decisions}
    assert outcomes == {"recommended"}
    levers = {d["lever"] for d in ctl.decisions}
    assert "evict" in levers and "retier" in levers


def test_cooldown_and_rate_limit():
    co = ElasticCoordinator(8, min_world=2)
    ctl = _controller(co, cooldowns={"evict": 1000.0}, evict_k=1,
                      evict_n=1)
    for s in range(8):
        _emit_fleet_step(s, slow_rank=7)
    ctl.tick(now=100.0)
    co.commit(co.poll())                           # 7 evicted, committed
    for s in range(8, 24):
        _emit_fleet_step(s, slow_rank=6, alive=range(7))
        ctl.tick(now=101.0 + s)                    # inside the cooldown
    assert co.poll() is None
    assert any(d["outcome"] == "cooldown" for d in ctl.decisions)

    # rate limiter: cooldown passed but the hourly budget is spent
    ctl2 = _controller(ElasticCoordinator(8), evict_k=1, evict_n=1,
                       max_actions_per_hour=0)
    for s in range(8):
        _emit_fleet_step(s, slow_rank=7)
    ctl2.tick(now=1.0)
    assert ctl2._co.poll() is None
    assert any(d["outcome"] == "rate_limited" for d in ctl2.decisions)


def test_quarantine_after_max_evictions():
    co = ElasticCoordinator(8)
    ctl = _controller(co, evict_k=1, evict_n=1, max_evictions=1,
                      rejoin_after=0.0)
    for s in range(8):
        _emit_fleet_step(s, slow_rank=7)
    ctl.tick(now=1.0)
    co.commit(co.poll())                          # eviction committed
    # probation lapsed, but one eviction == quarantine: never readmitted
    for s in range(8, 16):
        _emit_fleet_step(s, alive=range(7))
        ctl.tick(now=10.0 + s)
    assert co.poll() is None
    assert 7 not in co.alive


def test_backfill_rejoins_after_probation():
    co = ElasticCoordinator(8)          # no heartbeat discipline
    ctl = _controller(co, max_evictions=5, rejoin_after=0.0, evict_k=1,
                      evict_n=1)
    co.kill(4, reason="failure")        # the fleet lost a rank on its own
    co.commit(co.poll())
    for s in range(8):
        _emit_fleet_step(s, alive=[r for r in range(8) if r != 4])
    ctl.tick(now=50.0)
    ev = co.poll()
    assert ev is not None and 4 in ev.ranks       # backfilled
    acts = [d for d in ctl.decisions if d["outcome"] == "actuated"]
    assert acts and acts[-1]["lever"] == "backfill"


def test_backfill_gate_disables_the_lever():
    """auto_backfill=False: an operator-drained rank is never force-
    rejoined (every lever is independently gated)."""
    co = ElasticCoordinator(8)
    ctl = _controller(co, auto_backfill=False, rejoin_after=0.0)
    co.leave(4, reason="maintenance")
    co.commit(co.poll())
    for s in range(8):
        _emit_fleet_step(s, alive=[r for r in range(8) if r != 4])
    ctl.tick(now=50.0)
    assert co.poll() is None
    assert not [d for d in ctl.decisions if d["lever"] == "backfill"]


def test_backfill_waits_for_fresh_heartbeat():
    co = ElasticCoordinator(8, heartbeat_timeout=0.2)
    ctl = _controller(co, rejoin_after=0.0)
    for r in range(8):
        co.heartbeat(r)
    co.kill(4, reason="failure")
    co.commit(co.poll())
    for s in range(8):
        _emit_fleet_step(s, alive=[r for r in range(8) if r != 4])
    ctl.tick(now=50.0)
    assert co.poll() is None            # dead-silent: stays out
    co.heartbeat(4)                     # it beats again -> readmit
    ctl.tick(now=51.0)
    ev = co.poll()
    assert ev is not None and 4 in ev.ranks


def test_breaker_freezes_controller_on_failed_actuations():
    co = ElasticCoordinator(8)
    ctl = _controller(co, evict_k=1, evict_n=1)

    fails = {"n": 0}
    real_kill = co.kill

    def broken_kill(rank=None, reason="failure"):
        fails["n"] += 1
        raise RuntimeError("kvstore wedged")

    co.kill = broken_kill
    try:
        for s in range(40):
            _emit_fleet_step(s, slow_rank=7)
            ctl.tick(now=float(s))
    finally:
        co.kill = real_kill
    # controller breaker: 2 consecutive failures -> open -> frozen
    assert fails["n"] == 2
    assert ctl.breaker.state == CircuitBreaker.OPEN
    assert ctl.state == "frozen"
    outcomes = [d["outcome"] for d in ctl.decisions
                if d["lever"] == "evict"]
    assert outcomes.count("failed") == 2
    assert "frozen" in outcomes
    assert co.poll() is None            # nothing ever actuated
    snap = telemetry.hub().snapshot()["gauges"]
    assert snap["controller_state"] == 2.0  # frozen
    assert snap["circuit_breaker_state{breaker=controller}"] == 2.0


def test_goodput_regression_counts_against_breaker():
    co = ElasticCoordinator(8)
    ctl = _controller(co, evict_k=1, evict_n=1, evaluate_after=5.0,
                      regress_tolerance=0.1)
    for s in range(8):
        _emit_fleet_step(s, slow_rank=7)
    ctl.tick(now=1.0)                   # evicts rank 7, baseline banked
    co.commit(co.poll())
    # post-actuation fleet is MUCH slower -> evaluation records a failure
    for s in range(8, 24):
        _emit_fleet_step(s, base_ms=50.0, alive=range(7))
    ctl.tick(now=10.0)                  # past the evaluate_after deadline
    assert ctl.breaker.failures >= 1
    assert any(d["outcome"] == "regressed" for d in ctl.decisions)


def test_tick_thread_mode_runs_and_stops():
    co = ElasticCoordinator(8)
    ctl = _controller(co, interval=0.01)
    t = ctl.start()
    assert t.name == "mx-fleet-ctl" and t.daemon
    assert ctl.threaded
    for s in range(8):
        _emit_fleet_step(s)
    time.sleep(0.1)
    ctl.stop()
    assert not ctl.threaded
    # the thread ticked: state gauge was published
    assert telemetry.hub().snapshot()["gauges"]["controller_state"] == 0.0


def test_controller_resolve():
    ctl = FleetController()
    assert FleetController.resolve(ctl) is ctl
    assert FleetController.resolve(None) is None
    assert FleetController.resolve(False) is None
    assert FleetController.resolve(True).cfg.dry_run is False
    os.environ["MXNET_TPU_CONTROLLER"] = "dry"
    try:
        assert FleetController.resolve(None).cfg.dry_run is True
    finally:
        del os.environ["MXNET_TPU_CONTROLLER"]
    with pytest.raises(MXNetError):
        FleetController.resolve("bogus")


# -- e2e: the acceptance scenario ----------------------------------------------

class _FleetFaults:
    """Injected pathology for a dp-8 virtual fit: rank 7 drags every
    step (a real sleep — the whole SPMD step waits on it) and emits
    per-rank spans blaming it; rank 6's out-of-band heartbeats stop
    mid-run until the coordinator buries it, then resume (the host
    "recovered" — recovery precedes readmission). The beater thread
    heartbeats every rank, departed ones included, so a long AOT
    re-warm gap can never read as a mass death."""

    def __init__(self, co, stall_s=0.015, straggler=7, flaky=6,
                 outage_step=8):
        self.co = co
        self.stall_s = stall_s
        self.straggler = straggler
        self.flaky = flaky
        self.outage_step = outage_step
        self.step = 0
        self._outage = False
        self._recovered = False
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._beat, daemon=True)
        self.thread.start()

    def _beat(self):
        while not self._stop.wait(0.02):
            if self._outage and not self._recovered and \
                    self.co.last_heartbeat(self.flaky) is None:
                # the coordinator buried it (kill pops the beat record):
                # the flaky host comes back and starts beating again
                self._recovered = True
            silent = self._outage and not self._recovered
            for r in range(self.co.full_world_size):
                if r == self.flaky and silent:
                    continue
                self.co.heartbeat(r)

    def close(self):
        self._stop.set()
        self.thread.join(timeout=2.0)

    def __call__(self, param):
        del param
        s = self.step
        self.step += 1
        if s >= self.outage_step:
            self._outage = True
        alive = self.co.alive
        if self.straggler in alive:
            time.sleep(self.stall_s)
        for r in alive:
            d = (self.stall_s * 1e3 + 2.0) if r == self.straggler else 2.0
            telemetry.emit("span", rank=r, name="step", epoch=0, step=s,
                           dur_ms=d,
                           phases=[{"name": "device", "dur_ms": d}])


def test_e2e_controller_evicts_backfills_and_retiers(tmp_path):
    """ISSUE 12 acceptance: persistent straggler + flaky rank in a dp-8
    fit; the armed controller evicts the blamed rank, backfills the
    recovered flaky rank, auto-picks a compression tier from the
    (bandwidth-scaled) comm:compute ratio, and the whole run lands in a
    CRC-valid flight dump with controller incidents."""
    X, y = _blobs(n=840)
    batch = 168                       # divisible by every world 6/7/8
    co = ElasticCoordinator(8, heartbeat_timeout=0.3)
    ctl = FleetController(
        interval=0.0, window=16, min_report_steps=16, evict_k=2,
        evict_n=4, max_evictions=1, rejoin_after=0.05,
        evaluate_after=0.5,
        cooldowns={"evict": 0.0, "backfill": 0.0, "retier": 0.0},
        wire_gbps=1e-5)               # scaled: the tier policy must act
    # outage from step 2: the flaky rank must die, recover, and be
    # backfilled with plenty of run left (the eviction/retier re-warm
    # gaps push most steps late)
    faults = _FleetFaults(co, outage_step=2)
    m = mx.FeedForward(_mlp(), ctx=_ctx(8), num_epoch=6, optimizer="sgd",
                       learning_rate=0.1)
    try:
        m.fit(X, y, batch_size=batch, elastic=co, controller=ctl,
              sharded_checkpoint_dir=str(tmp_path / "ckpt"),
              batch_end_callback=faults,
              telemetry=telemetry.TelemetryConfig(timeline=False,
                                                  memory=False))
    finally:
        faults.close()

    # the blamed straggler was evicted (reason label distinguishes it
    # from a failure), training converged on the survivors
    evicts = [d for d in ctl.decisions
              if d["lever"] == "evict" and d["outcome"] == "actuated"]
    assert [d["rank"] for d in evicts] == [7]
    assert 7 not in co.alive
    resize_events = telemetry.hub().events(kind="resize")
    assert any("evicted" in e.get("reason_kinds", "")
               for e in resize_events)
    # the flaky rank died by heartbeat and was backfilled once it beat
    # again (a loaded box can expire other ranks too — the contract is
    # that rank 6 came back, not that nothing else ever flapped)
    backfills = [d for d in ctl.decisions
                 if d["lever"] == "backfill" and
                 d["outcome"] == "actuated"]
    assert 6 in [d["rank"] for d in backfills]
    assert 6 in co.alive
    # the tier policy actually picked a tier on this (scaled) rig
    assert ctl._comm_mode in ("bf16", "int8", "twobit")
    assert any(d["lever"] == "retier" and d["outcome"] == "actuated"
               for d in ctl.decisions)
    assert ctl.breaker.state == CircuitBreaker.CLOSED
    assert m.score(X, y=y) > 0.9

    # forensics: the decision log is in a CRC-valid flight dump
    dump = str(tmp_path / "flight.json")
    telemetry.flight.dump(dump, reason="test")
    ok, payload = telemetry.validate_flight(dump)
    assert ok, payload
    kinds = {e["kind"] for e in payload["incidents"]}
    assert "controller" in kinds


def test_e2e_controller_failure_freezes_not_kills(tmp_path):
    """A controller whose staged actuation cannot be applied (bogus
    tier) trips its own breaker and freezes — the fit finishes
    unharmed."""
    X, y = _blobs(n=480)
    co = ElasticCoordinator(8)
    ctl = FleetController(interval=0.0, window=8, min_report_steps=8,
                          auto_tier=False, auto_evict=False)
    staged = {"n": 0}

    def drive(param):
        telemetry.emit("span", rank=0, name="step", epoch=0,
                       step=staged.setdefault("s", 0), dur_ms=2.0,
                       phases=[{"name": "device", "dur_ms": 2.0}])
        staged["s"] = staged.get("s", 0) + 1
        if staged["n"] < 2:
            staged["n"] += 1
            # sabotage: stage an unappliable tier change
            ctl._pending_retier = {"mode": "bogus-tier"}

    m = mx.FeedForward(_mlp(), ctx=_ctx(8), num_epoch=2, optimizer="sgd",
                       learning_rate=0.1)
    m.fit(X, y, batch_size=48, elastic=co, controller=ctl,
          sharded_checkpoint_dir=str(tmp_path / "ckpt"),
          batch_end_callback=drive)
    assert staged["n"] == 2
    assert ctl.breaker.state == CircuitBreaker.OPEN
    assert ctl.state == "frozen"
    fails = [d for d in ctl.decisions if d["outcome"] == "failed"]
    assert len(fails) == 2 and all(d["lever"] == "retier" for d in fails)
    assert m.score(X, y=y) > 0.9      # the fit itself never noticed


# -- tier-2 chaos soak ---------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_controller_keeps_fleet_healthy(tmp_path):
    """Tier-2 soak (satellite): a random rank is killed or slowed every
    few steps for several minutes of virtual training under the armed
    controller — the run must never hang, the fleet must converge, and
    every flight dump must validate."""
    flight_dir = str(tmp_path / "flight")
    os.makedirs(flight_dir)
    prev_flight = os.environ.get("MXNET_TPU_FLIGHT_DIR")
    os.environ["MXNET_TPU_FLIGHT_DIR"] = flight_dir
    try:
        X, y = _blobs(n=1680)
        batch = 168                   # divides every reachable world 6/7/8
        co = ElasticCoordinator(8, min_world=6)
        ctl = FleetController(
            interval=0.0, window=16, min_report_steps=16, evict_k=2,
            evict_n=4, max_evictions=3, rejoin_after=0.1,
            evaluate_after=0.5,
            cooldowns={"evict": 0.2, "backfill": 0.1, "retier": 1.0})
        rng = np.random.RandomState(7)
        state = {"s": 0, "slow": None}
        kill_every, rejoin_every = 9, 23

        def drive(param):
            s = state["s"]
            state["s"] += 1
            if s % 5 == 0:            # re-roll the slowed rank
                alive = co.alive
                state["slow"] = int(rng.choice(alive)) \
                    if rng.rand() < 0.7 else None
            # random-rank churn, floor-safe: a kill only lands while the
            # TARGET world has headroom (MX311-exempt: tests own chaos)
            if s and s % kill_every == 0:
                ev = co.poll()
                headroom = (ev.world_size if ev is not None
                            else co.world_size) > co.min_world
                if headroom:
                    co.kill(reason="failure")
            if s and s % rejoin_every == 0:
                co.join_all(reason="recovered")
            slow = state["slow"]
            alive = co.alive
            if slow in alive:
                time.sleep(0.005)
            for r in alive:
                d = 7.0 if r == slow else 2.0
                telemetry.emit(
                    "span", rank=r, name="step", epoch=0, step=s,
                    dur_ms=d, phases=[{"name": "device", "dur_ms": d}])

        m = mx.FeedForward(_mlp(), ctx=_ctx(8), num_epoch=8,
                           optimizer="sgd", learning_rate=0.1)
        it = mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False)
        m.fit(it, batch_size=batch, elastic=co, controller=ctl,
              sharded_checkpoint_dir=str(tmp_path / "ckpt"),
              batch_end_callback=drive)

        assert co.resizes >= 3            # the soak really churned
        assert co.world_size >= co.min_world
        assert m.score(X, y=y) > 0.9      # converged through it all
        assert ctl.decisions              # the controller was alive
        # every dump written during the soak + one final validates
        final = os.path.join(flight_dir, "final.json")
        telemetry.flight.dump(final, reason="soak_end")
        dumps = [os.path.join(flight_dir, f)
                 for f in os.listdir(flight_dir)]
        assert dumps
        for path in dumps:
            ok, payload = telemetry.validate_flight(path)
            assert ok, (path, payload)
    finally:
        if prev_flight is None:
            os.environ.pop("MXNET_TPU_FLIGHT_DIR", None)
        else:
            os.environ["MXNET_TPU_FLIGHT_DIR"] = prev_flight
