"""Import-surface guard (ISSUE 1 satellite).

The seed's single unchecked API drift (``from jax import shard_map``)
surfaced as 75 opaque pytest collection errors. This test imports every
``mxnet_tpu.*`` submodule under the CPU platform, so any future drift —
a moved JAX symbol, a typo'd import, a missing optional dep leaking into a
module scope — fails exactly ONE obvious test naming the broken module.
"""

import importlib
import pkgutil

import pytest

import mxnet_tpu

# modules whose import has side effects that need env not present in unit
# tests (none today; keep the hook so future additions are explicit)
_SKIP: set[str] = set()


def _all_submodules():
    mods = ["mxnet_tpu"]
    for info in pkgutil.walk_packages(mxnet_tpu.__path__,
                                      prefix="mxnet_tpu."):
        # native/libmxtpu_*.so are ctypes payloads (loaded via CDLL), not
        # Python extension modules — pkgutil lists them anyway
        if info.name.rsplit(".", 1)[-1].startswith("lib"):
            continue
        mods.append(info.name)
    return sorted(mods)


@pytest.mark.parametrize("module_name", _all_submodules())
def test_submodule_imports(module_name):
    if module_name in _SKIP:
        pytest.skip(f"{module_name}: explicit skip")
    importlib.import_module(module_name)


def test_walk_found_the_tree():
    """The walk itself must see the package layout (a packaging regression
    that hides submodules would otherwise pass vacuously)."""
    mods = _all_submodules()
    for expected in ("mxnet_tpu.symbol", "mxnet_tpu.executor",
                     "mxnet_tpu.compat", "mxnet_tpu.analysis",
                     "mxnet_tpu.analysis.source_lint",
                     "mxnet_tpu.models.transformer",
                     "mxnet_tpu.parallel.sequence"):
        assert expected in mods, f"{expected} missing from package walk"
    assert len(mods) > 40


def test_shard_map_compat_shim():
    """compat.shard_map accepts either spelling of the replication flag
    and resolves on the installed JAX."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu.compat import JAX_VERSION, shard_map
    from mxnet_tpu.parallel import make_mesh

    assert isinstance(JAX_VERSION, tuple) and JAX_VERSION >= (0, 4)
    mesh = make_mesh(dp=8)
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    for flag in ({"check_vma": False}, {"check_rep": False}, {}):
        out = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"), **flag)(x)
        np.testing.assert_allclose(np.asarray(out), x * 2)
