"""Shape-inference tests (reference: tests/python/unittest/test_infer_shape.py)."""

import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def test_mlp_infer_shape():
    data = sym.Variable("data")
    out = sym.FullyConnected(data=data, name="fc1", num_hidden=1000)
    out = sym.Activation(data=out, act_type="relu")
    out = sym.FullyConnected(data=out, name="fc2", num_hidden=10)
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(100, 100))
    names = out.list_arguments()
    d = dict(zip(names, arg_shapes))
    assert d["fc1_weight"] == (1000, 100)
    assert d["fc1_bias"] == (1000,)
    assert d["fc2_weight"] == (10, 1000)
    assert out_shapes == [(100, 10)]
    assert aux_shapes == []


def test_conv_infer_shape():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, num_filter=32, kernel=(3, 3), pad=(1, 1))
    arg_shapes, out_shapes, _ = conv.infer_shape(data=(2, 3, 16, 16))
    d = dict(zip(conv.list_arguments(), arg_shapes))
    assert d[f"{conv.name}_weight"] == (32, 3, 3, 3)
    assert out_shapes == [(2, 32, 16, 16)]


def test_batchnorm_aux_shape():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, name="bn")
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(4, 8, 5, 5))
    assert aux_shapes == [(8,), (8,)]
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]


def test_softmax_label_shape_inferred():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, name="fc", num_hidden=10)
    net = sym.SoftmaxOutput(data=fc, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(32, 100))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["softmax_label"] == (32,)


def test_incomplete_infer_raises():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=10)
    with pytest.raises(mx.MXNetError):
        net.infer_shape()


def test_mismatch_raises():
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    net = lhs + rhs
    with pytest.raises(mx.MXNetError):
        net.infer_shape(lhs=(2, 3), rhs=(3, 2))


def test_pooling_global():
    data = sym.Variable("data")
    p = sym.Pooling(data=data, kernel=(1, 1), global_pool=True, pool_type="avg")
    _, out_shapes, _ = p.infer_shape(data=(2, 16, 7, 7))
    assert out_shapes == [(2, 16, 1, 1)]


def test_reshape_flatten():
    data = sym.Variable("data")
    r = sym.Reshape(data=data, target_shape=(0, -1))
    _, out_shapes, _ = r.infer_shape(data=(4, 3, 2))
    assert out_shapes == [(4, 6)]
    f = sym.Flatten(data=data)
    _, out_shapes, _ = f.infer_shape(data=(4, 3, 2, 2))
    assert out_shapes == [(4, 12)]
