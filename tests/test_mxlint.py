"""mxlint tier: seeded violations produce exactly the expected rule ids,
the repo itself lints clean (THE self-lint gate: this test runs in tier-1
on every PR), and Symbol.verify enforces the StaticGraph::InferShape
contract at bind time (ISSUE 1 acceptance criteria)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import lint_source, verify_json, verify_symbol
from mxnet_tpu.base import MXNetError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ids(findings):
    return [f.rule.id for f in findings]


# -- Pass 1: source lint fixtures ---------------------------------------------

def test_fixture_syntax_error_is_mx100():
    findings = lint_source("def broken(:\n", "fx.py")
    assert _ids(findings) == ["MX100"]
    assert findings[0].is_error


def test_fixture_bad_import():
    findings = lint_source("from jax import shard_map\n", "fx.py")
    assert _ids(findings) == ["MX101"]
    assert findings[0].is_error


def test_fixture_bad_import_experimental_path():
    src = "from jax.experimental.shard_map import shard_map\n"
    assert _ids(lint_source(src, "fx.py")) == ["MX101"]


def test_fixture_item_in_jitted_fn():
    src = (
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item()\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX202"]
    assert findings[0].line == 5


def test_fixture_host_sync_via_tracing_call():
    src = (
        "import jax\n"
        "from jax import lax\n"
        "def body(c, x):\n"
        "    return c + float(x), None\n"
        "def run(xs):\n"
        "    return lax.scan(body, 0.0, xs)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX202"]


def test_fixture_numpy_in_shard_map_body():
    src = (
        "import numpy as np\n"
        "from mxnet_tpu.compat import shard_map\n"
        "def block(x):\n"
        "    return np.sum(x)\n"
        "def run(mesh, spec, x):\n"
        "    return shard_map(block, mesh=mesh, in_specs=spec,\n"
        "                     out_specs=spec)(x)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX201"]


def test_fixture_static_argnums_list():
    src = (
        "import jax\n"
        "def g(x, n):\n"
        "    return x\n"
        "h = jax.jit(g, static_argnums=[1])\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX301"]


def test_fixture_mx303_jit_inside_loop():
    src = (
        "import jax\n"
        "def train(batches):\n"
        "    for b in batches:\n"
        "        step = jax.jit(lambda x: x * 2)\n"
        "        step(b)\n"
    )
    assert "MX303" in _ids(lint_source(src, "fx.py"))


def test_fixture_mx303_immediate_jit_call():
    src = (
        "import jax\n"
        "def f(g, x):\n"
        "    return jax.jit(g)(x)\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX303"]
    assert "fresh jit wrapper" in findings[0].message


def test_fixture_mx303_unstable_static_args():
    src = (
        "import jax\n"
        "def g(x, n):\n"
        "    return x\n"
        "h = jax.jit(g, static_argnums=list(range(1, 2)))\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX303"]
    src2 = (
        "import jax\n"
        "def g(x, n):\n"
        "    return x\n"
        "h = jax.jit(g, static_argnames=[n for n in ('n',)])\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == ["MX303"]


def test_fixture_mx303_clean_patterns_pass():
    """The sanctioned shapes: wrapper cached at module/instance scope,
    tuple static args — no findings."""
    src = (
        "import jax\n"
        "def g(x, n):\n"
        "    return x\n"
        "step = jax.jit(g, static_argnums=(1,))\n"
        "def train(batches):\n"
        "    for b in batches:\n"
        "        step(b, 2)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []


def test_fixture_mx303_pragma_suppression():
    src = (
        "import jax\n"
        "def f(g, x):\n"
        "    return jax.jit(g)(x)  # mxlint: disable=MX303\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []


def test_fixture_fstring_in_traced_fn():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    name = f'shape={x.shape}'\n"
        "    return x\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX302"]


def test_callback_bodies_are_exempt():
    """numpy inside a pure_callback host fn is correct, not a hazard."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    def cb(a):\n"
        "        return np.asarray(a) * 2\n"
        "    return jax.pure_callback(cb, x, x)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []


def test_pragma_suppression():
    src = "from jax import shard_map  # mxlint: disable=MX101\n"
    assert lint_source(src, "fx.py") == []
    src2 = "# mxlint: skip-file\nfrom jax import shard_map\n"
    assert lint_source(src2, "fx.py") == []
    # pragma for a different rule does NOT suppress
    src3 = "from jax import shard_map  # mxlint: disable=MX202\n"
    assert _ids(lint_source(src3, "fx.py")) == ["MX101"]


# -- MX304: raw gradient psum outside the comm subsystem (ISSUE 4) ------------

def test_fixture_mx304_direct_psum_on_grads():
    src = (
        "import jax\n"
        "from jax import lax\n"
        "def sync(grads, ax):\n"
        "    return lax.psum(grads, ax)\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX304"]
    assert not findings[0].is_error  # perf warning, not a gate


def test_fixture_mx304_tree_map_lambda_psum():
    src = (
        "import jax\n"
        "from jax import lax\n"
        "def sync(grads, ax):\n"
        "    return jax.tree_util.tree_map(\n"
        "        lambda g: lax.psum(g, ax), grads)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX304"]


def test_fixture_mx304_clean_patterns():
    # psum of a scalar constant (axis-size probe) is not gradient traffic
    src = (
        "from jax import lax\n"
        "def axis_size(ax):\n"
        "    return lax.psum(1, ax)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    # the comm package is the sanctioned home for raw gradient psums
    src2 = (
        "import jax\n"
        "from jax import lax\n"
        "def sync(grads, ax):\n"
        "    return lax.psum(grads, ax)\n"
    )
    assert _ids(lint_source(src2, "mxnet_tpu/comm/allreduce.py")) == []
    # pragma suppression works like every other rule
    src3 = (
        "import jax\n"
        "from jax import lax\n"
        "def sync(grads, ax):\n"
        "    return lax.psum(grads, ax)  # mxlint: disable=MX304\n"
    )
    assert _ids(lint_source(src3, "fx.py")) == []


# -- MX6xx robustness fixtures (ISSUE 2 satellite) ----------------------------

def test_fixture_bare_except_is_mx601():
    src = "try:\n    risky()\nexcept:\n    pass\n"
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX601"]
    assert findings[0].is_error and findings[0].line == 3


def test_fixture_unbounded_retry_loop_is_mx602():
    src = (
        "def send(op):\n"
        "    while True:\n"
        "        try:\n"
        "            return op()\n"
        "        except ConnectionError:\n"
        "            continue\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX602"]
    assert findings[0].is_error


def test_fixture_bounded_retry_loops_are_clean():
    # backoff sleep bounds it
    src = (
        "import time\n"
        "def send(op):\n"
        "    while True:\n"
        "        try:\n"
        "            return op()\n"
        "        except ConnectionError:\n"
        "            time.sleep(0.1)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    # a handler that escapes the loop is failure propagation, not a retry
    src2 = (
        "def serve(op):\n"
        "    while True:\n"
        "        try:\n"
        "            op()\n"
        "        except OSError:\n"
        "            return\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == []
    # real work in the handler (e.g. replying on a socket) is an event
    # loop, not a blind retry
    src3 = (
        "def serve(conn, op):\n"
        "    while True:\n"
        "        try:\n"
        "            op()\n"
        "        except ValueError as e:\n"
        "            reply(conn, e)\n"
    )
    assert _ids(lint_source(src3, "fx.py")) == []


# -- MX306 un-barriered timing fixtures (ISSUE 5 satellite) -------------------

def test_fixture_mx306_unbarriered_delta():
    src = (
        "import time\n"
        "def bench(step, x):\n"
        "    t0 = time.time()\n"
        "    out = step(x)\n"
        "    return time.time() - t0\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX306"]
    assert findings[0].line == 5
    # perf_counter, delta via a second stored read
    src2 = (
        "from time import perf_counter\n"
        "def bench(step, x):\n"
        "    t0 = perf_counter()\n"
        "    out = step(x)\n"
        "    t1 = perf_counter()\n"
        "    return t1 - t0\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == ["MX306"]


def test_fixture_mx306_barriered_deltas_are_clean():
    # block_until_ready between start and read
    src = (
        "import time\n"
        "import jax\n"
        "def bench(step, x):\n"
        "    t0 = time.perf_counter()\n"
        "    out = step(x)\n"
        "    jax.block_until_ready(out)\n"
        "    return time.perf_counter() - t0\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    # monotonic deadlines/backoff bookkeeping are not measurements
    src2 = (
        "import time\n"
        "def poll(op):\n"
        "    start = time.monotonic()\n"
        "    op()\n"
        "    return time.monotonic() - start\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == []
    # no work between the reads: nothing is being mis-timed
    src3 = (
        "import time\n"
        "def stamp():\n"
        "    t0 = time.time()\n"
        "    return time.time() - t0\n"
    )
    assert _ids(lint_source(src3, "fx.py")) == []
    # blocking .result() (engine futures, precompile) counts as a barrier
    src4 = (
        "import time\n"
        "def bench(pool, job):\n"
        "    t0 = time.time()\n"
        "    fut = pool.submit(job)\n"
        "    fut.result()\n"
        "    return time.time() - t0\n"
    )
    assert _ids(lint_source(src4, "fx.py")) == []


def test_fixture_mx306_pragma_and_exempt_paths():
    src = (
        "import time\n"
        "def bench(step, x):\n"
        "    t0 = time.time()\n"
        "    out = step(x)\n"
        "    return time.time() - t0  # mxlint: disable=MX306\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    src2 = src.replace("  # mxlint: disable=MX306", "")
    # the sanctioned timing homes are exempt wholesale
    assert _ids(lint_source(
        src2, "mxnet_tpu/telemetry/timeline.py")) == []
    assert _ids(lint_source(src2, "mxnet_tpu/utils/profiler.py")) == []


def test_tree_has_no_mx306_findings():
    """ISSUE 5 satellite: the tree self-lints clean of the un-barriered-
    timing footgun (every wall-clock measurement either blocks first or is
    explicitly pragma'd with its justification)."""
    from mxnet_tpu.analysis import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX306"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX309 host-sync-in-step-loop fixtures (ISSUE 9) ---------------------------

def test_fixture_mx309_host_sync_in_step_loop():
    src = (
        "import numpy as np\n"
        "def loop(batches, train_step, state):\n"
        "    for b in batches:\n"
        "        state = train_step(state, b)\n"
        "        loss = np.asarray(state[1])\n"
        "        acc = state[2].asnumpy()\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX309", "MX309"]


def test_fixture_mx309_scalar_pull_shapes():
    # float(name)/int(name): the classic per-step scalar pull
    src = (
        "def loop(batches, train_step, state, loss):\n"
        "    for b in batches:\n"
        "        state, loss = train_step(state, b)\n"
        "        print(float(loss))\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX309"]
    # attribute/subscript args are host metadata (shapes, pads): exempt
    src2 = (
        "def loop(batches, train_step, state):\n"
        "    for b in batches:\n"
        "        state = train_step(state, b)\n"
        "        n = int(b.shape[0])\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == []


def test_fixture_mx309_only_fires_in_step_loops():
    # same syncs, no step dispatch in the loop: init/checkpoint loops may
    # pull freely
    src = (
        "import numpy as np\n"
        "def save_all(arrays):\n"
        "    out = []\n"
        "    for a in arrays:\n"
        "        out.append(np.asarray(a))\n"
        "    return out\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    # a once-per-epoch pull AFTER the inner step loop is not blamed on it
    src2 = (
        "import numpy as np\n"
        "def fit(epochs, batches, train_step, state, gstate):\n"
        "    for e in range(epochs):\n"
        "        for b in batches:\n"
        "            state = train_step(state, b)\n"
        "        stats = np.asarray(gstate)\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == []


def test_fixture_mx309_pragma_and_exemptions():
    src = (
        "import numpy as np\n"
        "def loop(batches, train_step, state):\n"
        "    for b in batches:\n"
        "        state = train_step(state, b)\n"
        "        loss = np.asarray(state[1])  # mxlint: disable=MX309\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    src2 = src.replace("  # mxlint: disable=MX309", "")
    assert _ids(lint_source(src2, "fx.py")) == ["MX309"]
    # the telemetry/profiler timing homes are exempt wholesale
    assert _ids(lint_source(src2, "mxnet_tpu/telemetry/timeline.py")) == []
    assert _ids(lint_source(src2, "mxnet_tpu/utils/profiler.py")) == []


def test_tree_has_no_mx309_findings():
    """ISSUE 9: the tree self-lints clean of implicit host syncs in step
    loops — every intentional per-step pull (guard verdicts, host-metric
    paths, predict's output materialization) carries a justified pragma."""
    from mxnet_tpu.analysis import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX309"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX310 world-size-literal-in-closure fixtures (ISSUE 10) -------------------

def test_fixture_mx310_world_literal_in_closure():
    src = (
        "def build(mesh):\n"
        "    ndev = 8\n"
        "    def step(x):\n"
        "        return x / ndev\n"
        "    return step\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX310"]
    assert findings[0].line == 4  # reported at the stale use
    # name matching covers the whole world-size vocabulary
    src2 = src.replace("ndev", "world_size")
    assert _ids(lint_source(src2, "fx.py")) == ["MX310"]


def test_fixture_mx310_healthy_idioms_clean():
    # derived from the live mesh: a call result, not a frozen literal
    src = (
        "def build(mesh):\n"
        "    ndev = int(mesh.shape['dp'])\n"
        "    def step(x):\n"
        "        return x / ndev\n"
        "    return step\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    # passed as an argument: every (re)build sees the current world
    src2 = (
        "def build():\n"
        "    ndev = 8\n"
        "    def step(x, ndev):\n"
        "        return x / ndev\n"
        "    return step\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == []
    # rebound inside the closure: not a capture
    src3 = (
        "def build():\n"
        "    ndev = 8\n"
        "    def step(x):\n"
        "        ndev = len(x)\n"
        "        return x / ndev\n"
        "    return step\n"
    )
    assert _ids(lint_source(src3, "fx.py")) == []
    # a literal used only in the binding scope is fine (no closure)
    src4 = (
        "def build():\n"
        "    ndev = 8\n"
        "    return list(range(ndev))\n"
    )
    assert _ids(lint_source(src4, "fx.py")) == []
    # the mesh/coordinator providers may define worlds from literals
    src5 = (
        "def build():\n"
        "    ndev = 8\n"
        "    def step(x):\n"
        "        return x / ndev\n"
        "    return step\n"
    )
    assert _ids(lint_source(src5, "mxnet_tpu/parallel/mesh.py")) == []
    assert _ids(lint_source(src5, "mxnet_tpu/resilience/elastic.py")) == []


def test_tree_has_no_mx310_findings():
    """ISSUE 10 satellite: the tree self-lints clean of world-size
    literals frozen into closures — every axis/world size a closure uses
    is derived from the live mesh/kvstore/coordinator or passed in."""
    from mxnet_tpu.analysis import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX310"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX311 fleet-actuation-outside-the-policy-loop fixtures (ISSUE 12) ---------

def test_fixture_mx311_direct_actuation():
    src = (
        "def rebalance(co, kv):\n"
        "    co.kill(3, reason='slow')\n"
        "    co.request_world(4)\n"
        "    kv.set_gradient_compression('int8')\n"
    )
    findings = lint_source(src, "mxnet_tpu/somewhere.py")
    assert _ids(findings) == ["MX311", "MX311", "MX311"]
    assert [f.line for f in findings] == [2, 3, 4]
    # coordinator-shaped receiver names all count for .kill
    src2 = (
        "def f(elastic_co, my_coordinator):\n"
        "    elastic_co.kill()\n"
        "    my_coordinator.kill(1)\n"
    )
    assert _ids(lint_source(src2, "mxnet_tpu/x.py")) == ["MX311", "MX311"]


def test_fixture_mx311_non_actuation_kills_clean():
    # os.kill / process handles are not fleet actuation; an override
    # delegating to its base class is a definition, not a site
    src = (
        "import os\n"
        "def f(proc):\n"
        "    os.kill(123, 9)\n"
        "    proc.kill()\n"
        "class S(Base):\n"
        "    def set_gradient_compression(self, c):\n"
        "        return super().set_gradient_compression(c)\n"
    )
    assert _ids(lint_source(src, "mxnet_tpu/x.py")) == []


def test_fixture_mx311_exemptions_and_pragma():
    src = "def f(co):\n    co.request_world(4)\n"
    # the policy loop and the lever's owner are the sanctioned homes
    assert _ids(lint_source(
        src, "mxnet_tpu/resilience/controller.py")) == []
    assert _ids(lint_source(src, "mxnet_tpu/resilience/elastic.py")) == []
    # tests and examples drive fleets by hand
    assert _ids(lint_source(src, "tests/test_x.py")) == []
    assert _ids(lint_source(src, "examples/distributed/demo.py")) == []
    # deliberate out-of-loop sites carry the audit-record pragma
    src_pr = ("def f(co):\n"
              "    co.request_world(4)  "
              "# mxlint: disable=MX311 - recovery runbook tool\n")
    assert _ids(lint_source(src_pr, "mxnet_tpu/x.py")) == []


def test_tree_has_no_mx311_findings():
    """ISSUE 12 satellite: fleet actuation in the tree flows through the
    FleetController policy loop — the two launch-config sites
    (fit/create_group applying a user's static compression spec) carry
    justified pragmas."""
    from mxnet_tpu.analysis import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX311"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX313 per-leaf-host-stat-loop fixtures (ISSUE 14) -------------------------

def test_fixture_mx313_per_leaf_stat_loop_in_traced_fn():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(params, grads):\n"
        "    stats = {}\n"
        "    for name, g in grads.items():\n"
        "        stats[name] = float(jnp.sum(jnp.abs(g)))\n"
        "    return stats\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX313"]
    assert findings[0].line == 7  # reported at the materializing call
    # .item() / numpy shapes of the same pattern fire too (numpy also
    # trips the general traced-numpy rule MX201 — both are real)
    src2 = src.replace("float(jnp.sum(jnp.abs(g)))", "jnp.sum(g).item()")
    assert "MX313" in _ids(lint_source(src2, "fx.py"))


def test_fixture_mx313_clean_patterns():
    # a pure-jnp per-leaf loop (unrolled at trace) materializes nothing
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(params, grads):\n"
        "    stats = {}\n"
        "    for name, g in grads.items():\n"
        "        stats[name] = jnp.sum(jnp.abs(g))\n"
        "    return stats\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    # the same loop OUTSIDE traced code is host-side tooling (the
    # sanctioned Monitor shape), not a traced-loop hazard
    src2 = (
        "def summarize(grads):\n"
        "    out = {}\n"
        "    for name, g in grads.items():\n"
        "        out[name] = float(abs(g).sum())\n"
        "    return out\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == []
    # loops not over gradient-named values stay clean
    src3 = (
        "import jax\n"
        "@jax.jit\n"
        "def step(params, batches):\n"
        "    for b in batches:\n"
        "        x = float(b)\n"
        "    return x\n"
    )
    assert _ids(lint_source(src3, "fx.py")) == []


def test_fixture_mx313_pragma():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(grads):\n"
        "    out = []\n"
        "    for g in grads:\n"
        "        out.append(float(jnp.sum(g)))  "
        "# mxlint: disable=MX313 - debug tool\n"
        "    return out\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []


def test_tree_has_no_mx313_findings():
    """ISSUE 14 satellite: the tree self-lints clean — per-layer stats
    come from the in-graph health engine, not per-leaf host pulls."""
    from mxnet_tpu.analysis import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX313"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX307 leaked-span fixtures (ISSUE 6 satellite) ----------------------------

def test_fixture_mx307_leaked_span():
    src = (
        "def loop(tl, batches):\n"
        "    for i, b in enumerate(batches):\n"
        "        span = tl.begin_step(0, i)\n"
        "        span.mark('device')\n"
        "        step(b)\n"
    )
    findings = lint_source(src, "fx.py")
    assert _ids(findings) == ["MX307"]
    assert findings[0].line == 3


def test_fixture_mx307_bare_calls():
    # a discarded begin_step can never be ended
    src = (
        "def loop(tl):\n"
        "    tl.begin_step(0, 0)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == ["MX307"]
    # phase()/timed() return context managers; a bare call records nothing
    src2 = (
        "from mxnet_tpu import telemetry\n"
        "def push(kv, grads):\n"
        "    telemetry.phase('kvstore_push')\n"
        "    kv.push_many(grads)\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == ["MX307"]
    src3 = (
        "from mxnet_tpu.telemetry import timed\n"
        "def stage(x):\n"
        "    timed('stage')\n"
        "    return work(x)\n"
    )
    assert _ids(lint_source(src3, "fx.py")) == ["MX307"]


def test_fixture_mx307_clean_patterns():
    # context-manager span: __exit__ closes it
    src = (
        "def loop(tl, batches):\n"
        "    for i, b in enumerate(batches):\n"
        "        with tl.begin_step(0, i) as span:\n"
        "            span.mark('device')\n"
        "            step(b)\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    # explicit .end() anywhere in the function (incl. a finally)
    src2 = (
        "def loop(tl, batches):\n"
        "    for i, b in enumerate(batches):\n"
        "        span = tl.begin_step(0, i)\n"
        "        try:\n"
        "            step(b)\n"
        "        finally:\n"
        "            span.end()\n"
    )
    assert _ids(lint_source(src2, "fx.py")) == []
    # the fit-loop shape: conditional open, conditional end
    src3 = (
        "def loop(tl, batches):\n"
        "    for i, b in enumerate(batches):\n"
        "        span = tl.begin_step(0, i) if tl is not None else None\n"
        "        step(b)\n"
        "        if span is not None:\n"
        "            span.end()\n"
    )
    assert _ids(lint_source(src3, "fx.py")) == []
    # with-entered phase is the sanctioned use
    src4 = (
        "from mxnet_tpu import telemetry\n"
        "def push(kv, grads):\n"
        "    with telemetry.phase('kvstore_push'):\n"
        "        kv.push_many(grads)\n"
    )
    assert _ids(lint_source(src4, "fx.py")) == []


def test_fixture_mx307_pragma_and_exempt_paths():
    src = (
        "def loop(tl):\n"
        "    span = tl.begin_step(0, 0)  # mxlint: disable=MX307\n"
        "    step()\n"
    )
    assert _ids(lint_source(src, "fx.py")) == []
    src2 = src.replace("  # mxlint: disable=MX307", "")
    # the primitives' home is exempt wholesale
    assert _ids(lint_source(
        src2, "mxnet_tpu/telemetry/timeline.py")) == []


def test_tree_has_no_mx307_findings():
    """ISSUE 6 satellite: the tree self-lints clean of leaked spans —
    every begin_step is closed on every path and every phase()/timed()
    is with-entered."""
    from mxnet_tpu.analysis import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX307"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX312 pallas-kernel-discipline fixtures (ISSUE 13) ------------------------

def test_fixture_mx312_pallas_call_outside_layer():
    src = (
        "from jax.experimental import pallas as pl\n"
        "def hot(x):\n"
        "    return pl.pallas_call(k, out_shape=o)(x)\n"
        "def hotter(x):\n"
        "    return pl.pallas_call(k2, out_shape=o)(x)\n"
    )
    findings = lint_source(src, "mxnet_tpu/models/fastnet.py")
    assert [f.rule.id for f in findings] == ["MX312", "MX312"]
    assert [f.line for f in findings] == [3, 5]


def test_fixture_mx312_kernel_module_missing_registry_entry():
    # inside the layer but unpriced: ONE finding per module, at the
    # first pallas_call
    src = (
        "from jax.experimental import pallas as pl\n"
        "def my_kernel(x):\n"
        "    return pl.pallas_call(k, out_shape=o, name='my_kernel')(x)\n"
        "def my_kernel2(x):\n"
        "    return pl.pallas_call(k2, out_shape=o, name='my_kernel2')(x)\n"
    )
    findings = lint_source(src, "mxnet_tpu/ops/pallas/newkern.py")
    assert [f.rule.id for f in findings] == ["MX312"]
    assert findings[0].line == 3
    assert "register" in findings[0].message


def test_fixture_mx312_registered_kernel_module_clean():
    src = (
        "from jax.experimental import pallas as pl\n"
        "from .registry import register_kernel\n"
        "def my_kernel(x):\n"
        "    return pl.pallas_call(k, out_shape=o, name='my_kernel')(x)\n"
        "register_kernel('my_kernel', cost_fn)\n"
    )
    assert [f.rule.id for f in
            lint_source(src, "mxnet_tpu/ops/pallas/newkern.py")] == []
    # modules that never emit a pallas_call owe the registry nothing
    assert lint_source("def f(x):\n    return x\n",
                       "mxnet_tpu/ops/pallas/helpers.py") == []


def test_fixture_mx312_pragma_escape_hatch():
    src = (
        "from jax.experimental import pallas as pl\n"
        "def hot(x):\n"
        "    return pl.pallas_call(k, out_shape=o)(x)"
        "  # mxlint: disable=MX312 - vendored prototype\n"
    )
    assert [f.rule.id for f in
            lint_source(src, "mxnet_tpu/models/fastnet.py")] == []


def test_self_lint_mx312_clean():
    """The kernel layer itself passes its own discipline: every module
    emitting a pallas_call registers a cost model, and no pallas_call
    lives outside ops/pallas/."""
    from mxnet_tpu.analysis.source_lint import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX312"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX314 raw-profiler-capture fixtures (ISSUE 15) ----------------------------

def test_fixture_mx314_raw_jax_profiler_capture():
    # a raw jax.profiler capture outside utils/profiler.py /
    # telemetry/profiling.py: both the start and the raw stop fire
    src = (
        "import jax\n"
        "def cap(d):\n"
        "    jax.profiler.start_trace(d)\n"
        "    run()\n"
        "    jax.profiler.stop_trace()\n"
    )
    findings = lint_source(src, "mxnet_tpu/models/fastnet.py")
    assert [f.rule.id for f in findings] == ["MX314", "MX314"]
    assert [f.line for f in findings] == [3, 5]
    # the context-manager shape fires too, and so does a name bound by
    # `from jax import profiler`
    src2 = (
        "import jax\n"
        "def cap(d):\n"
        "    with jax.profiler.trace(d):\n"
        "        run()\n"
    )
    assert [f.rule.id for f in
            lint_source(src2, "mxnet_tpu/models/fastnet.py")] == ["MX314"]
    src3 = (
        "from jax import profiler\n"
        "def cap(d):\n"
        "    profiler.start_trace(d)\n"
    )
    assert "MX314" in [f.rule.id for f in
                       lint_source(src3, "mxnet_tpu/models/fastnet.py")]


def test_fixture_mx314_unguarded_start_trace():
    # even the sanctioned wrapper fires when its stop is not in a
    # finally: an exception leaks the process-global running trace
    src = (
        "from mxnet_tpu.utils import profiler\n"
        "def cap(d):\n"
        "    profiler.start_trace(d)\n"
        "    run()\n"
        "    profiler.stop_trace()\n"
    )
    findings = lint_source(src, "mxnet_tpu/models/fastnet.py")
    assert [f.rule.id for f in findings] == ["MX314"]
    assert findings[0].line == 3
    assert "finally" in findings[0].message
    # the low-level capture API leaks identically and fires identically
    src2 = (
        "from mxnet_tpu.telemetry import profiling\n"
        "def cap(d):\n"
        "    profiling.start_capture(d)\n"
        "    run()\n"
        "    profiling.stop_capture()\n"
    )
    findings = lint_source(src2, "mxnet_tpu/models/fastnet.py")
    assert [f.rule.id for f in findings] == ["MX314"]
    assert "start_capture" in findings[0].message
    # a nested def inside a try body owns ITS start: the outer finally
    # cannot guard a deferred body that runs after the finally fired
    src3 = (
        "from mxnet_tpu.utils import profiler\n"
        "def f(d):\n"
        "    try:\n"
        "        def helper():\n"
        "            profiler.start_trace(d)\n"
        "        register(helper)\n"
        "    finally:\n"
        "        profiler.stop_trace()\n"
    )
    findings = lint_source(src3, "mxnet_tpu/models/fastnet.py")
    assert [f.line for f in findings] == [5], findings


def test_fixture_mx314_guarded_and_capture_clean():
    # finally-guarded stop: clean
    src = (
        "from mxnet_tpu.utils import profiler\n"
        "def cap(d):\n"
        "    profiler.start_trace(d)\n"
        "    try:\n"
        "        run()\n"
        "    finally:\n"
        "        profiler.stop_trace()\n"
    )
    assert lint_source(src, "mxnet_tpu/models/fastnet.py") == []
    # the sanctioned capture() context manager: clean
    src2 = (
        "from mxnet_tpu.telemetry import profiling\n"
        "def cap(d):\n"
        "    with profiling.capture(d):\n"
        "        run()\n"
    )
    assert lint_source(src2, "mxnet_tpu/models/fastnet.py") == []
    # a second function's finally does NOT excuse this one's bare start
    src3 = (
        "from mxnet_tpu.utils import profiler\n"
        "def bare(d):\n"
        "    profiler.start_trace(d)\n"
        "def guarded(d):\n"
        "    profiler.start_trace(d)\n"
        "    try:\n"
        "        run()\n"
        "    finally:\n"
        "        profiler.stop_trace()\n"
    )
    findings = lint_source(src3, "mxnet_tpu/models/fastnet.py")
    assert [f.line for f in findings] == [3]


def test_fixture_mx314_pragma_and_owner_exemptions():
    src = (
        "import jax\n"
        "def cap(d):\n"
        "    jax.profiler.start_trace(d)"
        "  # mxlint: disable=MX314 - raw capture for the xprof UI\n"
    )
    assert lint_source(src, "mxnet_tpu/models/fastnet.py") == []
    # the owner modules ARE the sanctioned doorway
    raw = (
        "import jax\n"
        "def start_capture(d):\n"
        "    jax.profiler.start_trace(d)\n"
    )
    assert lint_source(raw, "mxnet_tpu/telemetry/profiling.py") == []
    assert lint_source(raw, "mxnet_tpu/utils/profiler.py") == []


def test_self_lint_mx314_clean():
    """No raw jax.profiler captures outside the profiling layer, and no
    unguarded start_trace anywhere in the tree."""
    from mxnet_tpu.analysis.source_lint import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX314"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX315 checkpoint-discipline fixtures (ISSUE 17 satellite) -----------------

def test_fixture_mx315_direct_save_sharded():
    # a direct durable write outside the checkpoint plane: races the
    # async writer's `.tmp.<step>` staging, dodges retention GC and the
    # `checkpoint` badput pricing
    src = (
        "from mxnet_tpu.utils import checkpoint as ck\n"
        "def snapshot(d, step, params):\n"
        "    ck.save_sharded(d, step, params)\n"
    )
    findings = lint_source(src, "mxnet_tpu/models/fastnet.py")
    assert [f.rule.id for f in findings] == ["MX315"]
    assert "durability ordering" in findings[0].message

    # the private staging helpers are just as off-limits
    src2 = (
        "from mxnet_tpu.utils.checkpoint import _write_manifest\n"
        "def stage(d, shards):\n"
        "    _write_manifest(d, shards)\n"
    )
    assert [f.rule.id for f in
            lint_source(src2, "mxnet_tpu/models/fastnet.py")] == ["MX315"]


def test_fixture_mx315_reads_and_sanctioned_paths_clean():
    # loads / latest_step / the ckpt_async doorway never match
    src = (
        "from mxnet_tpu.utils import checkpoint as ck\n"
        "from mxnet_tpu.resilience import ckpt_async\n"
        "def resume(d, w):\n"
        "    step = ck.latest_step(d)\n"
        "    state = ck.load_sharded(d, step)\n"
        "    ckpt_async.save_now(d, step, state[0], symbol=None)\n"
        "    w.submit(None)\n"
        "    return state\n"
    )
    assert lint_source(src, "mxnet_tpu/models/fastnet.py") == []


def test_fixture_mx315_pragma_and_owner_exemptions():
    src = (
        "from mxnet_tpu.utils import checkpoint as ck\n"
        "def snapshot(d, step, params):\n"
        "    ck.save_sharded(d, step, params)"
        "  # mxlint: disable=MX315 - migration shim, bypasses GC on purpose\n"
    )
    assert lint_source(src, "mxnet_tpu/models/fastnet.py") == []
    # the owner modules ARE the checkpoint plane
    raw = (
        "def save_now(d, step, params):\n"
        "    return save_sharded(d, step, params)\n"
    )
    assert lint_source(raw, "mxnet_tpu/utils/checkpoint.py") == []
    assert lint_source(raw, "mxnet_tpu/resilience/ckpt_async.py") == []
    # tests drive save_sharded directly all over — exempt
    assert lint_source(raw, "tests/test_sharded_checkpoint.py") == []


def test_self_lint_mx315_clean():
    """Every durable checkpoint write in the tree flows through the
    checkpoint plane (utils/checkpoint.py + resilience/ckpt_async.py)."""
    from mxnet_tpu.analysis.source_lint import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX315"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX316 run-ledger-discipline fixtures (ISSUE 20 satellite) -----------------

def test_fixture_mx316_env_consultation_and_summary_emit():
    # a layer resolving the ledger dir itself to write its own summary
    # file: un-CRC'd records the trend/compare gates cannot read, plus a
    # duplicate run_summary event skewing the golden-key stream
    src = (
        "import os, json\n"
        "def summarize(hub, outcomes):\n"
        "    d = os.environ.get('MXNET_TPU_LEDGER_DIR')\n"
        "    with open(os.path.join(d, 'summary.json'), 'w') as f:\n"
        "        json.dump(outcomes, f)\n"
        "    hub.emit('run_summary', run_id='abc')\n"
    )
    findings = lint_source(src, "mxnet_tpu/models/fastnet.py")
    assert [f.rule.id for f in findings] == ["MX316", "MX316"]
    assert "ledger_dir()" in findings[0].message
    assert "run_summary" in findings[1].message

    # writing the env var directly is the same bypass
    src2 = (
        "import os\n"
        "def redirect(d):\n"
        "    os.environ['MXNET_TPU_LEDGER_DIR'] = d\n"
    )
    assert [f.rule.id for f in
            lint_source(src2, "mxnet_tpu/models/fastnet.py")] == ["MX316"]


def test_fixture_mx316_sanctioned_paths_clean():
    # the sanctioned shapes: ledger_dir()/record_run/publish_bench, other
    # env vars, other emit kinds — and monkeypatch.setenv (keyword "key"
    # position is not the getter-call shape MX316 matches)
    src = (
        "import os\n"
        "def ok(hub, monkeypatch):\n"
        "    from mxnet_tpu.telemetry import ledger\n"
        "    monkeypatch.setenv('MXNET_TPU_LEDGER_DIR', '/tmp/x')\n"
        "    d = ledger.ledger_dir()\n"
        "    ledger.record_run('fit', fingerprint='fp')\n"
        "    flight = os.environ.get('MXNET_TPU_FLIGHT_DIR')\n"
        "    hub.emit('epoch_summary', mfu_pct=1.0)\n"
    )
    assert lint_source(src, "mxnet_tpu/models/fastnet.py") == []


def test_fixture_mx316_pragma_and_owner_exemptions():
    src = (
        "import os\n"
        "def probe(hub):\n"
        "    d = os.environ.get('MXNET_TPU_LEDGER_DIR')"
        "  # mxlint: disable=MX316 - launcher probe, read-only\n"
    )
    assert lint_source(src, "mxnet_tpu/models/fastnet.py") == []
    # the owner module IS the ledger
    raw = (
        "import os\n"
        "def ledger_dir():\n"
        "    return os.environ.get('MXNET_TPU_LEDGER_DIR') or None\n"
        "def announce(hub, rec):\n"
        "    hub.emit('run_summary', run_id=rec['run_id'])\n"
    )
    assert lint_source(raw, "mxnet_tpu/telemetry/ledger.py") == []
    # tests point the store at tmpdirs constantly — exempt
    assert lint_source(raw, "tests/test_ledger.py") == []


def test_self_lint_mx316_clean():
    """Every run-summary write in the tree flows through
    telemetry/ledger.py (the one writer the gates can read)."""
    from mxnet_tpu.analysis.source_lint import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX316"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- MX308 unpinned-wire-collective fixtures (ISSUE 7 satellite) ---------------

def test_fixture_mx308_unpinned_collective():
    # a wire collective in comm/ with no optimization_barrier anywhere:
    # XLA can commute the encode/decode casts across it (fp32 on the
    # wire, compression silently lost — allreduce.py's documented bug
    # class)
    src = (
        "import jax.lax as lax\n"
        "def exchange(q, axis):\n"
        "    s = lax.all_to_all(q, axis, 0, 0)\n"
        "    return lax.all_gather(s, axis)\n"
    )
    findings = lint_source(src, "mxnet_tpu/comm/fx.py")
    assert _ids(findings) == ["MX308", "MX308"]
    assert sorted(f.line for f in findings) == [3, 4]
    # pinned on one side only is still flagged (the convert commutes
    # across whichever side is open)
    src2 = (
        "import jax.lax as lax\n"
        "def exchange(q, axis):\n"
        "    (q,) = lax.optimization_barrier((q,))\n"
        "    return lax.all_to_all(q, axis, 0, 0)\n"
    )
    assert _ids(lint_source(src2, "mxnet_tpu/comm/fx.py")) == ["MX308"]


def test_fixture_mx308_pinned_and_out_of_scope():
    # barriers lexically before AND after the collective: clean
    src = (
        "import jax.lax as lax\n"
        "def exchange(q, axis):\n"
        "    (q,) = lax.optimization_barrier((q,))\n"
        "    s = lax.all_to_all(q, axis, 0, 0)\n"
        "    g = lax.all_gather(s, axis)\n"
        "    (g,) = lax.optimization_barrier((g,))\n"
        "    return g\n"
    )
    assert _ids(lint_source(src, "mxnet_tpu/comm/fx.py")) == []
    # the rule is scoped to comm/: collectives elsewhere are not its
    # business (MX304 polices raw grad psums outside comm/)
    src2 = (
        "import jax.lax as lax\n"
        "def gather(q, axis):\n"
        "    return lax.all_gather(q, axis)\n"
    )
    assert _ids(lint_source(src2, "mxnet_tpu/parallel/fx.py")) == []
    # nested defs are their own scope: an inner pinned exchange does not
    # excuse an outer bare one
    src3 = (
        "import jax.lax as lax\n"
        "def outer(q, axis):\n"
        "    def inner(v):\n"
        "        (v,) = lax.optimization_barrier((v,))\n"
        "        v = lax.all_to_all(v, axis, 0, 0)\n"
        "        (v,) = lax.optimization_barrier((v,))\n"
        "        return v\n"
        "    return lax.all_gather(inner(q), axis)\n"
    )
    assert _ids(lint_source(src3, "mxnet_tpu/comm/fx.py")) == ["MX308"]


def test_fixture_mx308_lambda_and_module_scopes():
    # a lambda body is its own scope: an unpinned collective in one
    # cannot hide behind barriers in the enclosing function
    src = (
        "import jax.lax as lax\n"
        "def exchange(q, axis):\n"
        "    (q,) = lax.optimization_barrier((q,))\n"
        "    f = lambda v: lax.all_gather(v, axis)\n"
        "    (q,) = lax.optimization_barrier((q,))\n"
        "    return f(q)\n"
    )
    findings = lint_source(src, "mxnet_tpu/comm/fx.py")
    assert _ids(findings) == ["MX308"]
    assert findings[0].line == 4
    # module-level collectives are scanned too
    src2 = (
        "import jax.lax as lax\n"
        "OUT = lax.all_to_all(IN, 'dp', 0, 0)\n"
    )
    assert _ids(lint_source(src2, "mxnet_tpu/comm/fx.py")) == ["MX308"]


def test_fixture_mx308_pragma_suppression():
    src = (
        "import jax.lax as lax\n"
        "def exchange(q, axis):\n"
        "    return lax.all_to_all(q, axis, 0, 0)"
        "  # mxlint: disable=MX308\n"
    )
    assert _ids(lint_source(src, "mxnet_tpu/comm/fx.py")) == []
    src2 = src.replace("  # mxlint: disable=MX308", "")
    assert _ids(lint_source(src2, "mxnet_tpu/comm/fx.py")) == ["MX308"]


def test_tree_has_no_mx308_findings():
    """ISSUE 7 satellite: the tree self-lints clean — every wire
    collective in comm/ (fused AND per-bucket paths) is barrier-pinned
    on both sides."""
    from mxnet_tpu.analysis import lint_paths

    findings = [f for f in lint_paths([os.path.join(REPO, "mxnet_tpu")])
                if f.rule.id == "MX308"]
    assert not findings, "\n".join(f.format() for f in findings)


# -- Pass 2: graph verifier fixtures ------------------------------------------

def test_fixture_duplicate_argument():
    g = mx.sym.Variable("x") + mx.sym.Variable("x")
    findings = [f for f in verify_symbol(g, {"x": (2, 2)}) if f.is_error]
    assert _ids(findings) == ["MX401"]
    with pytest.raises(MXNetError, match="MX401"):
        g.verify(arg_shapes={"x": (2, 2)})


def test_fixture_shape_conflict():
    data = mx.sym.Variable("data")
    fc = mx.symbol.FullyConnected(data=data, num_hidden=3, name="fc1")
    bad = fc + data  # (4,3) + (4,5)
    findings = [f for f in verify_symbol(bad, {"data": (4, 5)})
                if f.is_error]
    assert _ids(findings) == ["MX402"]
    msg = findings[0].message
    assert "_Plus" in msg and "input chain" in msg  # op + chain named
    with pytest.raises(MXNetError, match="MX402"):
        bad.verify(arg_shapes={"data": (4, 5)})


def test_fixture_dtype_conflict():
    lhs = mx.sym.Variable("l", shape=(2, 2), dtype=np.float32)
    rhs = mx.sym.Variable("r", shape=(2, 2), dtype=np.float16)
    with pytest.raises(MXNetError, match="MX403"):
        (lhs + rhs).verify()


def test_embedding_mixed_dtypes_allowed():
    """Embedding is heterogeneous by design: int ids + float table."""
    emb = mx.symbol.Embedding(data=mx.sym.Variable("tokens"),
                              input_dim=16, output_dim=4, name="emb")
    findings = emb.verify(arg_shapes={"tokens": (2, 8)},
                          arg_dtypes={"tokens": np.int32,
                                      "emb_weight": np.float32})
    assert not [f for f in findings if f.is_error]


def test_unused_output_warning():
    split = mx.symbol.SliceChannel(mx.sym.Variable("data"), num_outputs=2,
                                   name="split")
    one_head = split[0]  # output 1 computed, never consumed
    findings = one_head.verify(arg_shapes={"data": (4, 6)})
    assert "MX404" in _ids(findings)
    assert not [f for f in findings if f.is_error]  # warning only


def test_unreachable_node_in_json():
    net = mx.symbol.FullyConnected(data=mx.sym.Variable("data"),
                                   num_hidden=3, name="fc1")
    import json

    graph = json.loads(net.tojson())
    graph["nodes"].append({"op": "null", "name": "orphan", "inputs": []})
    findings = verify_json(json.dumps(graph))
    assert "MX405" in _ids(findings)


def test_verify_runs_on_bind():
    """Acceptance: bind invokes verify automatically and names the node."""
    import mxnet_tpu.ndarray as nd

    net = mx.symbol.FullyConnected(data=mx.sym.Variable("data"),
                                   num_hidden=3, name="fc1")
    args = {"data": nd.zeros((4, 5)), "fc1_weight": nd.zeros((3, 9)),
            "fc1_bias": nd.zeros((3,))}
    with pytest.raises(MXNetError) as ei:
        net.bind(mx.cpu(), args)
    assert "fc1" in str(ei.value) and "MX402" in str(ei.value)
    # the env gate turns it off (failure then happens later, at trace)
    os.environ["MXNET_TPU_VERIFY"] = "0"
    try:
        net.bind(mx.cpu(), args)  # bind itself now succeeds
    finally:
        del os.environ["MXNET_TPU_VERIFY"]


# -- Pass 3: jaxpr audit ------------------------------------------------------

def test_jaxpr_audit_costs_and_promotion():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.analysis import audit_executor, audit_jaxpr

    net = mx.symbol.FullyConnected(data=mx.sym.Variable("data"),
                                   num_hidden=8, name="fc1")
    exe = net.simple_bind(mx.cpu(), data=(16, 32))
    rep = audit_executor(exe)
    assert not rep.errors
    by_prim = {r["primitive"]: r for r in rep.rows}
    # FC = x@W.T + b: 2*M*N*K MACs-as-flops
    assert by_prim["dot_general"]["flops"] == 2 * 16 * 32 * 8
    assert rep.totals["bytes"] > 0

    def leaky(x):
        return x.astype(jnp.float32) * 2.0

    closed = jax.make_jaxpr(leaky)(jnp.ones((4, 4), jnp.bfloat16))
    rep2 = audit_jaxpr(closed, intended_dtype=jnp.bfloat16)
    assert "MX502" in [f.rule.id for f in rep2.findings]


# -- MX70x: concurrency pass (ISSUE 11) ---------------------------------------

def _cc_ids(src):
    from mxnet_tpu.analysis import concurrency

    return [f.rule.id for f in concurrency.lint_source(src, "fx.py")]


def test_fixture_mx701_unlocked_shared_attr():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "        self._t = threading.Thread(target=self._work,\n"
        "                                   daemon=True)\n"
        "    def _work(self):\n"
        "        self.count += 1\n"
        "    def bump(self):\n"
        "        self.count += 1\n"
    )
    findings = [f for f in _cc_ids(src)]
    assert findings == ["MX701"]


def test_fixture_mx701_common_lock_is_clean():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "        self._t = threading.Thread(target=self._work,\n"
        "                                   daemon=True)\n"
        "    def _work(self):\n"
        "        with self._lock:\n"
        "            self.count += 1\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.count += 1\n"
    )
    assert _cc_ids(src) == []


def test_fixture_mx701_weakref_callback_and_container_mutator():
    """GC-callback entry point + .append() mutator (the ledger shape)."""
    src = (
        "import threading\n"
        "import weakref\n"
        "class Ledger:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.rows = []\n"
        "    def add(self, arr):\n"
        "        ref = weakref.ref(arr, self._on_dead)\n"
        "        self.rows.append(ref)\n"
        "    def _on_dead(self, ref):\n"
        "        self.rows.remove(ref)\n"
    )
    assert _cc_ids(src) == ["MX701"]


def test_fixture_mx701_private_helper_under_lock_is_clean():
    """The guaranteed-held-lock inference: a private helper whose every
    call site holds the lock needs no pragma."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "        self._t = threading.Thread(target=self._work,\n"
        "                                   daemon=True)\n"
        "    def _bump_locked(self):\n"
        "        self.n += 1\n"
        "    def _work(self):\n"
        "        with self._lock:\n"
        "            self._bump_locked()\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._bump_locked()\n"
    )
    assert _cc_ids(src) == []


def test_fixture_mx702_lock_order_inversion():
    src = (
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def f():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def g():\n"
        "    with B:\n"
        "        with A:\n"
        "            pass\n"
    )
    assert _cc_ids(src) == ["MX702"]


def test_fixture_mx702_consistent_order_is_clean():
    src = (
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def f():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def g():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
    )
    assert _cc_ids(src) == []


def test_fixture_mx702_via_call_hop():
    """The one-hop edge: holding A while calling a method that takes B,
    against a method taking them in the other order."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def _take_b(self):\n"
        "        with self._b:\n"
        "            pass\n"
        "    def f(self):\n"
        "        with self._a:\n"
        "            self._take_b()\n"
        "    def g(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    assert "MX702" in _cc_ids(src)


def test_fixture_mx703_bare_wait():
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.lock = threading.Lock()\n"
        "        self.cv = threading.Condition(self.lock)\n"
        "    def bad(self):\n"
        "        with self.cv:\n"
        "            self.cv.wait()\n"
    )
    assert _cc_ids(src) == ["MX703"]


def test_fixture_mx703_wait_for_and_loop_are_clean():
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.lock = threading.Lock()\n"
        "        self.cv = threading.Condition(self.lock)\n"
        "        self.ready = False\n"
        "    def ok1(self):\n"
        "        with self.cv:\n"
        "            self.cv.wait_for(lambda: self.ready)\n"
        "    def ok2(self):\n"
        "        with self.cv:\n"
        "            while not self.ready:\n"
        "                self.cv.wait()\n"
    )
    assert _cc_ids(src) == []


def test_fixture_mx704_unjoined_non_daemon_thread():
    src = (
        "import threading\n"
        "def spawn():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
    )
    assert _cc_ids(src) == ["MX704"]


def test_fixture_mx704_daemon_or_joined_is_clean():
    src = (
        "import threading\n"
        "def ok1():\n"
        "    threading.Thread(target=print, daemon=True).start()\n"
        "def ok2():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
        "    t.join()\n"
        "class C:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=print)\n"
        "        self._t.start()\n"
        "    def stop(self):\n"
        "        self._t.join()\n"
    )
    assert _cc_ids(src) == []


def test_fixture_mx705_fresh_lock():
    """The real-world citation: comm/stats.py:161 (pre-fix) locked
    `getattr(self, '_lock', threading.Lock())` — a fresh private lock
    whenever _lock was missing, guarding nothing."""
    src = (
        "import threading\n"
        "class R:\n"
        "    def reset(self):\n"
        "        with getattr(self, '_lock', threading.Lock()):\n"
        "            self.x = 1\n"
        "def direct():\n"
        "    with threading.Lock():\n"
        "        pass\n"
    )
    ids = _cc_ids(src)
    assert ids == ["MX705", "MX705"]


def test_fixture_mx705_reused_lock_is_clean():
    src = (
        "import threading\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def reset(self):\n"
        "        with self._lock:\n"
        "            self.x = 1\n"
    )
    assert _cc_ids(src) == []


def test_fixture_mx70x_pragma_suppression():
    src = (
        "import threading\n"
        "def spawn():\n"
        "    t = threading.Thread(target=print)  "
        "# mxlint: disable=MX704 - joined by the caller\n"
        "    t.start()\n"
    )
    assert _cc_ids(src) == []


def test_concurrency_lockwatch_factory_counts_as_lock_ctor():
    """Locks built by the analysis.lockwatch factory are first-class in
    the static model: same rules, same aliasing."""
    src = (
        "from mxnet_tpu.analysis.lockwatch import named_condition, "
        "named_lock\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.lock = named_lock('s')\n"
        "        self.cv = named_condition('s.cv', self.lock)\n"
        "    def bad(self):\n"
        "        with self.cv:\n"
        "            self.cv.wait()\n"
    )
    assert _cc_ids(src) == ["MX703"]


def test_self_lint_concurrency_clean():
    """ISSUE 11 gate: the tree self-lints MX701-MX705 clean (fixed or
    pragma'd with a justification)."""
    from mxnet_tpu.analysis import concurrency

    findings = [f for f in concurrency.lint_paths(
        [os.path.join(REPO, "mxnet_tpu")])
        if f.rule.id.startswith("MX70")]
    assert not findings, "\n".join(f.format() for f in findings)


def test_cli_concurrency_flag(tmp_path):
    """`python -m mxnet_tpu.analysis --concurrency` reports MX70x."""
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import threading\n"
        "def spawn():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n")
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--concurrency",
         str(bad)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr  # warning-grade
    assert "MX704" in proc.stdout
    # and --warnings-as-errors promotes it to a failing exit
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--concurrency",
         "--warnings-as-errors", str(bad)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=240)
    assert proc.returncode == 1


# -- the self-lint gate -------------------------------------------------------

def test_self_lint_package_clean():
    """mxlint over mxnet_tpu/ itself: zero errors (warnings allowed)."""
    from mxnet_tpu.analysis import lint_paths

    findings = lint_paths([os.path.join(REPO, "mxnet_tpu")])
    errors = [f for f in findings if f.is_error]
    assert not errors, "\n".join(f.format() for f in errors)


@pytest.mark.parametrize("target,expect_ok", [
    (os.path.join(REPO, "mxnet_tpu"), True),
    (None, False),  # seeded violation file, built in the test
])
def test_cli_exit_codes(tmp_path, target, expect_ok):
    """Acceptance: `python -m mxnet_tpu.analysis mxnet_tpu/` exits 0; a
    seeded violation makes it exit non-zero with the rule id printed."""
    if target is None:
        bad = tmp_path / "seeded.py"
        bad.write_text("from jax import shard_map\n")
        target = str(bad)
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", target],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=240)
    if expect_ok:
        assert proc.returncode == 0, proc.stdout + proc.stderr
    else:
        assert proc.returncode == 1
        assert "MX101" in proc.stdout
