"""Extended augmenter flags (VERDICT r3 item 6): rotate / rotate_list,
min/max_random_scale, min/max_img_size, max_random_contrast,
max_random_illumination, fixed mirror — in both the PIL and native paths.

Reference semantics: src/io/image_augmenter.h:40-79 (geometric: fixed
rotate overrides max_rotate_angle, rotate_list overrides both; scale
s ~ U[min,max] with per-dimension clamp to [min_img_size, max_img_size])
and src/io/iter_normalize.h:173-201 (photometric: out = ((px - mean) * c
+ i) * scale, c ~ U[1-mc, 1+mc], i ~ U[-mi, mi]).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import native as native_mod
from mxnet_tpu import recordio as rio


def _make_jpeg_rec(tmp_path, n=8, size=32, quality=95, name="imgs.rec"):
    path = str(tmp_path / name)
    w = rio.MXRecordIO(path, "w")
    imgs = []
    for i in range(n):
        yy, xx = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
        img = np.stack([(yy * 255 / size), (xx * 255 / size),
                        np.full_like(yy, (i * 13) % 255)],
                       axis=-1).astype(np.uint8)
        imgs.append(img)
        w.write(rio.pack_img(rio.IRHeader(0, float(i), i, 0), img,
                             quality=quality, img_fmt=".jpg"))
    w.close()
    return path, imgs


def _decoded(path, n):
    """The images exactly as the iterator's decoder sees them (JPEG is
    lossy, so expectations are built from the decoded pixels)."""
    r = rio.MXRecordIO(path, "r")
    out = []
    for _ in range(n):
        _, img = rio.unpack_img(r.read())
        out.append(img.astype(np.float32))
    r.close()
    return out


def _batches_chw(it):
    out = []
    for b in it:
        out.extend(np.asarray(b.data[0].asnumpy()))
    return out


# ---------------------------------------------------------------- PIL path

def test_rotate_fixed_180(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    path, _ = _make_jpeg_rec(tmp_path, n=4, size=32)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=4, rotate=180)
    assert it._native is None  # rotation routes around the native pipeline
    got = _batches_chw(it)
    for img, chw in zip(_decoded(path, 4), got):
        expect = img[::-1, ::-1].transpose(2, 0, 1)  # 180 deg is exact
        np.testing.assert_allclose(chw, expect, atol=1.0)


def test_rotate_list_picks_from_list(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    path, _ = _make_jpeg_rec(tmp_path, n=16, size=32)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=16, rotate_list="90,270", seed=3)
    got = _batches_chw(it)
    hits = set()
    for img, chw in zip(_decoded(path, 16), got):
        # PIL rotates counterclockwise; 90/270 on a square image are exact
        cands = {90: np.rot90(img, 1), 270: np.rot90(img, 3)}
        matched = None
        for ang, exp in cands.items():
            if np.allclose(chw, exp.transpose(2, 0, 1), atol=1.0):
                matched = ang
                break
        assert matched is not None, "image matches neither listed angle"
        hits.add(matched)
    assert hits == {90, 270}, f"both angles should occur, saw {hits}"


def test_random_scale_deterministic_when_pinned(tmp_path, monkeypatch):
    """min=max_random_scale pins the draw: 64px input at scale 0.5 becomes
    exactly the 32px resize (crop is then the identity)."""
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    from PIL import Image

    path, _ = _make_jpeg_rec(tmp_path, n=4, size=64)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=4, min_random_scale=0.5,
                             max_random_scale=0.5)
    got = _batches_chw(it)
    for img, chw in zip(_decoded(path, 4), got):
        expect = np.asarray(
            Image.fromarray(img.astype(np.uint8)).resize((32, 32)),
            dtype=np.float32).transpose(2, 0, 1)
        np.testing.assert_allclose(chw, expect, atol=1.0)


def test_img_size_clamp(tmp_path, monkeypatch):
    """Upscale by 2 with max_img_size=48: dims clamp to 48 (not 64), then
    the center crop takes 32."""
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    from PIL import Image

    path, _ = _make_jpeg_rec(tmp_path, n=4, size=32)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=4, min_random_scale=2.0,
                             max_random_scale=2.0, max_img_size=48)
    got = _batches_chw(it)
    for img, chw in zip(_decoded(path, 4), got):
        up = np.asarray(
            Image.fromarray(img.astype(np.uint8)).resize((48, 48)),
            dtype=np.float32)
        expect = up[8:40, 8:40].transpose(2, 0, 1)  # center 32 of 48
        np.testing.assert_allclose(chw, expect, atol=1.0)


def test_illumination_adds_bounded_constant(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=32)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=8, max_random_illumination=50,
                             seed=11)
    got = _batches_chw(it)
    offsets = []
    for img, chw in zip(_decoded(path, 8), got):
        diff = chw - img.transpose(2, 0, 1)
        off = float(np.mean(diff))
        assert abs(off) <= 50.0 + 1e-3
        np.testing.assert_allclose(diff, off, atol=1e-3)  # constant/image
        offsets.append(round(off, 3))
    assert len(set(offsets)) > 1, "illumination draw should vary per image"


def test_contrast_scales_about_mean(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_NATIVE_IO", "0")
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=32)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=8, max_random_contrast=0.5, seed=5)
    got = _batches_chw(it)
    factors = []
    for img, chw in zip(_decoded(path, 8), got):
        base = img.transpose(2, 0, 1)
        c = float(np.sum(chw * base) / np.sum(base * base))  # lsq factor
        assert 0.5 - 1e-3 <= c <= 1.5 + 1e-3
        np.testing.assert_allclose(chw, base * c, atol=1e-2)
        factors.append(round(c, 4))
    assert len(set(factors)) > 1, "contrast draw should vary per image"


def test_uint8_output_rejects_photometric(tmp_path):
    path, _ = _make_jpeg_rec(tmp_path, n=4, size=32)
    with pytest.raises(mx.base.MXNetError):
        mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                            batch_size=4, output_dtype="uint8",
                            max_random_contrast=0.5)


def test_scale_range_validation(tmp_path):
    path, _ = _make_jpeg_rec(tmp_path, n=4, size=32)
    with pytest.raises(mx.base.MXNetError):
        mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                            batch_size=4, min_random_scale=1.5,
                            max_random_scale=0.5)


# ------------------------------------------------------------- native path

needs_native = pytest.mark.skipif(native_mod.get_lib() is None,
                                  reason="native library unavailable")


@needs_native
def test_native_stays_on_fast_path_for_new_flags(tmp_path):
    """Scale/img-size/photometric/fixed-mirror run natively; rotation still
    routes to the PIL path."""
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=64)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                             batch_size=8, min_random_scale=0.8,
                             max_random_scale=1.2, max_random_contrast=0.2,
                             mirror=True)
    assert it._native is not None
    it2 = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                              batch_size=8, rotate=90)
    assert it2._native is None


@needs_native
def test_native_pinned_scale_equals_resize_short(tmp_path):
    """scale 0.5 on 64px input takes the same ResizeBilinear as
    resize_short=32 — byte-identical outputs."""
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=64)
    offs = native_mod.scan_offsets(path)
    a = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32),
                                  min_random_scale=0.5, max_random_scale=0.5)
    b = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32),
                                  resize=32)
    da, _, _ = a.next()
    db, _, _ = b.next()
    np.testing.assert_array_equal(da, db)


@needs_native
def test_native_img_size_clamp_identity(tmp_path):
    """Upscale by 2 clamped back to the source size is the identity."""
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=64)
    offs = native_mod.scan_offsets(path)
    a = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32),
                                  min_random_scale=2.0, max_random_scale=2.0,
                                  max_img_size=64.0)
    b = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32))
    da, _, _ = a.next()
    db, _, _ = b.next()
    np.testing.assert_array_equal(da, db)


@needs_native
def test_native_illumination_bounded_constant(tmp_path):
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=32)
    offs = native_mod.scan_offsets(path)
    a = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32),
                                  max_random_illumination=50.0, seed=7)
    b = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32))
    da, _, _ = a.next()
    db, _, _ = b.next()
    offsets = set()
    for i in range(8):
        diff = da[i] - db[i]
        off = float(np.mean(diff))
        assert abs(off) <= 50.0 + 1e-3
        np.testing.assert_allclose(diff, off, atol=1e-3)
        offsets.add(round(off, 3))
    assert len(offsets) > 1


@needs_native
def test_native_contrast_bounded_factor(tmp_path):
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=32)
    offs = native_mod.scan_offsets(path)
    a = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32),
                                  max_random_contrast=0.5, seed=7)
    b = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32))
    da, _, _ = a.next()
    db, _, _ = b.next()
    factors = set()
    for i in range(8):
        c = float(np.sum(da[i] * db[i]) / np.sum(db[i] * db[i]))
        assert 0.5 - 1e-3 <= c <= 1.5 + 1e-3
        np.testing.assert_allclose(da[i], db[i] * c, atol=1e-2)
        factors.add(round(c, 4))
    assert len(factors) > 1


@needs_native
def test_native_fixed_mirror(tmp_path):
    path, _ = _make_jpeg_rec(tmp_path, n=8, size=32)
    offs = native_mod.scan_offsets(path)
    a = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32),
                                  mirror=True)
    b = native_mod.NativePipeline(path, offs, batch=8, data_shape=(3, 32, 32))
    da, _, _ = a.next()
    db, _, _ = b.next()
    np.testing.assert_array_equal(da, db[:, :, :, ::-1])  # NCHW: flip W


@needs_native
def test_native_u8_rejects_photometric(tmp_path):
    path, _ = _make_jpeg_rec(tmp_path, n=4, size=32)
    offs = native_mod.scan_offsets(path)
    with pytest.raises(ValueError):
        native_mod.NativePipeline(path, offs, batch=4, data_shape=(3, 32, 32),
                                  out_u8=True, max_random_illumination=10.0)
