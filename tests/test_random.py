"""PRNG tests (reference: tests/python/unittest/test_random.py — moment
checks + seed determinism)."""

import numpy as np

import mxnet_tpu as mx


def test_uniform_moments():
    a, b = -10, 10
    shape = (100, 100)
    mx.random.seed(128)
    ret1 = mx.random.uniform(a, b, shape)
    mx.random.seed(128)
    ret2 = mx.random.uniform(a, b, shape)
    assert np.allclose(ret1.asnumpy(), ret2.asnumpy())
    un1 = ret1.asnumpy()
    assert abs(un1.mean() - (a + b) / 2) < 0.1
    assert un1.min() >= a and un1.max() < b


def test_normal_moments():
    mu, sigma = 10.0, 2.0
    shape = (100, 100)
    mx.random.seed(42)
    ret1 = mx.random.normal(mu, sigma, shape)
    mx.random.seed(42)
    ret2 = mx.random.normal(mu, sigma, shape)
    assert np.allclose(ret1.asnumpy(), ret2.asnumpy())
    arr = ret1.asnumpy()
    assert abs(arr.mean() - mu) < 0.1
    assert abs(arr.std() - sigma) < 0.1


def test_uniform_out_param():
    out = mx.nd.zeros((50, 50))
    mx.random.uniform(0, 1, out=out)
    arr = out.asnumpy()
    assert arr.min() >= 0 and arr.max() < 1
    assert arr.std() > 0


def test_different_draws():
    a = mx.random.uniform(0, 1, (10,)).asnumpy()
    b = mx.random.uniform(0, 1, (10,)).asnumpy()
    assert not np.allclose(a, b)


def test_randint():
    r = mx.random.randint(0, 5, (1000,)).asnumpy()
    assert r.min() >= 0 and r.max() < 5
