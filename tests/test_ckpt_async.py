"""Asynchronous multi-tier checkpointing tests (ISSUE 17).

Covers: the T0 bounded-queue background writer (drain, drop-oldest
backpressure, failure-as-incident), retention GC racing latest_step, the
T1 in-memory peer tier (ReplicaStore dedup/restore/drop + the kvstore
`replica` wire op on both the in-process group server and the dist_async
socket host), legacy save_checkpoint/load_checkpoint atomicity with CRC
sidecars, and the acceptance scenarios: a mid-epoch kill resuming
step-granular and bitwise-equal to a checkpoint-replay reference (torn
T2 dirs skipped), an elastic resize restoring from the peer tier with no
disk read (disk fallback chaos-proven), the controller's cadence lever,
and the zero-recompile invariant with async checkpointing stacked on the
full feature set.
"""

import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.resilience import (AsyncCheckpointWriter, ElasticCoordinator,
                                  FleetController, ReplicaStore, chaos_scope)
from mxnet_tpu.resilience import ckpt_async
from mxnet_tpu.telemetry import flight
from mxnet_tpu.utils import checkpoint as ckpt_mod
from mxnet_tpu.utils import compile as cm


@pytest.fixture(autouse=True)
def _fresh_hub():
    """These tests count checkpoint events/gauges/histograms: isolate the
    hub, and keep elastic commit()'s world relabeling from leaking."""
    prev = (telemetry.current_rank(), telemetry.world_size())
    telemetry.reset()
    yield
    telemetry.set_world(*prev)
    telemetry.reset()


def _ctx(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return [mx.cpu(i) for i in range(n)]


def _mlp(hidden=16, classes=2):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=hidden)
    net = mx.sym.Activation(data=net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(data=net, name="fc2", num_hidden=classes)
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def _blobs(n=480, dim=10, seed=0):
    rng = np.random.RandomState(seed)
    X = np.concatenate([rng.randn(n // 2, dim) + 1,
                        rng.randn(n - n // 2, dim) - 1]).astype(np.float32)
    y = np.concatenate([np.zeros(n // 2), np.ones(n - n // 2)]).astype(
        np.float32)
    order = rng.permutation(n)
    return X[order], y[order]


def _host_params(seed=0):
    rng = np.random.RandomState(seed)
    return {"fc1_weight": rng.randn(16, 8).astype(np.float32),
            "fc1_bias": rng.randn(16).astype(np.float32)}


def _snap(step, seed=None):
    return ckpt_async.capture_snapshot(
        step, _host_params(seed if seed is not None else step),
        meta={"epoch": 0, "num_update": step})


def _copy_steps(src, dst, steps):
    os.makedirs(dst, exist_ok=True)
    for step in steps:
        shutil.copytree(os.path.join(src, str(step)),
                        os.path.join(dst, str(step)))


# -- cadence / retention / queue resolution ------------------------------------

def test_resolvers_argument_beats_env(monkeypatch):
    assert ckpt_async.resolve_every(None) is None     # unarmed by default
    assert ckpt_async.resolve_every(7) == 7
    assert ckpt_async.resolve_every(0) == 1           # floor, not disable
    monkeypatch.setenv("MXNET_TPU_CKPT_STEPS", "12")
    assert ckpt_async.resolve_every(None) == 12
    assert ckpt_async.resolve_every(3) == 3           # explicit arg wins

    assert ckpt_async.resolve_keep(None) == 3
    monkeypatch.setenv("MXNET_TPU_CKPT_KEEP", "9")
    assert ckpt_async.resolve_keep(None) == 9
    assert ckpt_async.resolve_keep(0) == 0            # 0 = never prune

    assert ckpt_async.resolve_queue_depth(None) == 2
    monkeypatch.setenv("MXNET_TPU_CKPT_QUEUE", "5")
    assert ckpt_async.resolve_queue_depth(None) == 5


def test_capture_snapshot_is_host_side_and_priced():
    mesh = make_mesh(dp=8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = {"w": jax.device_put(np.ones((8, 4), np.float32),
                                  NamedSharding(mesh, P("dp")))}
    opt = {"m": jnp.zeros((8, 4))}
    before = telemetry.hub().snapshot()["histograms"].get(
        "checkpoint_save_seconds", {"count": 0})["count"]
    snap = ckpt_async.capture_snapshot(5, params, opt_state=opt,
                                       meta={"num_update": 5})
    # everything host numpy: the snapshot can outlive mesh/devices
    assert isinstance(snap.state["params"]["w"], np.ndarray)
    assert isinstance(snap.state["opt"][0], np.ndarray)
    assert snap.step == 5 and snap.meta["num_update"] == 5
    after = telemetry.hub().snapshot()["histograms"][
        "checkpoint_save_seconds"]["count"]
    assert after == before + 1  # the stall priced into checkpoint badput


# -- T0: the background writer -------------------------------------------------

def test_writer_drains_and_prunes(tmp_path):
    w = AsyncCheckpointWriter(tmp_path, queue_depth=8, keep_last_k=2)
    try:
        for step in (1, 2, 3, 4, 5):
            assert w.submit(_snap(step))
        assert w.flush(timeout=30)
        assert w.written == 5 and w.dropped == 0
        assert w.last_durable_step == 5
    finally:
        w.close()
    # retention: only the newest keep_last_k steps survive on disk
    kept = sorted(int(d) for d in os.listdir(tmp_path) if d.isdigit())
    assert kept == [4, 5]
    assert ckpt_mod.latest_step(tmp_path) == 5
    loaded, _, _, meta, _ = ckpt_mod.load_sharded(tmp_path)
    np.testing.assert_array_equal(loaded["fc1_weight"],
                                  _host_params(5)["fc1_weight"])
    assert meta["num_update"] == 5


def test_writer_backpressure_drops_oldest_never_blocks(tmp_path,
                                                       monkeypatch):
    gate = threading.Event()
    real = ckpt_mod.save_sharded

    def slow_save(*a, **kw):
        gate.wait(timeout=30)
        return real(*a, **kw)

    monkeypatch.setattr(ckpt_mod, "save_sharded", slow_save)
    w = AsyncCheckpointWriter(tmp_path, queue_depth=2, keep_last_k=0)
    try:
        w.submit(_snap(1))           # picked up, stalls on the gate
        time.sleep(0.05)
        for step in (2, 3, 4, 5):
            t0 = time.monotonic()
            w.submit(_snap(step))    # never blocks the producer
            assert time.monotonic() - t0 < 1.0
        gate.set()
        assert w.flush(timeout=30)
    finally:
        gate.set()
        w.close()
    # oldest pending snapshots were sacrificed, the freshest survived
    assert w.dropped == 2 and w.written == 3
    kept = sorted(int(d) for d in os.listdir(tmp_path) if d.isdigit())
    assert kept == [1, 4, 5]


def test_writer_failure_is_incident_not_exception(tmp_path, monkeypatch):
    """A dead disk must not kill training: the write failure is counted,
    emitted as a `checkpoint` incident (golden keys intact) and flight-
    dumped CRC-clean — and the NEXT write works again."""
    flight_d = tmp_path / "flight"
    flight_d.mkdir()
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(flight_d))
    d = tmp_path / "ckpt"
    w = AsyncCheckpointWriter(d, queue_depth=2, keep_last_k=0)
    try:
        with chaos_scope(seed=0, rules={"ckpt.async_write": 1.0}):
            w.submit(_snap(1))
            assert w.flush(timeout=30)
        assert w.failures == 1 and w.written == 0
        # chaos off: the writer thread survived and keeps writing
        w.submit(_snap(2))
        assert w.flush(timeout=30)
        assert w.written == 1 and w.last_durable_step == 2
    finally:
        w.close()
    incidents = [e for e in telemetry.hub().events("checkpoint")
                 if e.get("error")]
    assert incidents and incidents[0]["tier"] == "t0"
    for key in ("step", "seconds", "tier"):    # golden keys even on error
        assert key in incidents[0]
    dumps = list(flight_d.glob("flight-*-checkpoint-*.json"))
    assert dumps
    ok, msg = flight.validate_flight(str(dumps[0]))
    assert ok, msg


def test_prune_never_races_latest_step(tmp_path):
    """`latest_step` readers must always see a loadable step while the
    pruner is deleting: the pruner renames a victim out of the numeric
    namespace (one atomic op) before rmtree, so a concurrent scan never
    observes a half-deleted step dir."""
    params = _host_params()
    for step in range(1, 6):
        ckpt_mod.save_sharded(tmp_path, step, params)
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            step = ckpt_mod.latest_step(tmp_path)
            if step is None:
                errors.append("latest_step saw no valid step")
                return
            if not ckpt_mod.validate_step(tmp_path, step):
                errors.append(f"latest_step returned torn step {step}")
                return

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for step in range(6, 40):
            ckpt_mod.save_sharded(tmp_path, step, params)
            ckpt_mod.prune_steps(tmp_path, 2)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors
    kept = sorted(int(d) for d in os.listdir(tmp_path) if d.isdigit())
    assert kept == [38, 39]
    # no .gc. trash left behind either
    assert not [d for d in os.listdir(tmp_path) if d.startswith(".gc.")]


def test_prune_ignores_torn_dirs_when_counting(tmp_path):
    """Retention counts VALID steps: a torn dir must not displace a good
    checkpoint out of the keep window."""
    params = _host_params()
    for step in (1, 2, 3):
        ckpt_mod.save_sharded(tmp_path, step, params)
    os.makedirs(tmp_path / "9")               # torn: bare numeric dir
    ckpt_mod.prune_steps(tmp_path, 2)
    kept = sorted(int(d) for d in os.listdir(tmp_path) if d.isdigit())
    assert 2 in kept and 3 in kept            # both valid keeps survive


# -- T1: the in-memory peer tier -----------------------------------------------

def test_replica_store_dedup_restore_drop():
    store = ReplicaStore(4)
    assert store.holder_of(0) == 1 and store.holder_of(3) == 0
    assert store.replicate(0, _snap(3))
    assert store.replicate(1, _snap(5))
    # stale and duplicate replicas are dropped, like kvstore pushes
    assert not store.replicate(0, _snap(3))
    assert not store.replicate(0, _snap(2))
    assert store.duplicate_count == 2
    # freshest snapshot whose holder survives
    assert store.restore().step == 5
    # rank 1's snapshot is held by rank 2; kill 2 -> only rank 0's left
    assert store.restore(alive=(0, 1, 3)).step == 3
    # kill every holder -> T2 fallback
    assert store.restore(alive=(0,)) is None
    # a dead rank takes its own entry AND everything it held with it
    store.drop_rank(2)
    assert store.restore() .step == 3
    store.drop_rank(1)                        # holder of rank 0's snap
    assert store.restore() is None


def test_group_kv_replica_roundtrip():
    from mxnet_tpu import kvstore as kvstore_mod

    srv = kvstore_mod._GroupServer(4)
    workers = [kvstore_mod._GroupWorkerKVStore(srv, r) for r in range(4)]
    payload = {"state": {"params": _host_params()}, "meta": {"step": 7}}
    assert workers[0].push_replica(0, 7, payload)
    # stale step dropped, newest wins
    assert not workers[0].push_replica(0, 6, payload)
    assert srv.replica_count == 1 and srv.replica_duplicate_count == 1
    step, got = workers[2].pull_replica(0)
    assert step == 7
    np.testing.assert_array_equal(got["state"]["params"]["fc1_weight"],
                                  payload["state"]["params"]["fc1_weight"])
    assert workers[1].pull_replica(3) is None


def test_async_server_replica_op_dedup(monkeypatch):
    """The dist_async wire path: `replica` is newest-wins by step and
    (rank, seq)-replay-deduped like pushes, `replica_pull` returns the
    held blob, and `stats` exposes the replica count."""
    monkeypatch.setenv("MXNET_TPU_KV_OP_TIMEOUT", "2.0")
    import socket

    from mxnet_tpu.kvstore_async import (_MAGIC, _AsyncServer, _recv_exact,
                                         _recv_msg, _send_msg)

    srv = _AsyncServer("127.0.0.1", 0, 1)
    port = srv._srv.getsockname()[1]

    def connect():
        s = socket.create_connection(("127.0.0.1", port), timeout=5.0)
        s.sendall(_MAGIC)
        assert _recv_exact(s, 4) == _MAGIC
        return s

    def call(s, *msg):
        _send_msg(s, msg)
        return _recv_msg(s)

    import pickle

    blob = pickle.dumps({"step7": True}, protocol=pickle.HIGHEST_PROTOCOL)
    c = connect()
    try:
        assert call(c, "replica", 0, 7, blob, 0, 1) == ("ok", True)
        # an at-least-once RESEND of the same (rank, seq) replays the
        # recorded reply without re-applying
        assert call(c, "replica", 0, 7, blob, 0, 1) == ("ok", True)
        assert srv.replica_count == 1
        # a NEW request carrying an older step is dropped as stale
        assert call(c, "replica", 0, 5, blob, 0, 2) == ("ok", False)
        op, ent = call(c, "replica_pull", 0)
        assert op == "ok" and ent[0] == 7
        assert pickle.loads(ent[1]) == {"step7": True}
        assert call(c, "replica_pull", 3) == ("ok", None)
        assert call(c, "stats")[1]["replica_count"] == 1
    finally:
        c.close()
        srv._srv.close()


# -- satellite: legacy save/load on the atomic CRC writer ----------------------

def test_legacy_checkpoint_atomic_with_crc_sidecar(tmp_path):
    prefix = str(tmp_path / "legacy")
    data = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=4)
    arg = {"fc1_weight": mx.nd.array(np.random.randn(4, 3)),
           "fc1_bias": mx.nd.array(np.zeros(4))}
    mx.model.save_checkpoint(prefix, 3, sym, arg, {})
    # sidecars committed next to both artifacts, no tmp files left
    assert os.path.exists(prefix + "-0003.params.crc32")
    assert os.path.exists(prefix + "-symbol.json.crc32")
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    sym2, arg2, _ = mx.model.load_checkpoint(prefix, 3)
    np.testing.assert_array_equal(arg2["fc1_weight"].asnumpy(),
                                  arg["fc1_weight"].asnumpy())
    assert sym2.list_arguments() == sym.list_arguments()

    # torn params file: the CRC sidecar catches it loudly
    with open(prefix + "-0003.params", "r+b") as f:
        f.truncate(os.path.getsize(prefix + "-0003.params") - 1)
    assert ckpt_mod.check_sidecar(prefix + "-0003.params") is False
    with pytest.raises(MXNetError):
        mx.model.load_checkpoint(prefix, 3)


def test_atomic_write_helper(tmp_path):
    path = str(tmp_path / "blob.bin")
    ckpt_mod.atomic_write(path, lambda tmp: open(tmp, "wb").write(b"x" * 64))
    assert ckpt_mod.check_sidecar(path) is True
    with open(path + ".crc32") as f:
        side = json.load(f)
    assert side["size"] == 64
    # legacy file with no sidecar: accepted (None, not False)
    bare = str(tmp_path / "old.bin")
    with open(bare, "wb") as f:
        f.write(b"y")
    assert ckpt_mod.check_sidecar(bare) is None


# -- fit integration: cadence, telemetry, resume -------------------------------

def test_fit_step_cadence_writes_and_gauges(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CKPT_KEEP", "100")
    X, y = _blobs(n=256)
    d = str(tmp_path / "ckpt")
    m = mx.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2, optimizer="sgd",
                       learning_rate=0.1)
    m.fit(mx.io.NDArrayIter(X, y, batch_size=32, shuffle=False),
          batch_size=32, sharded_checkpoint_dir=d,
          checkpoint_every_n_steps=3)
    # 8 steps/epoch: cadence hits at 3/6/9/12/15 + epoch ends at 8/16
    steps = sorted(int(s) for s in os.listdir(d) if s.isdigit())
    assert steps == [3, 6, 8, 9, 12, 15, 16]
    for step in steps:
        assert ckpt_mod.validate_step(d, step)
    # mid-epoch meta carries the step-granular resume state
    _, _, _, meta, _ = ckpt_mod.load_sharded(d, 12)
    assert meta["num_update"] == 12 and meta["epoch"] == 1
    assert meta["batches_done"] == 4
    assert len(meta["rng_state"]) >= 1
    # epoch-end snapshots restart the iterator position
    _, _, _, meta16, _ = ckpt_mod.load_sharded(d, 16)
    assert meta16["batches_done"] == 0 and meta16["epoch"] == 2
    # the plane's health surface
    gauges = telemetry.hub().snapshot()["gauges"]
    names = {g.split("{")[0] for g in gauges}
    assert "ckpt_queue_depth" in names
    assert "ckpt_snapshot_age_steps" in names
    events = telemetry.hub().events("checkpoint")
    tiers = {e.get("tier") for e in events}
    assert "t0" in tiers and "t2" in tiers
    for e in events:                          # golden keys on every event
        assert {"step", "seconds", "tier"} <= set(e)


def test_acceptance_kill_mid_epoch_bitwise_step_resume(tmp_path,
                                                       monkeypatch):
    """ISSUE 17 acceptance: a run hard-killed mid-epoch — torn T2 step
    and a stray .tmp staging dir left behind, exactly a SIGKILL
    mid-async-write — resumes at the last durable STEP (not epoch) and
    the resumed trajectory is bitwise-equal to the uninterrupted run at
    matching steps (params, optimizer leaves, num_update)."""
    monkeypatch.setenv("MXNET_TPU_CKPT_KEEP", "100")
    X, y = _blobs(n=256)
    batch = 32
    d_ref = str(tmp_path / "ref")

    def run(d, **kw):
        m = mx.FeedForward(_mlp(), ctx=_ctx(8), num_epoch=2,
                           optimizer="sgd", learning_rate=0.1)
        m.fit(mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False),
              batch_size=batch, sharded_checkpoint_dir=d,
              checkpoint_every_n_steps=3, **kw)
        return m

    run(d_ref)  # the uninterrupted reference: steps 3..16 on disk

    # simulate the kill: the victim run died right after step 12 became
    # durable (mid-epoch 1) — later steps never landed; the in-flight
    # async write left a torn "15" and a stray .tmp staging dir
    d_kill = str(tmp_path / "killed")
    _copy_steps(d_ref, d_kill, (3, 6, 8, 9, 12))
    shutil.copytree(os.path.join(d_ref, "15"), os.path.join(d_kill, "15"))
    with open(os.path.join(d_kill, "15", "manifest.json"), "r+b") as f:
        f.truncate(8)
    shutil.copytree(os.path.join(d_ref, "16"),
                    os.path.join(d_kill, ".tmp.16"))
    assert ckpt_mod.latest_step(d_kill) == 12     # torn 15 skipped

    resumed = run(d_kill)
    assert resumed.begin_epoch == 1               # resumed, not retrained

    # bitwise at the next cadence step AND at the end of training
    for step in (15, 16):
        el = ckpt_mod.load_sharded(d_ref, step)
        re = ckpt_mod.load_sharded(d_kill, step)
        for k in el[0]:
            np.testing.assert_array_equal(el[0][k], re[0][k],
                                          err_msg=f"params[{k}]@{step}")
        for i, (a, b) in enumerate(zip(el[4], re[4])):
            np.testing.assert_array_equal(a, b, err_msg=f"opt[{i}]@{step}")
        assert el[3]["num_update"] == re[3]["num_update"] == step


def test_fit_resize_restores_from_peer_tier_no_disk_read(tmp_path,
                                                         monkeypatch):
    """ISSUE 17 acceptance: an elastic shrink with the async plane armed
    restores from the in-memory T1 tier — load_resharded (the disk path)
    is never called."""
    monkeypatch.setenv("MXNET_TPU_CKPT_KEEP", "100")
    X, y = _blobs(n=480)
    batch = 48
    d = str(tmp_path / "el")
    co = ElasticCoordinator(8)
    disk_reads = []
    real = ckpt_mod.load_resharded
    monkeypatch.setattr(
        ckpt_mod, "load_resharded",
        lambda *a, **kw: disk_reads.append(a) or real(*a, **kw))

    def drive(param):
        if param.epoch == 1 and param.nbatch == 3 and co.world_size == 8:
            co.kill()
            co.kill()

    m = mx.FeedForward(_mlp(), ctx=_ctx(8), num_epoch=3, optimizer="sgd",
                       learning_rate=0.1)
    m.fit(mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False),
          batch_size=batch, elastic=co, sharded_checkpoint_dir=d,
          checkpoint_every_n_steps=2, batch_end_callback=drive)
    assert co.world_size == 6 and co.resizes == 1
    assert not disk_reads                      # RAM tier, zero disk I/O
    events = telemetry.hub().events("checkpoint")
    assert any(e.get("tier") == "t1" for e in events)
    assert m.score(X, y=y) > 0.95


def test_fit_resize_falls_back_to_disk_when_replication_dead(tmp_path,
                                                             monkeypatch):
    """Chaos kills every peer replication (the mid-replication SIGKILL):
    the T1 tier is empty at resize time, so restore falls back to the
    durable T2 tier — correctness survives, only the disk read returns."""
    monkeypatch.setenv("MXNET_TPU_CKPT_KEEP", "100")
    X, y = _blobs(n=480)
    batch = 48
    d = str(tmp_path / "el")
    co = ElasticCoordinator(8)
    disk_reads = []
    real = ckpt_mod.load_resharded
    monkeypatch.setattr(
        ckpt_mod, "load_resharded",
        lambda *a, **kw: disk_reads.append(a) or real(*a, **kw))

    def drive(param):
        if param.epoch == 1 and param.nbatch == 3 and co.world_size == 8:
            co.kill()
            co.kill()

    m = mx.FeedForward(_mlp(), ctx=_ctx(8), num_epoch=3, optimizer="sgd",
                       learning_rate=0.1)
    with chaos_scope(seed=0, rules={"ckpt.replica": 1.0}):
        m.fit(mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False),
              batch_size=batch, elastic=co, sharded_checkpoint_dir=d,
              checkpoint_every_n_steps=2, batch_end_callback=drive)
    assert co.world_size == 6
    assert disk_reads                          # T2 carried the restore
    assert m.score(X, y=y) > 0.9


def test_fit_async_ckpt_zero_recompiles():
    """ACCEPTANCE: an armed RecompileTracker epoch stays green with
    step-cadence async checkpointing stacked on compression + overlap +
    fused-Adam + guards + health — every checkpoint op is host-side, so
    the step program compiles exactly once."""
    import tempfile

    X, y = _blobs(160, dim=10)
    model = mx.FeedForward(_mlp(hidden=64), ctx=_ctx(8), num_epoch=3,
                           optimizer="adam", fused=True, learning_rate=0.01)
    tracker = cm.RecompileTracker(raise_on_recompile=True)

    def arm_after_first(epoch, *_):
        if epoch == 0:
            tracker.arm()

    cm.reset_compile_stats()
    with tempfile.TemporaryDirectory() as d:
        try:
            model.fit(X, y, batch_size=32, compression="int8", overlap=True,
                      guards=True, health=True, sharded_checkpoint_dir=d,
                      checkpoint_every_n_steps=2,
                      epoch_end_callback=arm_after_first)
        finally:
            tracker.disarm()
        assert ckpt_mod.latest_step(d) is not None
    assert tracker.recompiles == []
    per = cm.compile_stats()["per_function"]
    train = [c for lbl, c in per.items() if lbl.startswith("train_step:")]
    assert train and train[0]["misses"] == 1  # compiled exactly once


# -- the controller's cadence lever --------------------------------------------

def test_select_ckpt_cadence():
    from mxnet_tpu.resilience.controller import select_ckpt_cadence

    # 0.5s save, 1s steps, 5% target -> every 10 steps
    assert select_ckpt_cadence(0.5, 1.0, 1) == 10
    # hysteresis: <25% moves hold the current cadence
    assert select_ckpt_cadence(0.5, 1.0, 9) == 9
    assert select_ckpt_cadence(0.5, 1.0, 40) == 10
    # no measurement, no opinion
    assert select_ckpt_cadence(None, 1.0, 8) == 8
    assert select_ckpt_cadence(0.5, None, 8) == 8
    # clamped to [floor, cap]
    assert select_ckpt_cadence(1e-9, 1.0, 64, floor=1) == 1
    assert select_ckpt_cadence(1e9, 1.0, 4, cap=1024) == 1024


def test_controller_stages_ckpt_cadence_and_fit_applies():
    co = ElasticCoordinator(8)
    ctl = FleetController(interval=0.0, window=8, min_report_steps=8,
                          rejoin_after=1000.0, evaluate_after=1000.0,
                          cooldowns={"evict": 1000.0, "backfill": 1000.0,
                                     "retier": 1000.0, "world": 1000.0,
                                     "ckpt": 0.0})
    ctl.bind(coordinator=co, model_key="m", world_size=8, ckpt_every=2)
    # fleet steps of ~10ms, save cost ~5ms -> 5% target wants every ~10
    for s in range(16):
        for r in range(8):
            telemetry.emit("span", rank=r, name="step", epoch=0, step=s,
                           dur_ms=10.0,
                           phases=[{"name": "device", "dur_ms": 10.0}])
    for _ in range(4):
        telemetry.observe("checkpoint_save_seconds", 0.005)
    ctl.tick(now=100.0)
    action = ctl.take_ckpt_cadence()
    assert action is not None and action["every"] == 10
    assert ctl.take_ckpt_cadence() is None     # staged once
    ctl.ckpt_cadence_applied(action)
    assert ctl._ckpt_every == 10
    applied = [d for d in ctl.decisions if d["lever"] == "ckpt"]
    assert applied and applied[0]["outcome"] == "actuated"
    events = [e for e in telemetry.hub().events("controller")
              if e.get("lever") == "ckpt" and e.get("outcome") == "applied"]
    assert events
