"""Symbol tests (reference: tests/python/unittest/test_symbol.py —
compose/internals/pickle/saveload)."""

import pickle

import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=10)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=5)
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_symbol_compose():
    net = _mlp()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label",
    ]
    assert net.list_outputs() == ["softmax_output"]


def test_symbol_internals():
    net = _mlp()
    internals = net.get_internals()
    outs = internals.list_outputs()
    assert "fc1_output" in outs
    fc1 = internals["fc1_output"]
    assert fc1.list_outputs() == ["fc1_output"]


def test_symbol_group():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data=data, name="fc1", num_hidden=10)
    fc2 = sym.FullyConnected(data=data, name="fc2", num_hidden=10)
    g = sym.Group([fc1, fc2])
    assert g.list_outputs() == ["fc1_output", "fc2_output"]
    assert len(g) == 2


def test_symbol_pickle():
    net = _mlp()
    s = pickle.dumps(net)
    net2 = pickle.loads(s)
    assert net.tojson() == net2.tojson()
    assert net2.list_arguments() == net.list_arguments()


def test_symbol_saveload(tmp_path):
    fname = str(tmp_path / "net.json")
    net = _mlp()
    net.save(fname)
    net2 = sym.load(fname)
    assert net.tojson() == net2.tojson()


def test_symbol_arithmetic():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    assert set(c.list_arguments()) == {"a", "b"}
    d = (a * b) / (a - b)
    assert set(d.list_arguments()) == {"a", "b"}


def test_symbol_auto_names():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=3)
    assert fc.name.startswith("fullyconnected")


def test_symbol_variable_arity():
    xs = [sym.Variable(f"x{i}") for i in range(4)]
    c = sym.Concat(*xs, dim=1, name="cat")
    assert c.list_arguments() == [f"x{i}" for i in range(4)]
    s = sym.ElementWiseSum(*xs, name="esum")
    assert len(s.list_arguments()) == 4


def test_symbol_unknown_input_rejected():
    data = sym.Variable("data")
    with pytest.raises(mx.MXNetError):
        sym.FullyConnected(bogus=data, num_hidden=3)
