// R .C-convention shim over the native predict ABI (mxtpu_predict.cc).
//
// Reference counterpart: R-package/src/*.cc (Rcpp wrappers over the C API).
// This shim deliberately uses ONLY the .C calling convention (plain
// int*/double*/char** arguments, no R headers), so it compiles without an R
// installation and is testable from any FFI. Handles are kept in an
// id-indexed registry because .C cannot carry pointers.

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
// the native predict ABI (libmxtpu_predict.so)
void *mxtpu_pred_create(const char *bundle_path);
const char *mxtpu_pred_last_error(void);
int mxtpu_pred_set_input(void *h, const char *name, const float *data,
                         const int64_t *shape, int ndim);
int mxtpu_pred_forward(void *h);
int mxtpu_pred_num_outputs(void *h);
int mxtpu_pred_output_ndim(void *h, int index);
int mxtpu_pred_output_shape(void *h, int index, int64_t *shape);
int64_t mxtpu_pred_get_output(void *h, int index, float *out, int64_t size);
void mxtpu_pred_free(void *h);
}

namespace {
std::mutex g_mu;
std::map<int, void *> g_handles;
int g_next_id = 1;

void *get(int id) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_handles.find(id);
  return it == g_handles.end() ? nullptr : it->second;
}
}  // namespace

extern "C" {

// status: 0 ok, negative on error. R passes scalars as length-1 arrays.
void mxtpu_r_create(char **bundle_path, int *id_out, int *status) {
  void *h = mxtpu_pred_create(bundle_path[0]);
  if (h == nullptr) {
    *status = -1;
    *id_out = 0;
    return;
  }
  std::lock_guard<std::mutex> lock(g_mu);
  g_handles[g_next_id] = h;
  *id_out = g_next_id++;
  *status = 0;
}

void mxtpu_r_last_error(char **msg, int *len) {
  // copies into the caller-allocated buffer of *len bytes
  const char *err = mxtpu_pred_last_error();
  std::strncpy(msg[0], err, *len - 1);
  msg[0][*len - 1] = '\0';
}

void mxtpu_r_set_input(int *id, char **name, double *data, int *shape,
                       int *ndim, int *status) {
  void *h = get(*id);
  if (h == nullptr) { *status = -2; return; }
  int64_t total = 1;
  std::vector<int64_t> shp(*ndim);
  for (int i = 0; i < *ndim; ++i) { shp[i] = shape[i]; total *= shape[i]; }
  std::vector<float> f(data, data + total);  // R numerics are double
  *status = mxtpu_pred_set_input(h, name[0], f.data(), shp.data(), *ndim);
}

void mxtpu_r_forward(int *id, int *status) {
  void *h = get(*id);
  *status = h == nullptr ? -2 : mxtpu_pred_forward(h);
}

void mxtpu_r_num_outputs(int *id, int *n) {
  void *h = get(*id);
  *n = h == nullptr ? -2 : mxtpu_pred_num_outputs(h);
}

void mxtpu_r_output_shape(int *id, int *index, int *ndim, int *shape) {
  // shape must have room for 8 dims
  void *h = get(*id);
  if (h == nullptr) { *ndim = -2; return; }
  *ndim = mxtpu_pred_output_ndim(h, *index);
  if (*ndim <= 0 || *ndim > 8) return;
  int64_t shp[8];
  mxtpu_pred_output_shape(h, *index, shp);
  for (int i = 0; i < *ndim; ++i) shape[i] = static_cast<int>(shp[i]);
}

void mxtpu_r_get_output(int *id, int *index, double *out, int *size,
                        int *status) {
  void *h = get(*id);
  if (h == nullptr) { *status = -2; return; }
  std::vector<float> f(*size);
  int64_t n = mxtpu_pred_get_output(h, *index, f.data(), *size);
  if (n < 0) { *status = -1; return; }
  for (int64_t i = 0; i < n; ++i) out[i] = f[i];
  *status = 0;
}

void mxtpu_r_free(int *id) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_handles.find(*id);
  if (it != g_handles.end()) {
    mxtpu_pred_free(it->second);
    g_handles.erase(it);
  }
}

}  // extern "C"
