// R training shim: .C-convention wrappers over the flat C API
// (mxnet_tpu/native/mxtpu_c_api.h).
//
// Reference counterpart: R-package/src/*.cc (Rcpp bindings over
// include/mxnet/c_api.h). R's .C interface passes everything as pointers
// to basic types and copies vectors, so handles cross as integer ids into
// a process-local table, strings as char**, and tensors as double* (R has
// no float; converted at the boundary).
//
// Build (needs libmxtpu_capi.so next to it or on LD_LIBRARY_PATH):
//   make -C ../mxnet_tpu/native capi
//   R CMD SHLIB mxtpu_r_train.cc -L../mxnet_tpu/native -lmxtpu_capi
// The same entry points are also exercised without R by
// tests/test_r_binding.py through ctypes using the identical pointer
// calling convention.

#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "../../mxnet_tpu/native/mxtpu_c_api.h"

namespace {

std::map<int, void*> g_handles;
int g_next_id = 1;
std::string g_last_error;

int put_handle(void* h) {
  int id = g_next_id++;
  g_handles[id] = h;
  return id;
}

void* get_handle(int id) {
  auto it = g_handles.find(id);
  return it == g_handles.end() ? nullptr : it->second;
}

int record(int rc) {
  if (rc != 0) g_last_error = MXGetLastError();
  return rc;
}

}  // namespace

extern "C" {

void mxr_last_error(char** msg, int* len) {
  std::strncpy(*msg, g_last_error.c_str(), *len - 1);
  (*msg)[*len - 1] = '\0';
}

void mxr_random_seed(int* seed, int* status) {
  *status = record(MXRandomSeed(*seed));
}

/* ------------------------------------------------------------- ndarray */

void mxr_nd_create(int* shape, int* ndim, int* id_out, int* status) {
  std::vector<mx_uint> s(shape, shape + *ndim);
  NDArrayHandle h;
  *status = record(MXNDArrayCreate(s.data(), *ndim, 1, 0, 0, &h));
  if (*status == 0) *id_out = put_handle(h);
}

void mxr_nd_free(int* id, int* status) {
  void* h = get_handle(*id);
  g_handles.erase(*id);
  *status = record(MXNDArrayFree(h));
}

void mxr_nd_shape(int* id, int* ndim_out, int* shape_out, int* status) {
  mx_uint nd;
  const mx_uint* dims;
  *status = record(MXNDArrayGetShape(get_handle(*id), &nd, &dims));
  if (*status != 0) return;
  if (nd > 8) {
    // the R caller indexes seq_len(ndim) into integer(8); reporting the
    // full ndim with a truncated copy would hand it NA dims — fail loudly
    // instead (same capacity contract as mxr_sym_infer_shapes)
    g_last_error = "mxr_nd_shape: array has more than 8 dimensions";
    *status = -1;
    return;
  }
  *ndim_out = (int)nd;
  for (mx_uint i = 0; i < nd; ++i) shape_out[i] = (int)dims[i];
}

void mxr_nd_set(int* id, double* data, int* n, int* status) {
  std::vector<float> buf(*n);
  for (int i = 0; i < *n; ++i) buf[i] = (float)data[i];
  *status = record(
      MXNDArraySyncCopyFromCPU(get_handle(*id), buf.data(), *n));
}

void mxr_nd_get(int* id, double* data, int* n, int* status) {
  std::vector<float> buf(*n);
  *status = record(MXNDArraySyncCopyToCPU(get_handle(*id), buf.data(), *n));
  if (*status != 0) return;
  for (int i = 0; i < *n; ++i) data[i] = buf[i];
}

/* ------------------------------------------------------------- symbols */

void mxr_sym_variable(char** name, int* id_out, int* status) {
  SymbolHandle h;
  *status = record(MXSymbolCreateVariable(name[0], &h));
  if (*status == 0) *id_out = put_handle(h);
}

void mxr_sym_atomic(char** opname, int* nparam, char** keys, char** vals,
                    int* id_out, int* status) {
  // enumerate the registry once and cache name -> creator: creator handles
  // from MXSymbolListAtomicSymbolCreators are owned allocations, so
  // re-listing per symbol would both leak them and cost O(#ops) embedded
  // Python round-trips for every layer an R model builds
  static std::map<std::string, AtomicSymbolCreator> creator_cache;
  if (creator_cache.empty()) {
    mx_uint n_creators;
    AtomicSymbolCreator* creators;
    *status = record(MXSymbolListAtomicSymbolCreators(&n_creators,
                                                      &creators));
    if (*status != 0) return;
    for (mx_uint i = 0; i < n_creators; ++i) {
      const char *nm, *desc, *kv;
      mx_uint na;
      const char **an, **at, **ad;
      if (MXSymbolGetAtomicSymbolInfo(creators[i], &nm, &desc, &na, &an,
                                      &at, &ad, &kv) != 0)
        continue;
      creator_cache[nm] = creators[i];
    }
  }
  auto it = creator_cache.find(opname[0]);
  if (it == creator_cache.end()) {
    g_last_error = std::string("unknown operator ") + opname[0];
    *status = -1;
    return;
  }
  AtomicSymbolCreator target = it->second;
  std::vector<const char*> k(*nparam), v(*nparam);
  for (int i = 0; i < *nparam; ++i) {
    k[i] = keys[i];
    v[i] = vals[i];
  }
  SymbolHandle h;
  *status = record(MXSymbolCreateAtomicSymbol(target, *nparam, k.data(),
                                              v.data(), &h));
  if (*status == 0) *id_out = put_handle(h);
}

void mxr_sym_compose(int* sym_id, char** name, int* nargs, char** keys,
                     int* arg_ids, int* status) {
  std::vector<const char*> k(*nargs);
  std::vector<SymbolHandle> args(*nargs);
  for (int i = 0; i < *nargs; ++i) {
    k[i] = keys[i];
    args[i] = get_handle(arg_ids[i]);
  }
  *status = record(MXSymbolCompose(get_handle(*sym_id), name[0], *nargs,
                                   k.data(), args.data()));
}

// joined with '\n' into the caller's buffer (R-friendly string return)
static void join_list(mx_uint n, const char** arr, char** out, int* cap) {
  std::string joined;
  for (mx_uint i = 0; i < n; ++i) {
    if (i) joined += '\n';
    joined += arr[i];
  }
  std::strncpy(*out, joined.c_str(), *cap - 1);
  (*out)[*cap - 1] = '\0';
}

void mxr_sym_arguments(int* id, char** out, int* cap, int* status) {
  mx_uint n;
  const char** names;
  *status = record(MXSymbolListArguments(get_handle(*id), &n, &names));
  if (*status == 0) join_list(n, names, out, cap);
}

void mxr_sym_aux(int* id, char** out, int* cap, int* status) {
  mx_uint n;
  const char** names;
  *status =
      record(MXSymbolListAuxiliaryStates(get_handle(*id), &n, &names));
  if (*status == 0) join_list(n, names, out, cap);
}

void mxr_sym_tojson(int* id, char** out, int* cap, int* status) {
  const char* js;
  *status = record(MXSymbolSaveToJSON(get_handle(*id), &js));
  if (*status != 0) return;
  std::strncpy(*out, js, *cap - 1);
  (*out)[*cap - 1] = '\0';
}

void mxr_sym_fromjson(char** js, int* id_out, int* status) {
  SymbolHandle h;
  *status = record(MXSymbolCreateFromJSON(js[0], &h));
  if (*status == 0) *id_out = put_handle(h);
}

// infer shapes given data shape; writes ndim+dims per argument
// (flattened, 8 slots per arg) and the same for aux states. `cap` is the
// number of per-argument slots the R caller allocated; exceeding it is an
// error, never an out-of-bounds write.
void mxr_sym_infer_shapes(int* id, char** data_name, int* data_shape,
                          int* data_ndim, int* cap, int* n_args_out,
                          int* arg_ndims, int* arg_shapes, int* n_aux_out,
                          int* aux_ndims, int* aux_shapes, int* status) {
  const char* keys[1] = {data_name[0]};
  mx_uint ind[2] = {0, (mx_uint)*data_ndim};
  std::vector<mx_uint> shp(*data_ndim);
  for (int i = 0; i < *data_ndim; ++i) shp[i] = data_shape[i];
  mx_uint in_n, out_n, aux_n;
  const mx_uint *in_nd, *out_nd, *aux_nd;
  const mx_uint **in_d, **out_d, **aux_d;
  int complete;
  *status = record(MXSymbolInferShape(
      get_handle(*id), 1, keys, ind, shp.data(), &in_n, &in_nd, &in_d,
      &out_n, &out_nd, &out_d, &aux_n, &aux_nd, &aux_d, &complete));
  if (*status != 0) return;
  if ((int)in_n > *cap || (int)aux_n > *cap) {
    g_last_error = "infer_shapes: symbol has more arguments than the "
                   "caller-provided capacity; raise max_args in "
                   "mx.symbol.infer.shapes";
    *status = -1;
    return;
  }
  *n_args_out = (int)in_n;
  for (mx_uint i = 0; i < in_n; ++i) {
    arg_ndims[i] = (int)in_nd[i];
    for (mx_uint j = 0; j < in_nd[i] && j < 8; ++j)
      arg_shapes[i * 8 + j] = (int)in_d[i][j];
  }
  *n_aux_out = (int)aux_n;
  for (mx_uint i = 0; i < aux_n; ++i) {
    aux_ndims[i] = (int)aux_nd[i];
    for (mx_uint j = 0; j < aux_nd[i] && j < 8; ++j)
      aux_shapes[i * 8 + j] = (int)aux_d[i][j];
  }
}

/* --------------------------------------------- checkpoint (nd save/load) */

// save named arrays to `fname` in the framework's checkpoint container —
// the SAME file format Python's mx.nd.save / model save_checkpoint writes,
// so R-side mx.model.save round-trips with Python FeedForward.load
// (reference capability: R-package/R/model.R mx.model.save -> mx.nd.save).
void mxr_nd_save(char** fname, int* n, int* ids, char** names,
                 int* status) {
  std::vector<NDArrayHandle> hs(*n);
  std::vector<const char*> ks(*n);
  for (int i = 0; i < *n; ++i) {
    hs[i] = get_handle(ids[i]);
    ks[i] = names[i];
  }
  *status = record(
      MXNDArraySave(fname[0], (mx_uint)*n, hs.data(), ks.data()));
}

// load a checkpoint container: ids into ids_out, names '\n'-joined into
// the caller's buffer (cap = id slots; name_cap = name buffer bytes)
void mxr_nd_load(char** fname, int* cap, int* n_out, int* ids_out,
                 char** names_out, int* name_cap, int* status) {
  mx_uint n, n_names;
  NDArrayHandle* hs;
  const char** names;
  *status = record(MXNDArrayLoad(fname[0], &n, &hs, &n_names, &names));
  if (*status != 0) return;
  if ((int)n > *cap || n_names != n) {
    for (mx_uint i = 0; i < n; ++i) MXNDArrayFree(hs[i]);
    g_last_error = "mxr_nd_load: more arrays than caller capacity (or "
                   "unnamed entries; R checkpoints are always named)";
    *status = -1;
    return;
  }
  std::string joined;
  for (mx_uint i = 0; i < n; ++i) {
    if (i) joined += '\n';
    joined += names[i];
  }
  if ((int)joined.size() >= *name_cap) {
    // truncating mid-name would hand R fewer/corrupt names than ids —
    // a silently mis-keyed model load; fail loudly instead (nothing was
    // registered yet, so no handle-table entries leak; the arrays
    // themselves are freed here)
    for (mx_uint i = 0; i < n; ++i) MXNDArrayFree(hs[i]);
    g_last_error = "mxr_nd_load: joined parameter names exceed the "
                   "caller-provided name buffer; raise name_cap in "
                   "mx.nd.load";
    *status = -1;
    return;
  }
  *n_out = (int)n;
  for (mx_uint i = 0; i < n; ++i) ids_out[i] = put_handle(hs[i]);
  std::strncpy(*names_out, joined.c_str(), *name_cap - 1);
  (*names_out)[*name_cap - 1] = '\0';
}

/* ------------------------------------- function registry (ndarray math) */

// invoke a registered NDArray function (MXFuncInvoke) — this is how the R
// optimizer layer runs its update math INSIDE the framework (XLA ops on
// runtime-resident arrays) instead of on R doubles, mirroring the
// reference's R optimizer over mx.nd arithmetic
// (reference: R-package/R/optimizer.R update() on mx.nd ops).
void mxr_func_invoke(char** fname, int* n_use, int* use_ids, int* n_scalar,
                     double* scalars, int* n_mutate, int* mutate_ids,
                     int* status) {
  FunctionHandle f;
  *status = record(MXGetFunction(fname[0], &f));
  if (*status != 0) return;
  std::vector<NDArrayHandle> use(*n_use), mut(*n_mutate);
  for (int i = 0; i < *n_use; ++i) use[i] = get_handle(use_ids[i]);
  for (int i = 0; i < *n_mutate; ++i) mut[i] = get_handle(mutate_ids[i]);
  std::vector<mx_float> sc(*n_scalar);
  for (int i = 0; i < *n_scalar; ++i) sc[i] = (mx_float)scalars[i];
  *status = record(MXFuncInvoke(f, use.data(), sc.data(), mut.data()));
}

/* -------------------------------------------------------------- kvstore */

void mxr_kv_create(char** type, int* id_out, int* status) {
  KVStoreHandle h;
  *status = record(MXKVStoreCreate(type[0], &h));
  if (*status == 0) *id_out = put_handle(h);
}

void mxr_kv_free(int* id, int* status) {
  void* h = get_handle(*id);
  g_handles.erase(*id);
  *status = record(MXKVStoreFree(h));
}

void mxr_kv_init(int* kv, int* n, int* keys, int* nd_ids, int* status) {
  std::vector<NDArrayHandle> vals(*n);
  for (int i = 0; i < *n; ++i) vals[i] = get_handle(nd_ids[i]);
  *status = record(
      MXKVStoreInit(get_handle(*kv), (mx_uint)*n, keys, vals.data()));
}

void mxr_kv_push(int* kv, int* n, int* keys, int* nd_ids, int* priority,
                 int* status) {
  std::vector<NDArrayHandle> vals(*n);
  for (int i = 0; i < *n; ++i) vals[i] = get_handle(nd_ids[i]);
  *status = record(MXKVStorePush(get_handle(*kv), (mx_uint)*n, keys,
                                 vals.data(), *priority));
}

void mxr_kv_pull(int* kv, int* n, int* keys, int* nd_ids, int* priority,
                 int* status) {
  std::vector<NDArrayHandle> vals(*n);
  for (int i = 0; i < *n; ++i) vals[i] = get_handle(nd_ids[i]);
  *status = record(MXKVStorePull(get_handle(*kv), (mx_uint)*n, keys,
                                 vals.data(), *priority));
}

void mxr_kv_rank(int* kv, int* rank_out, int* status) {
  *status = record(MXKVStoreGetRank(get_handle(*kv), rank_out));
}

void mxr_kv_size(int* kv, int* size_out, int* status) {
  *status = record(MXKVStoreGetGroupSize(get_handle(*kv), size_out));
}

void mxr_kv_barrier(int* kv, int* status) {
  *status = record(MXKVStoreBarrier(get_handle(*kv)));
}

/* ------------------------------------------------------------ executor */

void mxr_exec_bind(int* sym_id, int* n, int* arg_ids, int* grad_ids,
                   int* reqs, int* naux, int* aux_ids, int* id_out,
                   int* status) {
  std::vector<NDArrayHandle> args(*n), grads(*n), aux(*naux);
  std::vector<mx_uint> req(*n);
  for (int i = 0; i < *n; ++i) {
    args[i] = get_handle(arg_ids[i]);
    grads[i] = grad_ids[i] > 0 ? get_handle(grad_ids[i]) : nullptr;
    req[i] = (mx_uint)reqs[i];
  }
  for (int i = 0; i < *naux; ++i) aux[i] = get_handle(aux_ids[i]);
  ExecutorHandle h;
  *status = record(MXExecutorBind(get_handle(*sym_id), 1, 0, *n, args.data(),
                                  grads.data(), req.data(), *naux,
                                  aux.data(), &h));
  if (*status == 0) *id_out = put_handle(h);
}

void mxr_exec_forward(int* id, int* is_train, int* status) {
  *status = record(MXExecutorForward(get_handle(*id), *is_train));
}

void mxr_exec_backward(int* id, int* status) {
  *status = record(MXExecutorBackward(get_handle(*id), 0, nullptr));
}

void mxr_exec_outputs(int* id, int* ids_out, int* n_out, int* status) {
  mx_uint n;
  NDArrayHandle* outs;
  *status = record(MXExecutorOutputs(get_handle(*id), &n, &outs));
  if (*status != 0) return;
  if (n > 64) {  // R caller allocates 64 id slots
    g_last_error = "executor has more than 64 outputs";
    *status = -1;
    return;
  }
  *n_out = (int)n;
  for (mx_uint i = 0; i < n; ++i) ids_out[i] = put_handle(outs[i]);
}

}  // extern "C"
