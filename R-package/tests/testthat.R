# testthat entry point (reference capability: R-package/tests/testthat.R).
# The package is not installable without an R toolchain in this image, so
# the runner loads the shim + sources the R layer (demo/demo_loader.R
# pattern) instead of library(mxtpu); the test files themselves are
# interpreter-agnostic testthat and are lint-checked in CI
# (tests/test_r_lint.py) until an R interpreter is available.

library(testthat)
# normalize cwd to the R-package root: `Rscript tests/testthat.R` runs from
# the package root already; R CMD check runs from tests/
if (!file.exists(file.path("demo", "demo_loader.R")) &&
    file.exists(file.path("..", "demo", "demo_loader.R"))) setwd("..")
source(file.path("demo", "demo_loader.R"))

test_dir(file.path("tests", "testthat"))
