# ndarray tier (reference capability: R-package/tests/testthat/
# test_ndarray.R — elementwise arithmetic incl. reversed scalar forms,
# zeros, save/load). Written against the runtime-backed ndarray.R layer.

context("ndarray")

test_that("element-wise calculation for vector", {
  x <- as.double(1:10)
  mat <- mx.nd.array(x)
  expect_equal(x, as.vector(as.array(mat)))
  expect_equal(x + 1, as.vector(as.array(mat + 1)))
  expect_equal(x - 10, as.vector(as.array(mat - 10)))
  expect_equal(x * 20, as.vector(as.array(mat * 20)))
  expect_equal(x / 3, as.vector(as.array(mat / 3)), tolerance = 1e-5)
  expect_equal(-1 - x, as.vector(as.array(-1 - mat)))
  expect_equal(-5 / x, as.vector(as.array(-5 / mat)), tolerance = 1e-5)
  expect_equal(x + x, as.vector(as.array(mat + mat)))
  expect_equal(x / x, as.vector(as.array(mat / mat)))
  expect_equal(x * x, as.vector(as.array(mat * mat)))
  expect_equal(x - x, as.vector(as.array(mat - mat)))
})

test_that("element-wise calculation for matrix", {
  x <- matrix(as.double(1:4), 2, 2)
  mat <- mx.nd.array(x)
  expect_equal(x, as.array(mat))
  expect_equal(x + 1, as.array(mat + 1))
  expect_equal(x * 20, as.array(mat * 20))
  expect_equal(-1 - x, as.array(-1 - mat))
  expect_equal(-5 / x, as.array(-5 / mat), tolerance = 1e-5)
  expect_equal(x * x, as.array(mat * mat))
})

test_that("ndarray zeros, dot, norm, save and load", {
  expect_equal(rep(0, 10), as.vector(as.array(mx.nd.zeros(10L))))
  expect_equal(matrix(0, 10, 5), as.array(mx.nd.zeros(c(10L, 5L))))
  a <- mx.nd.array(matrix(as.double(1:6), 2, 3))
  b <- mx.nd.array(matrix(as.double(1:6), 3, 2))
  d <- mx.nd.dot(a, b)
  expect_equal(mx.nd.shape(d), c(2L, 2L))
  expect_equal(as.vector(as.array(mx.nd.norm(d))),
               sqrt(sum(as.array(d)^2)), tolerance = 1e-5)
  fname <- tempfile(fileext = ".nd")
  mx.nd.save(list(mat = d), fname)
  back <- mx.nd.load(fname)
  expect_equal(as.array(back[["mat"]]), as.array(d))
  file.remove(fname)
})

test_that("device RNG reproduces under mx.set.seed", {
  mx.set.seed(7)
  u1 <- as.array(mx.runif(c(3L, 3L)))
  mx.set.seed(7)
  u2 <- as.array(mx.runif(c(3L, 3L)))
  expect_identical(u1, u2)
})
