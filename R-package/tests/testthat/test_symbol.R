# symbol tier (reference capability: R-package/tests/testthat/
# test_symbol.R — compose, list arguments, JSON round-trip, shape
# inference). Written against the runtime-backed symbol.R layer.

context("symbol")

mlp <- function() {
  data <- mx.symbol.Variable("data")
  fc1 <- mx.symbol.FullyConnected(data = data, num_hidden = 100,
                                  name = "fc1")
  act <- mx.symbol.Activation(data = fc1, act_type = "relu", name = "relu1")
  fc2 <- mx.symbol.FullyConnected(data = act, num_hidden = 10, name = "fc2")
  mx.symbol.SoftmaxOutput(data = fc2, name = "softmax")
}

test_that("basic symbol operation", {
  net <- mlp()
  expect_true("fc1_weight" %in% mx.symbol.arguments(net))
  expect_true("softmax_label" %in% mx.symbol.arguments(net))
})

test_that("symbol JSON round-trip preserves the graph", {
  net <- mlp()
  js <- mx.symbol.tojson(net)
  net2 <- mx.symbol.fromjson(js)
  expect_identical(mx.symbol.arguments(net2), mx.symbol.arguments(net))
  expect_identical(mx.symbol.tojson(net2), js)
})

test_that("shape inference fills parameter shapes from the data shape", {
  net <- mlp()
  shapes <- mx.symbol.infer.shapes(net, c(32L, 784L))
  names(shapes$arg_shapes) <- mx.symbol.arguments(net)
  expect_equal(shapes$arg_shapes[["fc1_weight"]], c(100L, 784L))
  expect_equal(shapes$arg_shapes[["fc2_bias"]], c(10L))
})
