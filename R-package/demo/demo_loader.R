# Shared prologue for the basic_* demos (reference capability:
# R-package/demo/ — the reference demos open with require(mxnet); without
# an installed package the equivalent is loading the .C shim and sourcing
# the R layer into one namespace, the same order demo/lenet_train.R uses).
#
# Run any demo from the R-package directory with the shims built:
#   make -C ../mxnet_tpu/native capi
#   g++ -O2 -std=c++17 -fPIC -shared src/mxtpu_r_train.cc \
#       -o src/libmxtpu_r_train.so -L../mxnet_tpu/native -lmxtpu_capi \
#       -Wl,-rpath,$(realpath ../mxnet_tpu/native)
#   PYTHONPATH=$(realpath ..) Rscript demo/basic_ndarray.R

dyn.load(file.path("src", "libmxtpu_r_train.so"))
source(file.path("R", "mxtpu_train.R"))
source(file.path("R", "ndarray.R"))
source(file.path("R", "symbol.R"))
source(file.path("R", "executor.R"))
source(file.path("R", "mxtpu_generated.R"))
source(file.path("R", "optimizer.R"))
source(file.path("R", "initializer.R"))
source(file.path("R", "metric.R"))
source(file.path("R", "callback.R"))
source(file.path("R", "io.R"))
source(file.path("R", "kvstore.R"))
source(file.path("R", "model.R"))
source(file.path("R", "util.R"))
source(file.path("R", "context.R"))
source(file.path("R", "random.R"))
source(file.path("R", "viz.graph.R"))
