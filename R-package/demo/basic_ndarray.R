# Basic ndarray operations from R (reference capability:
# R-package/demo/basic_ndarray.R — arithmetic on device-backed arrays plus
# context descriptors). Every expression below runs inside the runtime via
# the registered NDArray functions; R holds only integer handles.

source(file.path("demo", "demo_loader.R"))

# vector construction and composed arithmetic (Ops group dispatch)
mat <- mx.nd.array(1:3)
mat <- mat + 1.0
mat <- mat + mat
mat <- mat - 5
mat <- 10 / mat
mat <- 7 * mat
mat <- 1 - mat + (2 * mat) / (mat + 0.5)
print(as.array(mat))

# matrices: dot product and norm run as runtime kernels
a <- mx.nd.array(matrix(1:6, 2, 3))
b <- mx.nd.array(matrix(1:6, 3, 2))
d <- mx.nd.dot(a, b)
cat("dot shape:", paste(mx.nd.shape(d), collapse = "x"),
    " norm:", as.array(mx.nd.norm(d)), "\n")

# save/load round-trip in the framework's checkpoint format
tmp <- tempfile(fileext = ".nd")
mx.nd.save(list(weights = d), tmp)
back <- mx.nd.load(tmp)
stopifnot(all.equal(as.array(back[["weights"]]), as.array(d)))
file.remove(tmp)

# contexts: the accelerator slot is the TPU; mx.gpu() aliases it so
# reference scripts stay portable
mx.ctx.default(mx.tpu(0))
print(mx.ctx.default())
print(is.mx.context(mx.cpu()))
