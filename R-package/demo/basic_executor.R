# Basic executor usage from R (reference capability:
# R-package/demo/basic_executor.R — bind a symbol over explicit argument
# arrays, run forward/backward, read outputs and gradients).

source(file.path("demo", "demo_loader.R"))

data <- mx.symbol.Variable("data")
fc <- mx.symbol.FullyConnected(data = data, num_hidden = 4, name = "fc")
net <- mx.symbol.SoftmaxOutput(data = fc, name = "softmax")

batch <- 2L
shapes <- mx.symbol.infer.shapes(net, c(batch, 3L))
arg_names <- mx.symbol.arguments(net)
print(arg_names)

mx.set.seed(0)
args <- integer(length(arg_names))
grads <- integer(length(arg_names))
reqs <- integer(length(arg_names))
for (i in seq_along(arg_names)) {
  shp <- shapes$arg_shapes[[i]]
  if (arg_names[i] == "data") {
    args[i] <- mx.nd.array(matrix(c(1, 2, 3, 4, 5, 6), nrow = batch,
                                  byrow = TRUE))
  } else if (mx.util.str.endswith(arg_names[i], "label")) {
    args[i] <- mx.nd.array(c(0, 3))
  } else {
    args[i] <- mx.runif(shp, min = -0.1, max = 0.1)
  }
  is_param <- arg_names[i] != "data" &&
    !mx.util.str.endswith(arg_names[i], "label")
  if (is_param) {
    grads[i] <- mx.nd.zeros(shp)
    reqs[i] <- 1L
  }
}

ex <- mx.executor.bind(net, args, grads, reqs, integer(0))
mx.executor.forward(ex, is.train = TRUE)
outs <- mx.executor.outputs(ex)
cat("softmax output:\n")
print(as.array(outs[[1]]))

# SoftmaxOutput injects the cross-entropy gradient at the head
mx.executor.backward(ex)
widx <- which(arg_names == "fc_weight")
cat("d loss / d fc_weight:\n")
print(as.array(structure(grads[widx], class = "mxtpu.ndarray")))
