# Device RNG from R (reference capability:
# R-package/demo/basic_random.R — mx.set.seed drives the framework RNG,
# separate from R's set.seed; samplers run inside the runtime).

source(file.path("demo", "demo_loader.R"))

mx.set.seed(42)
u1 <- as.array(mx.runif(c(2L, 3L), min = 0, max = 1))
n1 <- as.array(mx.rnorm(c(2L, 3L), mean = 0, sd = 2))

# re-seeding reproduces the exact stream
mx.set.seed(42)
u2 <- as.array(mx.runif(c(2L, 3L), min = 0, max = 1))
n2 <- as.array(mx.rnorm(c(2L, 3L), mean = 0, sd = 2))

stopifnot(identical(u1, u2), identical(n1, n2))
print(u1)
print(n1)
