# NDArray micro-benchmark from R (reference capability:
# R-package/demo/basic_bench.R — time device ops driven from the binding;
# the point is that R only dispatches, the runtime does the math).

source(file.path("demo", "demo_loader.R"))

mx.set.seed(0)
n <- 256L
a <- mx.runif(c(n, n))
b <- mx.runif(c(n, n))

iters <- 50
t0 <- proc.time()[["elapsed"]]
out <- a
for (i in seq_len(iters)) {
  out <- mx.nd.dot(out, b)
  out <- out / mx.nd.norm(out)   # keep values bounded, chain the result
}
sync <- as.array(mx.nd.norm(out))  # readback fences the device queue
t1 <- proc.time()[["elapsed"]]

gflop <- iters * 2 * as.double(n)^3 / 1e9
cat(sprintf("%d chained %dx%d dots: %.3f s (%.1f GFLOP/s)\n",
            iters, n, n, t1 - t0, gflop / (t1 - t0)))
