# Train a conv net from R (reference capability: R-package/R/model.R
# mx.model.FeedForward.create + demo/).
#
# Run from the R-package directory with the shims built:
#   make -C ../mxnet_tpu/native capi
#   g++ -O2 -std=c++17 -fPIC -shared src/mxtpu_r_train.cc \
#       -o src/libmxtpu_r_train.so -L../mxnet_tpu/native -lmxtpu_capi \
#       -Wl,-rpath,$(realpath ../mxnet_tpu/native)
#   PYTHONPATH=$(realpath ..) Rscript demo/lenet_train.R
#
# (The embedded Python runtime needs PYTHONPATH to import mxnet_tpu.)

dyn.load(file.path("src", "libmxtpu_r_train.so"))
source(file.path("R", "mxtpu_train.R"))
source(file.path("R", "ndarray.R"))
source(file.path("R", "symbol.R"))
source(file.path("R", "executor.R"))
source(file.path("R", "mxtpu_generated.R"))
source(file.path("R", "optimizer.R"))
source(file.path("R", "initializer.R"))
source(file.path("R", "metric.R"))
source(file.path("R", "callback.R"))
source(file.path("R", "io.R"))
source(file.path("R", "kvstore.R"))
source(file.path("R", "model.R"))
source(file.path("R", "util.R"))
source(file.path("R", "context.R"))
source(file.path("R", "random.R"))
source(file.path("R", "viz.graph.R"))

mx.r.seed(0)

# --- synthetic two-class 8x8 image task (offline-safe) ----------------------
n <- 512
X <- array(0, dim = c(8, 8, 1, n))   # R convention: sample axis LAST
y <- integer(n)
set.seed(0)
for (i in seq_len(n)) {
  cls <- i %% 2
  img <- matrix(rnorm(64) * 0.1, 8, 8)
  if (cls == 1) img[3:6, 3:6] <- img[3:6, 3:6] + 1.0
  else img[2:7, 4:5] <- img[2:7, 4:5] + 1.0
  X[, , 1, i] <- img
  y[i] <- cls
}

# --- LeNet-style symbol, composed exactly like the Python API ---------------
data <- mx.symbol.Variable("data")
c1 <- mx.symbol.Convolution(data = data, kernel = c(3, 3), pad = c(1, 1),
                            num_filter = 8, name = "c1")
a1 <- mx.symbol.Activation(data = c1, act_type = "relu", name = "a1")
p1 <- mx.symbol.Pooling(data = a1, kernel = c(2, 2), stride = c(2, 2),
                        pool_type = "max", name = "p1")
f  <- mx.symbol.Flatten(data = p1, name = "flat")
fc1 <- mx.symbol.FullyConnected(data = f, num_hidden = 16, name = "fc1")
a2 <- mx.symbol.Activation(data = fc1, act_type = "relu", name = "a2")
fc2 <- mx.symbol.FullyConnected(data = a2, num_hidden = 2, name = "fc2")
net <- mx.symbol.SoftmaxOutput(data = fc2, name = "softmax")

cat("arguments:", paste(mx.symbol.arguments(net), collapse = ", "), "\n")

# --- train ------------------------------------------------------------------
# gradients round through the kvstore (aggregation path) and the optimizer
# update runs inside the runtime via registered NDArray functions
kv <- mx.kv.create("local")
model <- mx.model.FeedForward.create(net, X, y, batch.size = 32,
                                     num.round = 8, learning.rate = 0.1,
                                     momentum = 0.9, kv = kv,
                                     initializer = mx.init.Xavier(),
                                     eval.metric = mx.metric.accuracy,
                                     batch.end.callback =
                                       mx.callback.log.train.metric(8))

stopifnot(model$train_acc > 0.9)

# --- checkpoint round-trip (format-compatible with the Python layer) --------
mx.model.save(model, file.path(tempdir(), "lenet_r"), 8)
loaded <- mx.model.load(file.path(tempdir(), "lenet_r"), 8)
stopifnot(length(loaded$arg_params) == 6)  # c1/fc1/fc2 weight+bias
bound <- mx.model.bind(loaded, c(32L, 1L, 8L, 8L))
prob2 <- mx.model.predict(bound, X, batch.size = 32)
cat("checkpoint save/load/bind/predict round-trip OK\n")

# --- predict + symbol JSON round-trip ---------------------------------------
prob <- mx.model.predict(model, X, batch.size = 32)  # N x classes
pred <- max.col(prob) - 1
cat(sprintf("final train accuracy: %.4f\n", mean(pred == y)))

js <- mx.symbol.tojson(net)
net2 <- mx.symbol.fromjson(js)
stopifnot(identical(mx.symbol.arguments(net), mx.symbol.arguments(net2)))
cat("symbol JSON round-trip OK\n")
