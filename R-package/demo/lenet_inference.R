# LeNet inference end-to-end from R (reference capability:
# R-package/vignettes — train in Python, predict from R).
#
# Python side (once):
#   import mxnet_tpu as mx, jax.numpy as jnp
#   model = mx.FeedForward(mx.models.lenet(), ctx=mx.tpu(), num_epoch=8,
#                          learning_rate=0.1, momentum=0.9,
#                          initializer=mx.init.Xavier())
#   model.fit(X, y, batch_size=32)
#   from mxnet_tpu.predictor import Predictor
#   Predictor(model.symbol, model.arg_params,
#             model.aux_params).export("lenet.mxtpu")
#
# R side (this script):

library(mxtpu)

args <- commandArgs(trailingOnly = TRUE)
bundle <- if (length(args) >= 1) args[[1]] else "lenet.mxtpu"

pred <- mx.pred.create(bundle)

# 10 random 28x28 grayscale digits as an mxtpu.ndarray (NCHW)
X <- mx.nd.array(array(runif(10 * 1 * 28 * 28), c(10, 1, 28, 28)))
cat("input: "); print(mx.nd.shape(X))

# batched prediction: slices the leading dim, pads the tail batch,
# stacks the de-padded softmax outputs
probs <- mx.pred.predict(pred, X, input.name = "data", batch.size = 4)
stopifnot(all(dim(probs) == c(10, 10)))
stopifnot(all(abs(rowSums(probs) - 1) < 1e-4))  # softmax rows sum to 1

classes <- max.col(probs) - 1  # 0-based digit labels
cat("predicted classes:", classes, "\n")

mx.pred.free(pred)
cat("lenet inference OK\n")
