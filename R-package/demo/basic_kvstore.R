# Basic kvstore usage from R (reference capability:
# R-package/demo/basic_kvstore.R — init/push/pull on a local store, the
# aggregation primitive FeedForward's multi-device training rides on).

source(file.path("demo", "demo_loader.R"))

kv <- mx.kv.create("local")
cat(sprintf("rank %d of %d workers\n", mx.kv.rank(kv), mx.kv.num.workers(kv)))

shape <- c(2L, 3L)
mx.kv.init(kv, 3L, list(mx.nd.array(array(1, shape))))

# pushing several values under ONE key aggregates them (sum) in the store
g1 <- mx.nd.array(array(2, shape))
g2 <- mx.nd.array(array(5, shape))
mx.kv.push(kv, c(3L, 3L), list(g1, g2))

out <- mx.nd.zeros(shape)
mx.kv.pull(kv, 3L, list(out))
print(as.array(out))   # all 7 = 2 + 5

mx.kv.free(kv)
