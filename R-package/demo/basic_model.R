# Train / save / load / predict from R (reference capability:
# R-package/demo/basic_model.R — mx.model.FeedForward.create on a small
# task, then checkpoint round-trip and batched prediction).

source(file.path("demo", "demo_loader.R"))

mx.set.seed(0)

# synthetic two-class task: 16 features, sample axis LAST (R convention)
n <- 256
set.seed(0)
X <- array(rnorm(16 * n) * 0.1, dim = c(16, n))
y <- integer(n)
for (i in seq_len(n)) {
  cls <- i %% 2
  if (cls == 1) X[1:8, i] <- X[1:8, i] + 1 else X[9:16, i] <- X[9:16, i] + 1
  y[i] <- cls
}

data <- mx.symbol.Variable("data")
fc1 <- mx.symbol.FullyConnected(data = data, num_hidden = 16, name = "fc1")
act <- mx.symbol.Activation(data = fc1, act_type = "relu", name = "relu1")
fc2 <- mx.symbol.FullyConnected(data = act, num_hidden = 2, name = "fc2")
net <- mx.symbol.SoftmaxOutput(data = fc2, name = "softmax")

model <- mx.model.FeedForward.create(net, X, y, batch.size = 32,
                                     num.round = 3, learning.rate = 0.5,
                                     momentum = 0.9,
                                     initializer = mx.init.Xavier())

# checkpoint round-trip in the framework's (Python-compatible) format
prefix <- file.path(tempdir(), "basic_model_demo")
mx.model.save(model, prefix, 3)
loaded <- mx.model.bind(mx.model.load(prefix, 3), c(32L, 16L))

probs <- mx.model.predict(loaded, X, batch.size = 32)
acc <- mean(max.col(probs) - 1L == y)
cat(sprintf("restored-model accuracy: %.3f\n", acc))
stopifnot(acc > 0.9)
