# Model layer for the R binding (reference capability:
# R-package/R/model.R — mx.model.FeedForward.create, mx.model.save,
# mx.model.load over the C API).
#
# Checkpoint FORMAT PARITY: mx.model.save writes `prefix-symbol.json` +
# `prefix-%04d.params` through the SAME container writer Python uses
# (MXNDArraySave with arg:/aux: prefixed names), so checkpoints round-trip
# between R and Python FeedForward.load/save in both directions
# (mxnet_tpu/model.py:63-85 save_checkpoint/load_checkpoint).
#
# Training routes through the framework: batches come from
# mx.io.NDArrayIter, gradients flow through the executor, and the update
# runs via mx.opt.get.updater whose math executes inside the runtime
# (registered NDArray functions — see optimizer.R). No in-R SGD.

.mxr.nd.from.host <- function(shape_rowmajor, values) {
  r <- .mxr.status(.C("mxr_nd_create", as.integer(shape_rowmajor),
                      as.integer(length(shape_rowmajor)), id = integer(1),
                      status = integer(1)))
  .mxr.status(.C("mxr_nd_set", as.integer(r$id), as.double(values),
                 as.integer(length(values)), status = integer(1)))
  structure(r$id, class = "mxtpu.ndarray")
}

mx.model.save <- function(model, prefix, iteration) {
  json <- mx.symbol.tojson(model$symbol)
  writeLines(json, paste0(prefix, "-symbol.json"))
  nds <- list()
  for (i in seq_along(model$arg_names)) {
    nm <- model$arg_names[i]
    if (nm == "data" || grepl("(^|_)label$", nm)) next
    nds[[paste0("arg:", nm)]] <- model$args[i]
  }
  if (!is.null(model$aux_names) && length(model$aux_names) > 0) {
    for (i in seq_along(model$aux_names)) {
      nds[[paste0("aux:", model$aux_names[i])]] <- model$auxs[i]
    }
  }
  mx.nd.save(nds, sprintf("%s-%04d.params", prefix, iteration))
}

# returns list(symbol, arg_params, aux_params) — named ndarray-id lists;
# hand the result to mx.model.bind(loaded, data_shape) to get a
# predict-ready model (mx.model.predict consumes its executor).
mx.model.load <- function(prefix, iteration) {
  json <- paste(readLines(paste0(prefix, "-symbol.json")), collapse = "\n")
  symbol <- mx.symbol.fromjson(json)
  loaded <- mx.nd.load(sprintf("%s-%04d.params", prefix, iteration))
  arg_params <- list()
  aux_params <- list()
  for (nm in names(loaded)) {
    if (mx.util.str.startswith(nm, "arg:")) {
      arg_params[[substring(nm, 5)]] <- loaded[[nm]]
    } else if (mx.util.str.startswith(nm, "aux:")) {
      aux_params[[substring(nm, 5)]] <- loaded[[nm]]
    }
  }
  list(symbol = symbol, arg_params = arg_params, aux_params = aux_params)
}

# Rebuild a forward-ready model from mx.model.load's result: bind an
# executor over the loaded parameter ndarrays (no gradient buffers),
# zero-filled data/label slots sized from `data_shape` (row-major,
# batch first). The returned structure feeds mx.model.predict.
mx.model.bind <- function(loaded, data_shape) {
  symbol <- loaded$symbol
  arg_names <- mx.symbol.arguments(symbol)
  shapes <- mx.symbol.infer.shapes(symbol, data_shape)
  args <- integer(length(arg_names))
  for (i in seq_along(arg_names)) {
    nm <- arg_names[i]
    if (!is.null(loaded$arg_params[[nm]])) {
      args[i] <- loaded$arg_params[[nm]]
    } else {
      shp <- shapes$arg_shapes[[i]]
      args[i] <- .mxr.nd.from.host(shp, rep(0, prod(shp)))
    }
  }
  aux_names <- mx.symbol.aux(symbol)
  auxs <- integer(0)
  if (length(aux_names) > 0) {
    auxs <- vapply(seq_along(aux_names), function(i) {
      nm <- aux_names[i]
      if (!is.null(loaded$aux_params[[nm]])) {
        loaded$aux_params[[nm]]
      } else {
        shp <- shapes$aux_shapes[[i]]
        .mxr.nd.from.host(shp, rep(0, prod(shp)))
      }
    }, integer(1))
  }
  ex <- mx.executor.bind(symbol, args, integer(length(arg_names)),
                         integer(length(arg_names)), auxs)
  structure(list(executor = ex, arg_names = arg_names, args = args,
                 aux_names = aux_names, auxs = auxs, symbol = symbol),
            class = "mxtpu.model")
}

# Train `symbol` on X (R dim order, sample axis LAST) / y. The kv argument
# accepts NULL (single-process) or an mxtpu.kvstore: gradients are then
# push/pulled through the store before the optimizer step, the multi-worker
# aggregation path (reference model.R kvstore=TRUE route).
mx.model.FeedForward.create <- function(symbol, X, y, batch.size = 32,
                                        num.round = 10, learning.rate = 0.1,
                                        momentum = 0.9, wd = 0,
                                        initializer.scale = 0.1,
                                        initializer = NULL,
                                        eval.metric = NULL,
                                        batch.end.callback = NULL,
                                        epoch.end.callback = NULL,
                                        kv = NULL, verbose = TRUE) {
  # default initializer keeps the historical behavior (normal * scale);
  # pass e.g. mx.init.Xavier() for conv nets (initializer.R)
  if (is.null(initializer))
    initializer <- mx.init.normal(initializer.scale)
  if (is.null(eval.metric)) eval.metric <- mx.metric.accuracy
  iter <- mx.io.NDArrayIter(X, y, batch.size = batch.size)
  nd <- length(dim(X))
  data_shape <- c(batch.size, rev(dim(X)[-nd]))

  arg_names <- mx.symbol.arguments(symbol)
  shapes <- mx.symbol.infer.shapes(symbol, data_shape)

  args <- integer(length(arg_names))
  grads <- integer(length(arg_names))
  reqs <- integer(length(arg_names))
  weight_ids <- list()
  grad_ids <- list()
  set.seed(0)
  for (i in seq_along(arg_names)) {
    shp <- shapes$arg_shapes[[i]]
    nm <- arg_names[i]
    args[i] <- .mxr.nd.from.host(shp, mx.init.param(initializer, nm, shp))
    if (nm == "data" || grepl("(^|_)label$", nm)) {
      grads[i] <- 0L
      reqs[i] <- 0L
    } else {
      grads[i] <- .mxr.nd.from.host(shp, rep(0, prod(shp)))
      reqs[i] <- 1L
      weight_ids[[length(weight_ids) + 1L]] <- args[i]
      grad_ids[[length(grad_ids) + 1L]] <- grads[i]
    }
  }
  aux_names <- mx.symbol.aux(symbol)
  auxs <- integer(0)
  if (length(aux_names) > 0) {
    auxs <- vapply(seq_along(aux_names), function(i) {
      shp <- shapes$aux_shapes[[i]]
      init <- if (grepl("var", aux_names[i])) rep(1, prod(shp))
              else rep(0, prod(shp))
      .mxr.nd.from.host(shp, init)
    }, integer(1))
  }

  ex <- mx.executor.bind(symbol, args, grads, reqs, auxs)
  data_idx <- which(arg_names == "data")
  label_idx <- which(grepl("(^|_)label$", arg_names))

  # with a kvstore the pulled gradient is the SUM across workers, so the
  # rescale folds in num_workers — same semantics as the Python layer
  # (mxnet_tpu/model.py fit: rescale_grad = 1/(batch_size*num_workers))
  nworkers <- if (is.null(kv)) 1L else mx.kv.num.workers(kv)
  optimizer <- mx.opt.create("sgd", learning.rate = learning.rate,
                             momentum = momentum, wd = wd,
                             rescale.grad = 1 / (batch.size * nworkers))
  updater <- mx.opt.get.updater(optimizer, weight_ids)
  if (!is.null(kv)) {
    mx.kv.init(kv, seq_along(weight_ids) - 1L, weight_ids)
  }

  acc <- 0
  model <- structure(list(executor = ex, arg_names = arg_names, args = args,
                          aux_names = aux_names, auxs = auxs,
                          symbol = symbol, train_acc = 0),
                     class = "mxtpu.model")
  for (round in seq_len(num.round)) {
    mstate <- eval.metric$init()
    nbatch <- 0
    iter$reset()
    while (iter$iter.next()) {
      b <- iter$value()
      # b$data is features-by-batch: as.double flattens it straight into
      # the runtime's row-major (batch, features...) layout (see io.R)
      .mxr.status(.C("mxr_nd_set", as.integer(args[data_idx]),
                     as.double(b$data), as.integer(length(b$data)),
                     status = integer(1)))
      .mxr.status(.C("mxr_nd_set", as.integer(args[label_idx]),
                     as.double(b$label), as.integer(batch.size),
                     status = integer(1)))
      mx.executor.forward(ex, is.train = TRUE)
      outs <- mx.executor.outputs(ex)
      prob <- as.array.mxtpu.ndarray(outs[[1]])  # batch x classes
      keep <- batch.size - b$pad
      mstate <- eval.metric$update(
        b$label[seq_len(keep)], prob[seq_len(keep), , drop = FALSE], mstate)
      nbatch <- nbatch + 1
      for (o in outs) mx.nd.free(o)
      mx.executor.backward(ex)
      if (!is.null(kv)) {
        # aggregate gradients across workers through the store, then the
        # local optimizer applies the combined gradient (update-on-worker)
        mx.kv.push(kv, seq_along(grad_ids) - 1L, grad_ids)
        mx.kv.pull(kv, seq_along(grad_ids) - 1L, grad_ids)
      }
      updater(weight_ids, grad_ids)
      if (!is.null(batch.end.callback)) {
        batch.end.callback(list(epoch = round, nbatch = nbatch,
                                metric.state = mstate,
                                metric.get = eval.metric$get))
      }
    }
    m <- eval.metric$get(mstate)
    if (verbose)
      message(sprintf("Round [%d] train %s: %.4f", round, m$name, m$value))
    acc <- m$value
    model$train_acc <- acc
    if (!is.null(epoch.end.callback)) epoch.end.callback(round, model)
  }
  model
}
