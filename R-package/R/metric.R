# Evaluation metrics for the R binding (reference capability:
# R-package/R/metric.R — mx.metric.custom and the accuracy/rmse/mae set,
# an environment-based accumulator with init/update/get).
#
# update(label, pred, state): label is the batch label vector, pred the
# batch-by-classes (or batch-long) prediction matrix the executor
# returned; state is the accumulator environment. feval returns the batch
# MEAN; the accumulator weights it by the batch's sample count, so the
# final partial (de-padded) batch counts sample-exactly, not batch-equal.

mx.metric.custom <- function(name, feval) {
  init <- function() {
    env <- new.env()
    env$sum <- 0
    env$n <- 0
    env
  }
  update <- function(label, pred, state) {
    k <- length(label)
    state$sum <- state$sum + feval(label, pred) * k
    state$n <- state$n + k
    state
  }
  get <- function(state) list(name = name, value = state$sum / state$n)
  list(init = init, update = update, get = get)
}

mx.metric.accuracy <- mx.metric.custom("accuracy", function(label, pred) {
  if (is.matrix(pred) && ncol(pred) > 1) {
    mean((max.col(pred) - 1) == label)
  } else {
    mean((as.numeric(pred) > 0.5) == label)
  }
})

mx.metric.rmse <- mx.metric.custom("rmse", function(label, pred) {
  sqrt(mean((label - as.numeric(pred))^2))
})

mx.metric.mae <- mx.metric.custom("mae", function(label, pred) {
  mean(abs(label - as.numeric(pred)))
})
