# Device random numbers for the R binding (reference capability:
# R-package/R/random.R — mx.set.seed / mx.runif / mx.rnorm).
#
# The samplers run INSIDE the runtime via the registered NDArray functions
# `_random_uniform` / `_random_gaussian` (capi_support.py _FUNCTIONS;
# reference ndarray.cc registered sampler pair): R never generates the
# numbers, it only seeds the framework RNG and reads results back. That is
# why mx.set.seed exists as its own function — the reference kept device
# RNG state separate from R's set.seed for exactly this reason, and here
# the state is the runtime's PRNG key chain (mxnet_tpu/random.py), not R's.

mx.set.seed <- function(seed) {
  invisible(.mxr.status(.C("mxr_random_seed", as.integer(seed),
                           status = integer(1))))
}

# Uniform in [min, max): scalars ride the registered function's scalar
# slots; the runtime sampler overwrites a freshly allocated ndarray
# (.mxr.nd.alloc, ndarray.R — runtime dims == logical R dims).
mx.runif <- function(shape, min = 0, max = 1) {
  stopifnot(is.numeric(min), is.numeric(max))
  out <- .mxr.nd.alloc(shape)
  .mxr.func("_random_uniform", integer(0), c(min, max), out)
  out
}

# Normal with mean/sd.
mx.rnorm <- function(shape, mean = 0, sd = 1) {
  stopifnot(is.numeric(mean), is.numeric(sd))
  out <- .mxr.nd.alloc(shape)
  .mxr.func("_random_gaussian", integer(0), c(mean, sd), out)
  out
}
