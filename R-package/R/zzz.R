# Package load hooks (reference capability: R-package/R/zzz.R — native
# library load on attach and version banner).
#
# The INSTALLED package's native code is libs/mxtpu.so (src/Makevars
# compiles the predict shim + standalone predictor; NAMESPACE's
# useDynLib(mxtpu) plus library.dynam here load it). The TRAINING shim
# (src/libmxtpu_r_train.so, which links the embedded-CPython runtime via
# libmxtpu_capi) is a development artifact built next to the repo and
# dyn.load'ed explicitly — see demo/lenet_train.R — because an installed
# R library cannot carry the Python runtime dependency.

.onLoad <- function(libname, pkgname) {
  library.dynam("mxtpu", pkgname, libname)
}

.onAttach <- function(libname, pkgname) {
  packageStartupMessage("mxtpu: TPU-native MXNet-compatible runtime")
}

.onUnload <- function(libpath) {
  library.dynam.unload("mxtpu", libpath)
}
