# Package load hooks (reference capability: R-package/R/zzz.R — dyn.load
# of the native library on attach and version banner).

.onLoad <- function(libname, pkgname) {
  lib <- file.path(libname, pkgname, "libs", "libmxtpu_r_train.so")
  if (file.exists(lib)) dyn.load(lib)
}

.onAttach <- function(libname, pkgname) {
  packageStartupMessage("mxtpu: TPU-native MXNet-compatible runtime")
}

.onUnload <- function(libpath) {
  lib <- file.path(libpath, "libs", "libmxtpu_r_train.so")
  if (file.exists(lib)) dyn.unload(lib)
}
