# Small shared helpers for the R binding (reference capability:
# R-package/R/util.R — string predicates and list filtering the user layer
# leans on).

mx.util.str.endswith <- function(name, suffix) {
  n <- nchar(name)
  s <- nchar(suffix)
  s <= n && substring(name, n - s + 1, n) == suffix
}

mx.util.str.startswith <- function(name, prefix) {
  nchar(prefix) <= nchar(name) &&
    substring(name, 1, nchar(prefix)) == prefix
}

# drop NULL entries, preserving names (reference-parity helper: scripts
# written against the reference's util.R use it to prune optional-argument
# lists before do.call)
mx.util.filter.null <- function(lst) {
  lst[!vapply(lst, is.null, logical(1))]
}
