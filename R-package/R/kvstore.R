# KVStore binding (reference capability: R-package/R/kvstore.R —
# mx.kv.create and the init/push/pull surface over the C API's MXKVStore*).
#
# Keys are integers (the reference's R layer used integer keys too); values
# are mxtpu.ndarray handles. push/pull on a 'local'/'device' store give the
# aggregation semantics FeedForward training uses; on a 'dist_*' store the
# same calls ride the process-collective backend.

mx.kv.create <- function(type = "local") {
  r <- .mxr.status(.C("mxr_kv_create", as.character(type), id = integer(1),
                      status = integer(1)))
  structure(r$id, class = "mxtpu.kvstore")
}

mx.kv.free <- function(kv) {
  invisible(.C("mxr_kv_free", as.integer(kv), status = integer(1)))
}

.mx.kv.call <- function(entry, kv, keys, nds, priority = 0L) {
  stopifnot(length(keys) == length(nds))
  invisible(.mxr.status(.C(entry, as.integer(kv), as.integer(length(keys)),
                           as.integer(keys), as.integer(unlist(nds)),
                           as.integer(priority), status = integer(1))))
}

mx.kv.init <- function(kv, keys, nds) {
  stopifnot(length(keys) == length(nds))
  invisible(.mxr.status(.C("mxr_kv_init", as.integer(kv),
                           as.integer(length(keys)), as.integer(keys),
                           as.integer(unlist(nds)), status = integer(1))))
}

mx.kv.push <- function(kv, keys, nds, priority = 0L)
  .mx.kv.call("mxr_kv_push", kv, keys, nds, priority)

mx.kv.pull <- function(kv, keys, nds, priority = 0L)
  .mx.kv.call("mxr_kv_pull", kv, keys, nds, priority)

mx.kv.rank <- function(kv) {
  .mxr.status(.C("mxr_kv_rank", as.integer(kv), rank = integer(1),
                 status = integer(1)))$rank
}

mx.kv.num.workers <- function(kv) {
  .mxr.status(.C("mxr_kv_size", as.integer(kv), size = integer(1),
                 status = integer(1)))$size
}

mx.kv.barrier <- function(kv) {
  invisible(.mxr.status(.C("mxr_kv_barrier", as.integer(kv),
                           status = integer(1))))
}
