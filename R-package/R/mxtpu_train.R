# R training layer over the flat C API (reference capability:
# R-package/R/{ndarray,symbol,executor,model}.R — mx.nd.array, mx.symbol.*,
# mx.model.FeedForward.create). The deployment/inference layer lives in
# mxtpu.R; this file adds the training surface via the .C shim
# src/mxtpu_r_train.cc -> libmxtpu_capi (embedded CPython runtime).
#
# Load order: dyn.load("src/libmxtpu_r_train.so") with PYTHONPATH pointing
# at the repo root (the embedded interpreter must import mxnet_tpu).
# See demo/lenet_train.R for the end-to-end walkthrough.

.mxr.status <- function(r) {
  if (r$status != 0) {
    buf <- paste(rep(" ", 2048), collapse = "")
    e <- .C("mxr_last_error", msg = as.character(buf), as.integer(2048))
    stop("mxtpu: ", e$msg)
  }
  r
}

mx.r.seed <- function(seed) {
  invisible(.mxr.status(.C("mxr_random_seed", as.integer(seed),
                           status = integer(1))))
}

# The binding's module layout mirrors the reference's R-package/R/ split:
#   ndarray.R symbol.R executor.R    (moved from this file, round 5)
#   model.R optimizer.R io.R kvstore.R initializer.R metric.R callback.R
#   mxtpu_generated.R                (autogen op wrappers)
# This file keeps the shared status/error helper, the RNG seed hook, and
# the prediction entry (mx.model.predict) the inference demo uses.

# -------------------------------------------------------------- FeedForward
#
# mx.model.FeedForward.create / mx.model.save / mx.model.load moved to
# model.R (training now routes through optimizer.R's framework-resident
# updater and io.R's NDArrayIter; checkpoints are format-compatible with
# the Python layer). mx.model.predict stays here with the executor layer.

# forward-only prediction on a trained model (batch.size must divide N)
mx.model.predict <- function(model, X, batch.size = 32) {
  nd <- length(dim(X))
  n <- dim(X)[nd]
  Xflat <- array(X, dim = c(prod(dim(X)[-nd]), n))
  data_idx <- which(model$arg_names == "data")
  preds <- NULL
  for (start in seq(1, n - batch.size + 1, by = batch.size)) {
    idx <- start:(start + batch.size - 1)
    batch <- t(Xflat[, idx])
    .mxr.status(.C("mxr_nd_set", as.integer(model$args[data_idx]),
                   as.double(t(batch)), as.integer(length(batch)),
                   status = integer(1)))
    mx.executor.forward(model$executor, is.train = FALSE)
    outs <- mx.executor.outputs(model$executor)
    prob <- as.array.mxtpu.ndarray(outs[[1]])  # batch x classes
    for (o in outs) mx.nd.free(o)
    preds <- rbind(preds, prob)
  }
  preds  # N x classes
}
