# R training layer over the flat C API (reference capability:
# R-package/R/{ndarray,symbol,executor,model}.R — mx.nd.array, mx.symbol.*,
# mx.model.FeedForward.create). The deployment/inference layer lives in
# mxtpu.R; this file adds the training surface via the .C shim
# src/mxtpu_r_train.cc -> libmxtpu_capi (embedded CPython runtime).
#
# Load order: dyn.load("src/libmxtpu_r_train.so") with PYTHONPATH pointing
# at the repo root (the embedded interpreter must import mxnet_tpu).
# See demo/lenet_train.R for the end-to-end walkthrough.

.mxr.status <- function(r) {
  if (r$status != 0) {
    buf <- paste(rep(" ", 2048), collapse = "")
    e <- .C("mxr_last_error", msg = as.character(buf), as.integer(2048))
    stop("mxtpu: ", e$msg)
  }
  r
}

mx.r.seed <- function(seed) {
  invisible(.mxr.status(.C("mxr_random_seed", as.integer(seed),
                           status = integer(1))))
}

# ------------------------------------------------------------------ NDArray

mx.nd.array <- function(data) {
  # R arrays are column-major; the runtime is row-major. aperm the data,
  # keep the LOGICAL dims (same convention as mxtpu.R's predictor layer).
  dims <- dim(data)
  if (is.null(dims)) dims <- length(data)
  r <- .mxr.status(.C("mxr_nd_create", as.integer(dims),
                      as.integer(length(dims)), id = integer(1),
                      status = integer(1)))
  h <- structure(r$id, class = "mxtpu.ndarray", dims = dims)
  rowmajor <- aperm(array(data, dims), rev(seq_along(dims)))
  .mxr.status(.C("mxr_nd_set", as.integer(h), as.double(rowmajor),
                 as.integer(length(rowmajor)), status = integer(1)))
  h
}

mx.nd.zeros <- function(shape) mx.nd.array(array(0, dim = shape))

mx.nd.shape <- function(h) {
  r <- .mxr.status(.C("mxr_nd_shape", as.integer(h), ndim = integer(1),
                      shape = integer(8), status = integer(1)))
  r$shape[seq_len(r$ndim)]
}

as.array.mxtpu.ndarray <- function(x, ...) {
  shape <- mx.nd.shape(x)          # row-major dims
  n <- prod(shape)
  r <- .mxr.status(.C("mxr_nd_get", as.integer(x), data = double(n),
                      as.integer(n), status = integer(1)))
  # back to column-major R array with the logical dims
  aperm(array(r$data, dim = rev(shape)), rev(seq_along(shape)))
}

mx.nd.set <- function(h, data) {
  dims <- dim(data)
  if (is.null(dims)) dims <- length(data)
  rowmajor <- aperm(array(data, dims), rev(seq_along(dims)))
  invisible(.mxr.status(.C("mxr_nd_set", as.integer(h), as.double(rowmajor),
                           as.integer(length(rowmajor)),
                           status = integer(1))))
}

mx.nd.free <- function(h) {
  invisible(.C("mxr_nd_free", as.integer(h), status = integer(1)))
}

# ------------------------------------------------------------------- Symbol

mx.symbol.Variable <- function(name) {
  r <- .mxr.status(.C("mxr_sym_variable", as.character(name),
                      id = integer(1), status = integer(1)))
  structure(r$id, class = "mxtpu.symbol")
}

# generic operator constructor: mx.symbol.op("FullyConnected",
#   data = prev_symbol, num_hidden = 10, name = "fc1")
mx.symbol.op <- function(opname, ..., name = "") {
  all_args <- list(...)
  is_sym <- vapply(all_args, inherits, logical(1), "mxtpu.symbol")
  params <- all_args[!is_sym]
  inputs <- all_args[is_sym]
  r <- .mxr.status(.C("mxr_sym_atomic", as.character(opname),
                      as.integer(length(params)),
                      as.character(names(params)),
                      as.character(vapply(params, function(p)
                        paste0(as.character(p), collapse = ","),
                        character(1))),
                      id = integer(1), status = integer(1)))
  sym <- structure(r$id, class = "mxtpu.symbol")
  .mxr.status(.C("mxr_sym_compose", as.integer(sym), as.character(name),
                 as.integer(length(inputs)), as.character(names(inputs)),
                 as.integer(unlist(inputs)), status = integer(1)))
  sym
}

mx.symbol.FullyConnected <- function(...) mx.symbol.op("FullyConnected", ...)
mx.symbol.Activation <- function(...) mx.symbol.op("Activation", ...)
mx.symbol.Convolution <- function(...) mx.symbol.op("Convolution", ...)
mx.symbol.Pooling <- function(...) mx.symbol.op("Pooling", ...)
mx.symbol.Flatten <- function(...) mx.symbol.op("Flatten", ...)
mx.symbol.BatchNorm <- function(...) mx.symbol.op("BatchNorm", ...)
mx.symbol.SoftmaxOutput <- function(...) mx.symbol.op("SoftmaxOutput", ...)

mx.symbol.arguments <- function(sym) {
  buf <- paste(rep(" ", 65536L), collapse = "")
  r <- .mxr.status(.C("mxr_sym_arguments", as.integer(sym),
                      out = as.character(buf), as.integer(65536L),
                      status = integer(1)))
  strsplit(r$out, "\n")[[1]]
}

mx.symbol.aux <- function(sym) {
  buf <- paste(rep(" ", 65536L), collapse = "")
  r <- .mxr.status(.C("mxr_sym_aux", as.integer(sym),
                      out = as.character(buf), as.integer(65536L),
                      status = integer(1)))
  out <- strsplit(r$out, "\n")[[1]]
  out[nchar(out) > 0]
}

mx.symbol.tojson <- function(sym) {
  buf <- paste(rep(" ", 1048576L), collapse = "")
  r <- .mxr.status(.C("mxr_sym_tojson", as.integer(sym),
                      out = as.character(buf), as.integer(1048576L),
                      status = integer(1)))
  r$out
}

mx.symbol.fromjson <- function(js) {
  r <- .mxr.status(.C("mxr_sym_fromjson", as.character(js), id = integer(1),
                      status = integer(1)))
  structure(r$id, class = "mxtpu.symbol")
}

mx.symbol.infer.shapes <- function(sym, data_shape, data_name = "data",
                                   max_args = 1024L) {
  r <- .mxr.status(.C("mxr_sym_infer_shapes", as.integer(sym),
                      as.character(data_name), as.integer(data_shape),
                      as.integer(length(data_shape)),
                      as.integer(max_args),
                      n_args = integer(1), arg_ndims = integer(max_args),
                      arg_shapes = integer(max_args * 8),
                      n_aux = integer(1), aux_ndims = integer(max_args),
                      aux_shapes = integer(max_args * 8),
                      status = integer(1)))
  get_shapes <- function(n, ndims, shapes) {
    lapply(seq_len(n), function(i)
      shapes[((i - 1) * 8 + 1):((i - 1) * 8 + ndims[i])])
  }
  list(arg_shapes = get_shapes(r$n_args, r$arg_ndims, r$arg_shapes),
       aux_shapes = get_shapes(r$n_aux, r$aux_ndims, r$aux_shapes))
}

# ----------------------------------------------------------------- Executor

mx.executor.bind <- function(sym, arg_ids, grad_ids, reqs, aux_ids) {
  r <- .mxr.status(.C("mxr_exec_bind", as.integer(sym),
                      as.integer(length(arg_ids)), as.integer(arg_ids),
                      as.integer(grad_ids), as.integer(reqs),
                      as.integer(length(aux_ids)), as.integer(aux_ids),
                      id = integer(1), status = integer(1)))
  structure(r$id, class = "mxtpu.executor")
}

mx.executor.forward <- function(ex, is.train = FALSE) {
  invisible(.mxr.status(.C("mxr_exec_forward", as.integer(ex),
                           as.integer(is.train), status = integer(1))))
}

mx.executor.backward <- function(ex) {
  invisible(.mxr.status(.C("mxr_exec_backward", as.integer(ex),
                           status = integer(1))))
}

mx.executor.outputs <- function(ex) {
  r <- .mxr.status(.C("mxr_exec_outputs", as.integer(ex),
                      ids = integer(64), n = integer(1),
                      status = integer(1)))
  lapply(seq_len(r$n), function(i)
    structure(r$ids[i], class = "mxtpu.ndarray"))
}

# -------------------------------------------------------------- FeedForward
#
# mx.model.FeedForward.create / mx.model.save / mx.model.load moved to
# model.R (training now routes through optimizer.R's framework-resident
# updater and io.R's NDArrayIter; checkpoints are format-compatible with
# the Python layer). mx.model.predict stays here with the executor layer.

# forward-only prediction on a trained model (batch.size must divide N)
mx.model.predict <- function(model, X, batch.size = 32) {
  nd <- length(dim(X))
  n <- dim(X)[nd]
  Xflat <- array(X, dim = c(prod(dim(X)[-nd]), n))
  data_idx <- which(model$arg_names == "data")
  preds <- NULL
  for (start in seq(1, n - batch.size + 1, by = batch.size)) {
    idx <- start:(start + batch.size - 1)
    batch <- t(Xflat[, idx])
    .mxr.status(.C("mxr_nd_set", as.integer(model$args[data_idx]),
                   as.double(t(batch)), as.integer(length(batch)),
                   status = integer(1)))
    mx.executor.forward(model$executor, is.train = FALSE)
    outs <- mx.executor.outputs(model$executor)
    prob <- as.array.mxtpu.ndarray(outs[[1]])  # batch x classes
    for (o in outs) mx.nd.free(o)
    preds <- rbind(preds, prob)
  }
  preds  # N x classes
}
