# Computation-graph visualization for the R binding (reference capability:
# R-package/R/viz.graph.R — graph.viz over the symbol's JSON). The
# reference rendered through an R graph widget; here the renderer-neutral
# form is Graphviz DOT text, built from the SAME symbol JSON the
# save/load path uses (symbol.R mx.symbol.tojson), so the picture always
# reflects what the executor will actually run. Write the string to a
# .dot file and any graphviz install renders it; tests parse the DOT
# structurally.
#
# The JSON subset the symbol serializer emits (objects, arrays, strings,
# numbers, booleans) is parsed by the recursive-descent reader below —
# base R has no JSON parser and the package adds no dependencies.

.mxr.json.parse <- function(text) {
  env <- new.env()
  env$s <- text
  env$i <- 1L
  env$n <- nchar(text)
  peek <- function() substring(env$s, env$i, env$i)
  adv <- function() env$i <- env$i + 1L
  skip.ws <- function() {
    while (env$i <= env$n && peek() %in% c(" ", "\n", "\t", "\r")) adv()
  }
  read.value <- function() {
    skip.ws()
    c0 <- peek()
    if (c0 == "{") return(read.object())
    if (c0 == "[") return(read.array())
    if (c0 == "\"") return(read.string())
    read.literal()
  }
  read.object <- function() {
    adv()  # consume {
    out <- list()
    skip.ws()
    if (peek() == "}") { adv(); return(out) }
    repeat {
      skip.ws()
      key <- read.string()
      skip.ws()
      adv()  # consume :
      out[[key]] <- read.value()
      skip.ws()
      if (peek() == ",") { adv(); next }
      adv()  # consume }
      break
    }
    out
  }
  read.array <- function() {
    adv()  # consume [
    out <- list()
    skip.ws()
    if (peek() == "]") { adv(); return(out) }
    repeat {
      out[[length(out) + 1L]] <- read.value()
      skip.ws()
      if (peek() == ",") { adv(); next }
      adv()  # consume ]
      break
    }
    out
  }
  read.string <- function() {
    adv()  # consume opening quote
    start <- env$i
    buf <- character(0)
    while (peek() != "\"") {
      if (peek() == "\\") {  # keep escaped char verbatim (names/op strings)
        buf <- c(buf, substring(env$s, start, env$i - 1L))
        adv()
        start <- env$i
      }
      adv()
    }
    s <- paste0(paste(buf, collapse = ""),
                substring(env$s, start, env$i - 1L))
    adv()  # consume closing quote
    s
  }
  read.literal <- function() {
    start <- env$i
    while (env$i <= env$n &&
           grepl("[-+0-9.eEa-z]", peek())) adv()
    tok <- substring(env$s, start, env$i - 1L)
    if (tok == "true") return(TRUE)
    if (tok == "false") return(FALSE)
    if (tok == "null") return(NULL)
    as.numeric(tok)
  }
  read.value()
}

# op -> DOT node style, the reference's convention of coloring by role
# (data/weights plain, compute ops filled by family)
.mxr.viz.style <- function(op) {
  if (op == "null")
    return("shape=ellipse, style=solid")
  fill <- if (grepl("Convolution|FullyConnected", op)) "#8dd3c7"
          else if (grepl("Activation|relu|LeakyReLU", op)) "#fb8072"
          else if (grepl("Pooling", op)) "#80b1d3"
          else if (grepl("BatchNorm", op)) "#bebada"
          else if (grepl("Softmax|Output|Loss", op)) "#fdb462"
          else "#d9d9d9"
  sprintf("shape=box, style=filled, fillcolor=\"%s\"", fill)
}

# symbol (or its JSON string) -> Graphviz DOT text. Auxiliary parameter
# inputs (weights/bias/moving stats) are folded into their consumer's
# label rather than drawn, matching the reference's hide.weights=TRUE
# default that keeps real topology readable.
mx.viz.graph <- function(symbol, hide.weights = TRUE) {
  json <- if (is.character(symbol)) symbol else mx.symbol.tojson(symbol)
  g <- .mxr.json.parse(json)
  nodes <- g$nodes
  is.param <- vapply(seq_along(nodes), function(i) {
    nd <- nodes[[i]]
    nd$op == "null" && nd$name != "data" &&
      !mx.util.str.endswith(nd$name, "label")
  }, logical(1))
  lines <- c("digraph mxtpu {", "  rankdir=BT;")
  for (i in seq_along(nodes)) {
    nd <- nodes[[i]]
    if (hide.weights && is.param[i]) next
    label <- if (nd$op == "null") nd$name
             else sprintf("%s\\n%s", nd$op, nd$name)
    lines <- c(lines, sprintf("  n%d [label=\"%s\", %s];",
                              i - 1L, label, .mxr.viz.style(nd$op)))
  }
  for (i in seq_along(nodes)) {
    nd <- nodes[[i]]
    if (hide.weights && is.param[i]) next
    for (inp in nd$inputs) {
      src <- inp[[1]] + 1L
      if (hide.weights && is.param[src]) next
      lines <- c(lines, sprintf("  n%d -> n%d;", src - 1L, i - 1L))
    }
  }
  paste(c(lines, "}"), collapse = "\n")
}
