# Initializers for the R binding (reference capability:
# R-package/R/initializer.R — mx.init.uniform / mx.init.normal /
# mx.init.Xavier and the name-dispatch rules).
#
# An initializer is a function(name, shape) -> numeric vector of
# prod(shape) values (row-major shape, as mx.symbol.infer.shapes returns).
# Name dispatch matches the framework's Python layer
# (mxnet_tpu/initializer.py): *weight -> the random rule, *bias/*beta ->
# 0, *gamma -> 1, aux running-var -> 1, running-mean -> 0.

mx.init.uniform <- function(scale) {
  function(name, shape) runif(prod(shape), -scale, scale)
}

mx.init.normal <- function(sd) {
  function(name, shape) rnorm(prod(shape)) * sd
}

# Glorot (mxnet_tpu/initializer.py:104-129): fan_out = shape[1] (leading
# row-major dim), fan_in = prod of the rest.
mx.init.Xavier <- function(rnd_type = "uniform", factor_type = "avg",
                           magnitude = 3) {
  function(name, shape) {
    fan_out <- shape[1]
    fan_in <- if (length(shape) > 1) prod(shape[-1]) else shape[1]
    factor <- switch(factor_type,
                     avg = (fan_in + fan_out) / 2,
                     "in" = fan_in,
                     out = fan_out,
                     stop("bad factor_type ", factor_type))
    scale <- sqrt(magnitude / factor)
    if (rnd_type == "uniform") {
      runif(prod(shape), -scale, scale)
    } else if (rnd_type == "gaussian") {
      rnorm(prod(shape)) * scale
    } else {
      stop("bad rnd_type ", rnd_type)
    }
  }
}

# full name-dispatch init for one argument/aux state
mx.init.param <- function(initializer, name, shape) {
  nel <- prod(shape)
  if (grepl("gamma", name) || grepl("var$", name)) {
    rep(1, nel)
  } else if (grepl("weight", name)) {
    initializer(name, shape)
  } else {
    rep(0, nel)  # bias/beta/running-mean and everything else
  }
}
