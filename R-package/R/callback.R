# Training callbacks for the R binding (reference capability:
# R-package/R/callback.R — mx.callback.log.train.metric and
# mx.callback.save.checkpoint, invoked from the model.R train loop).
#
# Batch callbacks: function(env) with env$epoch, env$nbatch, env$metric
# (accumulator state + get). Epoch callbacks: function(epoch, model).

mx.callback.log.train.metric <- function(period = 50) {
  function(env) {
    if (env$nbatch %% period == 0) {
      m <- env$metric.get(env$metric.state)
      message(sprintf("Batch [%d] Train-%s=%f", env$nbatch,
                      m$name, m$value))
    }
    TRUE
  }
}

mx.callback.save.checkpoint <- function(prefix) {
  function(epoch, model) {
    mx.model.save(model, prefix, epoch)
    message(sprintf("Model checkpoint saved to %s-%04d.params",
                    prefix, epoch))
    TRUE
  }
}
