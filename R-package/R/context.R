# Device contexts for the R binding (reference capability:
# R-package/R/context.R — mx.cpu / mx.gpu / mx.ctx.default). The runtime's
# accelerator slot is the TPU, so mx.tpu() is the native device and
# mx.gpu() aliases it for script compatibility (same mapping as the C API:
# dev_type 2 -> tpu, capi_support.py _ctx).
#
# Contexts are descriptors consumed at ndarray/executor creation; with one
# XLA backend per process the descriptor mainly records intent (device
# type + id), which keeps reference training scripts portable.

mx.ctx.new <- function(device, device.id = 0L) {
  structure(list(device = device, device_id = as.integer(device.id)),
            class = "MXContext")
}

mx.cpu <- function(dev.id = 0L) mx.ctx.new("cpu", dev.id)

mx.tpu <- function(dev.id = 0L) mx.ctx.new("tpu", dev.id)

# accelerator alias: reference scripts say mx.gpu(); the runtime's
# accelerator is the TPU
mx.gpu <- function(dev.id = 0L) mx.ctx.new("tpu", dev.id)

is.mx.context <- function(x) inherits(x, "MXContext")

# package-default context; mx.ctx.default(new) sets, mx.ctx.default() gets
.mxr.ctx.env <- new.env()

mx.ctx.default <- function(new = NULL) {
  if (!is.null(new)) {
    stopifnot(is.mx.context(new))
    .mxr.ctx.env$default <- new
  }
  if (is.null(.mxr.ctx.env$default)) .mxr.ctx.env$default <- mx.tpu()
  .mxr.ctx.env$default
}

print.MXContext <- function(x, ...) {
  cat(sprintf("mx.ctx(%s:%d)\n", x$device, x$device_id))
}
