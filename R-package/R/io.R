# Data iterators for the R binding (reference capability:
# R-package/R/io.R — mx.io.NDArrayIter with reset/iter.next/value and the
# batch/pad protocol FeedForward training consumes).
#
# R-native batching over R arrays: each value() call hands the CURRENT
# batch to the caller as host data ready for mxr_nd_set. The heavy device
# pipeline (RecordIO + native decode workers) stays on the Python/C++ side;
# this iterator is the R-facing protocol adapter, like the reference's
# (whose C-side NDArrayIter was likewise a batching shim over host arrays).

# X: R array with the sample axis LAST (R convention, e.g. 28x28x1xN);
# y: length-N labels. batch.size must be <= N; the last partial batch is
# padded by wrapping around, with the pad count reported like the
# reference's iterator pad() (io.R round-batch semantics).
mx.io.NDArrayIter <- function(X, y, batch.size = 32, shuffle = FALSE) {
  nd <- length(dim(X))
  n <- dim(X)[nd]
  feat_dims <- if (nd > 1) dim(X)[-nd] else integer(0)
  Xflat <- array(X, dim = c(max(1, prod(feat_dims)), n))
  env <- new.env()
  env$order <- seq_len(n)
  env$cursor <- 0L

  reset <- function() {
    if (shuffle) env$order <- sample(n)
    env$cursor <- 0L
    invisible(NULL)
  }
  iter.next <- function() {
    if (env$cursor >= n) return(FALSE)
    env$cursor <- env$cursor + batch.size
    TRUE
  }
  value <- function() {
    start <- env$cursor - batch.size + 1L
    idx <- start:env$cursor
    pad <- sum(idx > n)
    idx[idx > n] <- idx[idx > n] - n  # wrap-around padding
    sel <- env$order[idx]
    # features-by-batch block: one column per sample, so as.double()
    # (column-major flatten) IS the row-major (batch, features...) order
    # mxr_nd_set expects — no transpose copies on the hot path
    list(data = Xflat[, sel, drop = FALSE], label = y[sel], pad = pad,
         data.shape = c(batch.size, rev(feat_dims)))
  }
  list(reset = reset, iter.next = iter.next, value = value,
       batch.size = batch.size, num.samples = n)
}
