# Optimizers for the R binding (reference capability:
# R-package/R/optimizer.R — mx.opt.sgd / mx.opt.create / mx.opt.get.updater).
#
# The update math runs INSIDE the framework via the C API's registered
# NDArray functions (.C("mxr_func_invoke") -> MXFuncInvoke -> XLA ops on
# runtime-resident arrays): weights, gradients, and momentum never cross
# into R doubles during training. The reference R layer used the same
# route (mx.nd arithmetic over its C API); earlier rounds of this package
# ran in-R SGD on copied vectors, which both diverged from the reference's
# architecture and paid two full host round-trips per parameter per batch.

# in-place registered-function call: fn(use_vars..., scalars...) -> mutate
.mxr.func <- function(fname, use_ids, scalars, mutate_id) {
  invisible(.mxr.status(.C("mxr_func_invoke", as.character(fname),
                           as.integer(length(use_ids)), as.integer(use_ids),
                           as.integer(length(scalars)), as.double(scalars),
                           1L, as.integer(mutate_id),
                           status = integer(1))))
}

mx.nd.mul.scalar <- function(src, s, out = src) {
  .mxr.func("_mul_scalar", src, s, out)
  out
}

mx.nd.plus <- function(a, b, out = a) {
  .mxr.func("_plus", c(a, b), numeric(0), out)
  out
}

mx.nd.minus <- function(a, b, out = a) {
  .mxr.func("_minus", c(a, b), numeric(0), out)
  out
}

mx.nd.copyto <- function(src, out) {
  .mxr.func("_copyto", src, numeric(0), out)
  out
}

# SGD with momentum. create.state/update closure protocol is the
# reference's optimizer contract (optimizer.R:10-30); update mutates
# weight/state handles in place and returns them.
mx.opt.sgd <- function(learning.rate, momentum = 0, wd = 0,
                       rescale.grad = 1) {
  lr <- learning.rate
  create.state <- function(index, weight) {
    if (momentum == 0) return(NULL)
    mx.nd.zeros.like(weight)
  }
  update <- function(index, weight, grad, state) {
    # scratch holds lr*(rescale*grad + wd*weight); allocated once per
    # parameter and cached on the closure environment by index
    scratch <- .sgd.scratch(index, weight)
    mx.nd.mul.scalar(grad, rescale.grad, out = scratch)
    if (wd != 0) {
      scratch2 <- .sgd.scratch2(index, weight)
      mx.nd.mul.scalar(weight, wd, out = scratch2)
      mx.nd.plus(scratch, scratch2)
    }
    mx.nd.mul.scalar(scratch, lr)
    if (is.null(state)) {
      mx.nd.minus(weight, scratch)
    } else {
      mx.nd.mul.scalar(state, momentum)
      mx.nd.minus(state, scratch)
      mx.nd.plus(weight, state)
    }
    list(weight = weight, state = state)
  }
  scratch.env <- new.env()
  .sgd.scratch <- function(index, weight) {
    key <- paste0("s", index)
    if (is.null(scratch.env[[key]]))
      scratch.env[[key]] <- mx.nd.zeros.like(weight)
    scratch.env[[key]]
  }
  .sgd.scratch2 <- function(index, weight) {
    key <- paste0("t", index)
    if (is.null(scratch.env[[key]]))
      scratch.env[[key]] <- mx.nd.zeros.like(weight)
    scratch.env[[key]]
  }
  environment(update) <- environment()
  list(create.state = create.state, update = update)
}

mx.nd.zeros.like <- function(h) {
  shp <- mx.nd.shape(h)
  r <- .mxr.status(.C("mxr_nd_create", as.integer(shp),
                      as.integer(length(shp)), id = integer(1),
                      status = integer(1)))
  # runtime-side fill (_set_value) — no prod(shape) host doubles crossing
  # the .C boundary just to zero device memory
  .mxr.func("_set_value", integer(0), 0, r$id)
  structure(r$id, class = "mxtpu.ndarray", dims = shp)
}

mx.opt.create <- function(name, ...) {
  if (name == "sgd") return(mx.opt.sgd(...))
  stop("Unknown optimizer ", name)
}

# updater closure over a weight list: tracks per-index optimizer state
# (reference: optimizer.R:50-70 mx.opt.get.updater)
mx.opt.get.updater <- function(optimizer, weights) {
  n <- length(weights)
  state.list <- lapply(seq_len(n), function(i) {
    if (is.null(weights[[i]])) return(NULL)
    optimizer$create.state(i, weights[[i]])
  })
  update <- optimizer$update
  updater <- function(weight.list, grad.list) {
    for (i in seq_len(n)) {
      if (is.null(grad.list[[i]])) next
      res <- update(i, weight.list[[i]], grad.list[[i]], state.list[[i]])
      state.list[[i]] <<- res$state
    }
    weight.list
  }
  updater
}
