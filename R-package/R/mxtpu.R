# R user layer over the .C shim (reference capability: R-package/R/ — here
# the deployment slice: load an exported .mxtpu bundle and run forward).
#
# Example:
#   pred <- mx.pred.create("model.mxtpu")
#   mx.pred.set.input(pred, "data", batch)      # array, R dim() order
#   mx.pred.forward(pred)
#   probs <- mx.pred.get.output(pred, 1)
#   mx.pred.free(pred)

mx.pred.create <- function(bundle_path) {
  r <- .C("mxtpu_r_create", as.character(bundle_path),
          id = integer(1), status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  structure(r$id, class = "mxtpu.predictor")
}

.mx.last.error <- function() {
  buf <- paste(rep(" ", 512), collapse = "")
  r <- .C("mxtpu_r_last_error", msg = as.character(buf), as.integer(512))
  r$msg
}

mx.pred.set.input <- function(pred, name, value) {
  # R arrays are column-major; the runtime wants row-major (C) order with
  # the LOGICAL dims, so reorder the data (aperm) but send dims as-is.
  dims <- dim(value)
  if (is.null(dims)) dims <- length(value)
  value <- aperm(array(value, dims), rev(seq_along(dims)))
  r <- .C("mxtpu_r_set_input", as.integer(pred), as.character(name),
          as.double(value), as.integer(dims), as.integer(length(dims)),
          status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  invisible(NULL)
}

mx.pred.forward <- function(pred) {
  r <- .C("mxtpu_r_forward", as.integer(pred), status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  invisible(NULL)
}

mx.pred.num.outputs <- function(pred) {
  .C("mxtpu_r_num_outputs", as.integer(pred), n = integer(1))$n
}

mx.pred.get.output <- function(pred, index = 1) {
  s <- .C("mxtpu_r_output_shape", as.integer(pred), as.integer(index - 1),
          ndim = integer(1), shape = integer(8))
  if (s$ndim < 0) stop("mxtpu: bad output index")
  shape <- s$shape[seq_len(s$ndim)]
  size <- prod(shape)
  r <- .C("mxtpu_r_get_output", as.integer(pred), as.integer(index - 1),
          out = double(size), as.integer(size), status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  # back to column-major
  aperm(array(r$out, rev(shape)), rev(seq_along(shape)))
}

mx.pred.free <- function(pred) {
  .C("mxtpu_r_free", as.integer(pred))
  invisible(NULL)
}

# ---- NDArray construction layer -------------------------------------------
# Reference capability: R-package/R/ndarray.R (mx.nd.array / mx.nd.zeros /
# mx.nd.ones and shape accessors). The runtime here is the native predictor
# (host arrays), so mxtpu.ndarray is a thin typed wrapper holding data in
# the framework's row-major (C) order, constructed once instead of
# transposing on every predictor call.

mx.nd.array <- function(value, dims = NULL) {
  if (is.null(dims)) dims <- if (is.null(dim(value))) length(value) else dim(value)
  value <- array(as.double(value), dims)
  # to row-major once (reference R binding transposed at the C boundary)
  data <- aperm(value, rev(seq_along(dims)))
  structure(list(data = as.double(data), shape = as.integer(dims)),
            class = "mxtpu.ndarray")
}

mx.nd.zeros <- function(shape) mx.nd.array(array(0, shape), shape)

mx.nd.ones <- function(shape) mx.nd.array(array(1, shape), shape)

mx.nd.shape <- function(nd) nd$shape

# back to a plain column-major R array
as.array.mxtpu.ndarray <- function(x, ...) {
  aperm(array(x$data, rev(x$shape)), rev(seq_along(x$shape)))
}

print.mxtpu.ndarray <- function(x, ...) {
  cat("mxtpu.ndarray", paste(x$shape, collapse = "x"), "\n")
  print(as.array(x))
}

.mx.pred.set.input.nd <- function(pred, name, nd) {
  # data already row-major in nd$shape order: skip the aperm, send the
  # logical shape
  r <- .C("mxtpu_r_set_input", as.integer(pred), as.character(name),
          nd$data, as.integer(nd$shape), as.integer(length(nd$shape)),
          status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  invisible(NULL)
}

# ---- batched prediction ----------------------------------------------------
# Reference capability: R-package/R/model.R predict.MXFeedForwardModel —
# iterate a dataset in batches through the bound executor. Here: slice the
# leading dimension, pad the final partial batch (round_batch semantics),
# run the native predictor per batch, and stack the de-padded outputs.

mx.pred.predict <- function(pred, data, input.name = "data",
                            batch.size = 32, output.index = 1) {
  nd <- if (inherits(data, "mxtpu.ndarray")) data else mx.nd.array(data)
  n <- nd$shape[1]
  sample.shape <- nd$shape[-1]
  sample.size <- prod(sample.shape)
  batch.size <- min(batch.size, n)
  out <- NULL  # preallocated after the first batch reveals the output dims
  i <- 1
  while (i <= n) {
    take <- min(batch.size, n - i + 1)
    idx <- ((i - 1) * sample.size + 1):((i + take - 1) * sample.size)
    chunk <- nd$data[idx]
    if (take < batch.size) {  # pad the tail batch, drop the pad after
      chunk <- c(chunk, double((batch.size - take) * sample.size))
    }
    bnd <- structure(list(data = chunk,
                          shape = as.integer(c(batch.size, sample.shape))),
                     class = "mxtpu.ndarray")
    .mx.pred.set.input.nd(pred, input.name, bnd)
    mx.pred.forward(pred)
    res <- mx.pred.get.output(pred, output.index)
    rdim <- dim(res)
    if (is.null(out)) out <- array(0, c(n, rdim[-1]))
    rows <- rep(list(quote(expr = )), length(rdim))
    rows[[1]] <- (i - 1) + seq_len(take)
    keep <- rep(list(quote(expr = )), length(rdim))
    keep[[1]] <- seq_len(take)
    res <- do.call(`[`, c(list(res), keep, list(drop = FALSE)))
    out <- do.call(`[<-`, c(list(out), rows, list(value = res)))
    i <- i + take
  }
  out
}
