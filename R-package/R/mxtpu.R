# R user layer over the .C shim (reference capability: R-package/R/ — here
# the deployment slice: load an exported .mxtpu bundle and run forward).
#
# Example:
#   pred <- mx.pred.create("model.mxtpu")
#   mx.pred.set.input(pred, "data", batch)      # array, R dim() order
#   mx.pred.forward(pred)
#   probs <- mx.pred.get.output(pred, 1)
#   mx.pred.free(pred)

mx.pred.create <- function(bundle_path) {
  r <- .C("mxtpu_r_create", as.character(bundle_path),
          id = integer(1), status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  structure(r$id, class = "mxtpu.predictor")
}

.mx.last.error <- function() {
  buf <- paste(rep(" ", 512), collapse = "")
  r <- .C("mxtpu_r_last_error", msg = as.character(buf), as.integer(512))
  r$msg
}

mx.pred.set.input <- function(pred, name, value) {
  # R arrays are column-major; the runtime wants row-major (C) order, so
  # transpose by reversing dims, like the reference R binding did.
  dims <- dim(value)
  if (is.null(dims)) dims <- length(value)
  value <- aperm(array(value, dims), rev(seq_along(dims)))
  r <- .C("mxtpu_r_set_input", as.integer(pred), as.character(name),
          as.double(value), as.integer(rev(dims)), as.integer(length(dims)),
          status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  invisible(NULL)
}

mx.pred.forward <- function(pred) {
  r <- .C("mxtpu_r_forward", as.integer(pred), status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  invisible(NULL)
}

mx.pred.num.outputs <- function(pred) {
  .C("mxtpu_r_num_outputs", as.integer(pred), n = integer(1))$n
}

mx.pred.get.output <- function(pred, index = 1) {
  s <- .C("mxtpu_r_output_shape", as.integer(pred), as.integer(index - 1),
          ndim = integer(1), shape = integer(8))
  if (s$ndim < 0) stop("mxtpu: bad output index")
  shape <- s$shape[seq_len(s$ndim)]
  size <- prod(shape)
  r <- .C("mxtpu_r_get_output", as.integer(pred), as.integer(index - 1),
          out = double(size), as.integer(size), status = integer(1))
  if (r$status != 0) stop("mxtpu: ", .mx.last.error())
  # back to column-major
  aperm(array(r$out, rev(shape)), rev(seq_along(shape)))
}

mx.pred.free <- function(pred) {
  .C("mxtpu_r_free", as.integer(pred))
  invisible(NULL)
}
