# Executor layer of the R binding (reference capability:
# R-package/R/executor.R). Split out of mxtpu_train.R to mirror the
# reference's module layout; all files source() into one namespace —
# see demo/lenet_train.R for the canonical load order.

# ----------------------------------------------------------------- Executor

mx.executor.bind <- function(sym, arg_ids, grad_ids, reqs, aux_ids) {
  r <- .mxr.status(.C("mxr_exec_bind", as.integer(sym),
                      as.integer(length(arg_ids)), as.integer(arg_ids),
                      as.integer(grad_ids), as.integer(reqs),
                      as.integer(length(aux_ids)), as.integer(aux_ids),
                      id = integer(1), status = integer(1)))
  structure(r$id, class = "mxtpu.executor")
}

mx.executor.forward <- function(ex, is.train = FALSE) {
  invisible(.mxr.status(.C("mxr_exec_forward", as.integer(ex),
                           as.integer(is.train), status = integer(1))))
}

mx.executor.backward <- function(ex) {
  invisible(.mxr.status(.C("mxr_exec_backward", as.integer(ex),
                           status = integer(1))))
}

mx.executor.outputs <- function(ex) {
  r <- .mxr.status(.C("mxr_exec_outputs", as.integer(ex),
                      ids = integer(64), n = integer(1),
                      status = integer(1)))
  lapply(seq_len(r$n), function(i)
    structure(r$ids[i], class = "mxtpu.ndarray"))
}
