# NDArray layer of the R binding (reference capability:
# R-package/R/ndarray.R). Split out of mxtpu_train.R to mirror the
# reference's module layout; all files source() into one namespace —
# see demo/lenet_train.R for the canonical load order.

# ------------------------------------------------------------------ NDArray

mx.nd.array <- function(data) {
  # R arrays are column-major; the runtime is row-major. aperm the data,
  # keep the LOGICAL dims (same convention as mxtpu.R's predictor layer).
  dims <- dim(data)
  if (is.null(dims)) dims <- length(data)
  r <- .mxr.status(.C("mxr_nd_create", as.integer(dims),
                      as.integer(length(dims)), id = integer(1),
                      status = integer(1)))
  h <- structure(r$id, class = "mxtpu.ndarray", dims = dims)
  rowmajor <- aperm(array(data, dims), rev(seq_along(dims)))
  .mxr.status(.C("mxr_nd_set", as.integer(h), as.double(rowmajor),
                 as.integer(length(rowmajor)), status = integer(1)))
  h
}

mx.nd.zeros <- function(shape) mx.nd.array(array(0, dim = shape))

mx.nd.shape <- function(h) {
  r <- .mxr.status(.C("mxr_nd_shape", as.integer(h), ndim = integer(1),
                      shape = integer(8), status = integer(1)))
  r$shape[seq_len(r$ndim)]
}

as.array.mxtpu.ndarray <- function(x, ...) {
  shape <- mx.nd.shape(x)          # row-major dims
  n <- prod(shape)
  r <- .mxr.status(.C("mxr_nd_get", as.integer(x), data = double(n),
                      as.integer(n), status = integer(1)))
  # back to column-major R array with the logical dims
  aperm(array(r$data, dim = rev(shape)), rev(seq_along(shape)))
}

mx.nd.set <- function(h, data) {
  dims <- dim(data)
  if (is.null(dims)) dims <- length(data)
  rowmajor <- aperm(array(data, dims), rev(seq_along(dims)))
  invisible(.mxr.status(.C("mxr_nd_set", as.integer(h), as.double(rowmajor),
                           as.integer(length(rowmajor)),
                           status = integer(1))))
}

mx.nd.free <- function(h) {
  invisible(.C("mxr_nd_free", as.integer(h), status = integer(1)))
}
