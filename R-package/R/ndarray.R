# NDArray layer of the R binding (reference capability:
# R-package/R/ndarray.R). Split out of mxtpu_train.R to mirror the
# reference's module layout; all files source() into one namespace —
# see demo/lenet_train.R for the canonical load order.

# ------------------------------------------------------------------ NDArray

mx.nd.array <- function(data) {
  # R arrays are column-major; the runtime is row-major. aperm the data,
  # keep the LOGICAL dims (same convention as mxtpu.R's predictor layer).
  dims <- dim(data)
  if (is.null(dims)) dims <- length(data)
  r <- .mxr.status(.C("mxr_nd_create", as.integer(dims),
                      as.integer(length(dims)), id = integer(1),
                      status = integer(1)))
  h <- structure(r$id, class = "mxtpu.ndarray", dims = dims)
  rowmajor <- aperm(array(data, dims), rev(seq_along(dims)))
  .mxr.status(.C("mxr_nd_set", as.integer(h), as.double(rowmajor),
                 as.integer(length(rowmajor)), status = integer(1)))
  h
}

mx.nd.zeros <- function(shape) mx.nd.array(array(0, dim = shape))

mx.nd.shape <- function(h) {
  r <- .mxr.status(.C("mxr_nd_shape", as.integer(h), ndim = integer(1),
                      shape = integer(8), status = integer(1)))
  r$shape[seq_len(r$ndim)]
}

as.array.mxtpu.ndarray <- function(x, ...) {
  shape <- mx.nd.shape(x)          # row-major dims
  n <- prod(shape)
  r <- .mxr.status(.C("mxr_nd_get", as.integer(x), data = double(n),
                      as.integer(n), status = integer(1)))
  # back to column-major R array with the logical dims
  aperm(array(r$data, dim = rev(shape)), rev(seq_along(shape)))
}

mx.nd.set <- function(h, data) {
  dims <- dim(data)
  if (is.null(dims)) dims <- length(data)
  rowmajor <- aperm(array(data, dims), rev(seq_along(dims)))
  invisible(.mxr.status(.C("mxr_nd_set", as.integer(h), as.double(rowmajor),
                           as.integer(length(rowmajor)),
                           status = integer(1))))
}

mx.nd.free <- function(h) {
  invisible(.C("mxr_nd_free", as.integer(h), status = integer(1)))
}

# ---------------------------------------------------- ndarray math surface
# Reference capability: R-package/R/ndarray.R's arithmetic layer (Ops
# dispatch onto the registered NDArray functions). Everything below runs
# inside the runtime via mxr_func_invoke (MXFuncInvoke -> XLA); R holds
# only integer handles. Non-mutating: each op allocates its result
# ndarray, so R expressions compose like plain arrays (`(a + b) / c`).

# fresh runtime ndarray with the given dims (runtime dims == logical R
# dims, the mx.nd.array convention) — no host fill and no zeroing; callers
# overwrite it via a registered function
.mxr.nd.alloc <- function(shape) {
  r <- .mxr.status(.C("mxr_nd_create", as.integer(shape),
                      as.integer(length(shape)), id = integer(1),
                      status = integer(1)))
  structure(r$id, class = "mxtpu.ndarray", dims = as.integer(shape))
}

.mxr.nd.binop <- function(fname, a, b) {
  out <- .mxr.nd.alloc(mx.nd.shape(a))
  .mxr.func(fname, c(a, b), numeric(0), out)
  out
}

.mxr.nd.scalar.op <- function(fname, a, s) {
  out <- .mxr.nd.alloc(mx.nd.shape(a))
  .mxr.func(fname, a, s, out)
  out
}

# +, -, *, / on mxtpu.ndarray, mixed with R numerics: the scalar side maps
# onto the _*_scalar registered variants (including the reversed-operand
# _rminus/_rdiv forms, reference ndarray.cc's scalar family).
Ops.mxtpu.ndarray <- function(e1, e2) {
  op <- .Generic
  if (!op %in% c("+", "-", "*", "/"))
    stop("mxtpu.ndarray does not support ", op)
  if (missing(e2)) {  # unary +x / -x
    if (op == "+") return(e1)
    return(.mxr.nd.scalar.op("_mul_scalar", e1, -1))
  }
  a.nd <- inherits(e1, "mxtpu.ndarray")
  b.nd <- inherits(e2, "mxtpu.ndarray")
  if (a.nd && b.nd) {
    fname <- c(`+` = "_plus", `-` = "_minus",
               `*` = "_mul", `/` = "_div")[[op]]
    return(.mxr.nd.binop(fname, e1, e2))
  }
  if (a.nd) {
    fname <- c(`+` = "_plus_scalar", `-` = "_minus_scalar",
               `*` = "_mul_scalar", `/` = "_div_scalar")[[op]]
    return(.mxr.nd.scalar.op(fname, e1, as.double(e2)))
  }
  # scalar op ndarray: + and * commute; - and / use the reversed forms
  fname <- c(`+` = "_plus_scalar", `-` = "_rminus_scalar",
             `*` = "_mul_scalar", `/` = "_rdiv_scalar")[[op]]
  .mxr.nd.scalar.op(fname, e2, as.double(e1))
}

# The shape-preserving math surface (mx.nd.square/sqrt/exp/log/clip and
# the scalar forms) lives in mxtpu_generated.R: the generator emits those
# wrappers with an optional `out` that allocates via .mxr.nd.alloc. Only
# functions whose OUTPUT shape differs from the first operand's are
# hand-authored here (the generator can't know per-op shape rules).

# L2 norm reduces to one element
mx.nd.norm <- function(a, out = NULL) {
  if (is.null(out)) out <- .mxr.nd.alloc(1L)
  .mxr.func("norm", a, numeric(0), out)
  out
}

# matrix product of 2-d ndarrays: out dims follow (m,k)x(k,n)
mx.nd.dot <- function(a, b, out = NULL) {
  sa <- mx.nd.shape(a)
  sb <- mx.nd.shape(b)
  stopifnot(length(sa) == 2, length(sb) == 2, sa[2] == sb[1])
  if (is.null(out)) out <- .mxr.nd.alloc(c(sa[1], sb[2]))
  .mxr.func("dot", c(a, b), numeric(0), out)
  out
}

# ------------------------------------------------- ndarray save/load (user)
# Container-format parity with the Python/C sides (mxr_nd_save/load wrap
# the same writer MXNDArraySave uses), so R-written files load from
# Python's nd.load and vice versa. `nds` is a NAMED list of handles.
mx.nd.save <- function(nds, fname) {
  stopifnot(length(names(nds)) == length(nds))
  invisible(.mxr.status(.C("mxr_nd_save", as.character(fname),
                           as.integer(length(nds)),
                           as.integer(unlist(nds)),
                           as.character(names(nds)),
                           status = integer(1))))
}

mx.nd.load <- function(fname, max_n = 1024L, name_cap = 65536L) {
  buf <- paste(rep(" ", name_cap), collapse = "")
  r <- .mxr.status(.C("mxr_nd_load", as.character(fname),
                      as.integer(max_n), n = integer(1),
                      ids = integer(max_n), names = as.character(buf),
                      as.integer(name_cap), status = integer(1)))
  names <- strsplit(r$names, "\n")[[1]]
  out <- list()
  for (i in seq_len(r$n)) {
    out[[names[i]]] <- structure(r$ids[i], class = "mxtpu.ndarray")
  }
  out
}
