import faulthandler, sys, time
sys.path.insert(0, "/root/repo")
faulthandler.dump_traceback_later(150, repeat=True, exit=False)
sys.argv = ["bench.py", "--mode", "io", "--epochs", "2", "--num-images", "512"]
import bench
t0 = time.time()
bench.main()
print("elapsed", time.time() - t0)
