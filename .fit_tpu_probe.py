import sys, time, faulthandler
sys.path.insert(0, "/root/repo")
faulthandler.dump_traceback_later(150, repeat=True, exit=False)
import numpy as np, jax.numpy as jnp
import mxnet_tpu as mx
from mxnet_tpu.models import resnet50

n, b = 64, 32
X = (np.random.rand(n, 224, 224, 3) * 255).astype(np.uint8)
y = np.random.randint(0, 1000, n).astype(np.float32)
model = mx.model.FeedForward(resnet50(num_classes=1000, layout="NHWC"),
    ctx=mx.tpu(), num_epoch=2, learning_rate=0.01, momentum=0.9,
    initializer=mx.init.Xavier(), compute_dtype=jnp.bfloat16)
marks = [time.time()]
def cb(*a): marks.append(time.time()); print("epoch end", marks[-1]-marks[0], flush=True)
model.fit(X, y, batch_size=b, epoch_end_callback=cb)
print("done", flush=True)
