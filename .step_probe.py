import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt_mod, metric as metric_mod
from mxnet_tpu.models import resnet50
from mxnet_tpu import random as random_mod

b = 256
net = resnet50(num_classes=1000, layout="NHWC")
model = mx.model.FeedForward(net, ctx=mx.tpu(), num_epoch=1,
    learning_rate=0.01, momentum=0.9, initializer=mx.init.Xavier(),
    compute_dtype=jnp.bfloat16)
input_shapes = {"data": (b,224,224,3), "softmax_label": (b,)}
param_names, aux_names = model._init_params(input_shapes)
optimizer = opt_mod.create("sgd", rescale_grad=1.0/b, arg_names=param_names,
                           learning_rate=0.01, momentum=0.9)
em = metric_mod.create("accuracy")
step = model._build_train_step(["data"], ["softmax_label"], optimizer, None,
                               metric_update=em.device_update)
params = {k: jnp.asarray(model.arg_params[k].asnumpy()) for k in param_names}
aux = {k: jnp.asarray(model.aux_params[k].asnumpy()) for k in aux_names}
opt_state = optimizer.init_state_tree(params)
mstate = em.device_init()
X = (np.random.rand(b,224,224,3)*255).astype(np.uint8)
y = np.random.randint(0,1000,b).astype(np.float32)

def mark(s, t0): print(f"{s}: {time.time()-t0:.1f}s", flush=True)
t0=time.time()
batch = {"data": jax.device_put(X), "softmax_label": jax.device_put(y)}
jax.block_until_ready(batch["data"]); mark("device_put batch", t0)
t0=time.time()
rng = random_mod.next_key()
params, opt_state, aux, outs, mstate = step(params, opt_state, aux, batch, rng, 0.01, mstate)
mark("step dispatch (compile)", t0)
t0=time.time(); print("mstate", jax.device_get(mstate)); mark("readback after step1", t0)
for i in range(3):
    t0=time.time()
    rng = random_mod.next_key()
    params, opt_state, aux, outs, mstate = step(params, opt_state, aux, batch, rng, 0.01, mstate)
    mark(f"step{i+2} dispatch", t0)
    t0=time.time(); jax.device_get(mstate); mark(f"readback {i+2}", t0)
