# coding: utf-8
"""Lightweight standalone prediction API (reference capability:
predict/python/mxnet_predict.py — a ctypes-only Predictor for deployment
hosts that must not install the full package).

This file has ZERO dependency on the mxnet_tpu package: it speaks the C
predict ABI of libmxtpu_predict.so directly (the dependency-free native
predictor over exported ``.mxtpu`` bundles — no Python runtime, no JAX on
the serving path; build: ``make -C mxnet_tpu/native`` — it produces
``libmxtpu_predict.so`` alongside the data-pipeline library). Copy this
one file plus the .so next to your bundle and serve.

    from mxtpu_predict import Predictor
    p = Predictor("model.mxtpu")
    probs = p.predict({"data": batch})[0]
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

__all__ = ["Predictor", "find_lib_path"]


def find_lib_path():
    """Locate libmxtpu_predict.so: beside this file, cwd, or the in-repo
    build dir (reference: _find_lib_path candidate-list discipline)."""
    here = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    candidates = [
        os.path.join(here, "libmxtpu_predict.so"),
        os.path.join(os.getcwd(), "libmxtpu_predict.so"),
        os.path.join(here, "..", "..", "mxnet_tpu", "native",
                     "libmxtpu_predict.so"),
    ]
    paths = [p for p in candidates if os.path.isfile(p)]
    if not paths:
        raise RuntimeError(
            "Cannot find libmxtpu_predict.so.\nList of candidates:\n"
            + "\n".join(candidates)
            + "\nBuild it with: make -C mxnet_tpu/native "
            + "libmxtpu_predict.so")
    return paths


def _load_lib():
    lib = ctypes.CDLL(find_lib_path()[0])
    lib.mxtpu_pred_create.restype = ctypes.c_void_p
    lib.mxtpu_pred_create.argtypes = [ctypes.c_char_p]
    lib.mxtpu_pred_last_error.restype = ctypes.c_char_p
    lib.mxtpu_pred_set_input.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int]
    lib.mxtpu_pred_forward.argtypes = [ctypes.c_void_p]
    lib.mxtpu_pred_num_outputs.argtypes = [ctypes.c_void_p]
    lib.mxtpu_pred_output_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mxtpu_pred_output_shape.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
    lib.mxtpu_pred_get_output.restype = ctypes.c_int64
    lib.mxtpu_pred_get_output.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64]
    lib.mxtpu_pred_free.argtypes = [ctypes.c_void_p]
    return lib


_LIB = None


class Predictor:
    """Forward-only predictor over an exported ``.mxtpu`` bundle
    (``mxnet_tpu.Predictor.export`` writes them; reference analog:
    Predictor over MXPredCreate in predict/python/mxnet_predict.py)."""

    def __init__(self, bundle_path):
        global _LIB
        if _LIB is None:
            _LIB = _load_lib()
        self._lib = _LIB
        self._h = self._lib.mxtpu_pred_create(
            str(bundle_path).encode("utf-8"))
        if not self._h:
            raise RuntimeError(
                "load failed: "
                + self._lib.mxtpu_pred_last_error().decode())

    def _check(self, rc):
        if rc < 0:
            raise RuntimeError(self._lib.mxtpu_pred_last_error().decode())
        return rc

    def forward(self, **inputs):
        """Set named inputs (numpy arrays) and run one forward pass."""
        for name, arr in inputs.items():
            arr = np.ascontiguousarray(arr, dtype=np.float32)
            shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
            self._check(self._lib.mxtpu_pred_set_input(
                self._h, name.encode("utf-8"),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                shape, arr.ndim))
        self._check(self._lib.mxtpu_pred_forward(self._h))

    def get_output(self, index):
        ndim = self._check(self._lib.mxtpu_pred_output_ndim(self._h, index))
        shape = (ctypes.c_int64 * max(1, ndim))()
        self._check(self._lib.mxtpu_pred_output_shape(self._h, index, shape))
        out_shape = tuple(shape[i] for i in range(ndim))
        n = int(np.prod(out_shape)) if out_shape else 1
        buf = np.empty(n, np.float32)
        got = self._check(self._lib.mxtpu_pred_get_output(
            self._h, index,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(n)))
        return buf[:got].reshape(out_shape)

    def predict(self, inputs):
        """One-call convenience: dict of inputs -> list of output arrays."""
        self.forward(**inputs)
        n = self._lib.mxtpu_pred_num_outputs(self._h)
        return [self.get_output(i) for i in range(n)]

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.mxtpu_pred_free(h)
