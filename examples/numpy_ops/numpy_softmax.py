"""Custom numpy operator as a network head (rewrite of the reference
example/numpy-ops/numpy_softmax.py: a softmax loss written entirely in
numpy via the NumpyOp bridge, trained end-to-end).

The op executes on the host through jax.pure_callback inside the jitted
graph; the backward is the user's numpy code too (need_top_grad=False
because it is a loss head producing its own gradient).

Run: python examples/numpy_ops/numpy_softmax.py
"""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.operator import NumpyOp


class NumpySoftmax(NumpyOp):
    """Softmax output layer in pure numpy (reference semantics)."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["prob"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]]

    def forward(self, in_data, out_data):
        x = in_data[0]
        y = out_data[0]
        y[:] = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)

    def backward(self, out_grad, in_data, out_data, in_grad):
        label = in_data[1].astype(np.int64)
        y = out_data[0]
        dx = in_grad[0]
        dx[:] = y
        dx[np.arange(label.shape[0]), label] -= 1.0


def main():
    rng = np.random.RandomState(0)
    n, dim, classes = 600, 20, 4
    centers = rng.randn(classes, dim) * 2.0
    y = rng.randint(0, classes, n).astype(np.float32)
    X = (centers[y.astype(int)] + 0.5 * rng.randn(n, dim)).astype(np.float32)

    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=64)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=classes)
    net = NumpySoftmax()(data=net, name="softmax")

    model = mx.FeedForward(net, ctx=mx.cpu(), num_epoch=10,
                           learning_rate=0.2, momentum=0.9,
                           initializer=mx.init.Xavier())
    model.fit(X, y, batch_size=50)
    preds = model.predict(X, batch_size=50)
    acc = (preds.argmax(axis=1) == y).mean()
    print(f"train accuracy with numpy softmax head: {acc:.3f}")
    assert acc > 0.9, acc


if __name__ == "__main__":
    main()
