#!/usr/bin/env python
"""Asynchronous distributed MLP training (reference:
tests/python/multi-node/dist_async_mlp.py — workers train against the
parameter server at their own pace, no BSP rounds, final accuracy asserted).

Run under the launcher:
    python tools/launch.py -n 2 python examples/distributed/dist_async_mlp.py

fit(kvstore='dist_async') runs update-on-kvstore semantics: the optimizer
executes on the parameter host (rank 0 hosts it); every batch each worker
pushes its gradients (applied on arrival — unbounded staleness) and pulls
the current weights. The mesh stays process-local: there is no cross-worker
collective anywhere in the step.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx


def make_dataset(n=1024, dim=16, seed=42):
    rng = np.random.RandomState(seed)
    half = n // 2
    X = np.concatenate([rng.randn(half, dim) + 1.5,
                        rng.randn(half, dim) - 1.5]).astype(np.float32)
    y = np.concatenate([np.zeros(half), np.ones(half)]).astype(np.float32)
    perm = rng.permutation(n)
    return X[perm], y[perm]


def main():
    kv = mx.kv.create("dist_async")
    rank, nworker = kv.rank, kv.num_workers
    X, y = make_dataset()
    Xs, ys = X[rank::nworker], y[rank::nworker]

    net = mx.symbol.Variable("data")
    net = mx.symbol.FullyConnected(data=net, num_hidden=32, name="fc1")
    net = mx.symbol.Activation(data=net, act_type="relu", name="relu1")
    net = mx.symbol.FullyConnected(data=net, num_hidden=2, name="fc2")
    net = mx.symbol.SoftmaxOutput(data=net, name="softmax")

    model = mx.model.FeedForward(
        symbol=net, num_epoch=5, learning_rate=0.1, momentum=0.9,
        initializer=mx.init.Xavier())
    model.fit(Xs, ys, batch_size=32, kvstore=kv)

    acc = model.score(X, y=y)
    print(f"worker {rank}/{nworker}: dist_async_mlp accuracy = {acc:.4f}")
    assert acc > 0.95, f"worker {rank}: accuracy too low: {acc}"
    kv.barrier()


if __name__ == "__main__":
    main()
