#!/usr/bin/env python
"""dist_async semantics test (reference: tests/python/multi-node's
dist_async tier; server behavior kvstore_dist_server.h:194-202).

What distinguishes async from BSP dist_sync: a worker's push applies
immediately and its pull observes its own updates WITHOUT any other worker
pushing — under dist_sync the push would block until all workers arrive.

Run under the launcher:  python tools/launch.py -n 2 python <this file>
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx

SHAPE = (4,)
KEY = 11


def main():
    kv = mx.kv.create("dist_async")
    rank, nworker = kv.rank, kv.num_workers
    assert kv.type == "dist_async"
    kv.init(KEY, mx.nd.zeros(SHAPE))
    kv.set_optimizer(mx.optimizer.create("test"))  # w += g on the host

    if rank == 0:
        # Staleness: three pushes and a pull while the other workers are
        # idle at the barrier. Under BSP this would deadlock waiting for
        # worker 1's pushes; update-on-arrival must apply each immediately.
        for _ in range(3):
            kv.push(KEY, [mx.nd.ones(SHAPE)])
        out = mx.nd.empty(SHAPE)
        kv.pull(KEY, out=out)
        np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 3.0))
    kv.barrier()
    # after the barrier every worker observes rank 0's async updates
    out = mx.nd.empty(SHAPE)
    kv.pull(KEY, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 3.0))
    kv.barrier()  # all pulls above land before anyone's next push
    # now every worker pushes once; total becomes 3 + nworker regardless of
    # arrival order (sum is order-independent; no BSP rounds involved)
    kv.push(KEY, [mx.nd.ones(SHAPE)])
    kv.barrier()
    kv.pull(KEY, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 3.0 + nworker))
    print(f"worker {rank}/{nworker}: dist_async semantics OK "
          f"(value = {3 + nworker})")


if __name__ == "__main__":
    main()
