#!/usr/bin/env python
"""Failure recovery demonstration (SURVEY.md §5: the reference has no
elastic recovery — its story, and TPU practice, is checkpoint/restore +
re-launch; reference anchors: the kStopServer teardown in kvstore_dist.h
and callback.do_checkpoint).

Run once with MXTPU_CRASH_AFTER_EPOCH=2: the process hard-dies (os._exit,
no cleanup — simulating a preemption/OOM kill) right after epoch 2's
sharded checkpoint lands. Run again without it: fit() auto-resumes from
the newest complete step in the checkpoint dir and trains to completion.

    MXTPU_CRASH_AFTER_EPOCH=2 python crash_resume_train.py /tmp/ckpt || true
    python crash_resume_train.py /tmp/ckpt     # resumes at epoch 2
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.models import mlp


def main():
    ckpt_dir = sys.argv[1]
    crash_after = int(os.environ.get("MXTPU_CRASH_AFTER_EPOCH", "0"))

    rng = np.random.RandomState(0)
    X = np.concatenate([rng.randn(128, 8) + 1.0,
                        rng.randn(128, 8) - 1.0]).astype(np.float32)
    y = np.concatenate([np.ones(128), np.zeros(128)]).astype(np.float32)

    def maybe_crash(epoch, symbol, arg_params, aux_params):
        if crash_after and epoch + 1 >= crash_after:
            print(f"simulated preemption after epoch {epoch}", flush=True)
            os._exit(137)  # hard kill: no atexit, no flush, like the real thing

    model = mx.FeedForward(mlp(num_classes=2, hidden=(16,)), num_epoch=5,
                           optimizer="sgd", learning_rate=0.1,
                           initializer=mx.init.Xavier())
    model.fit(X, y, batch_size=32, sharded_checkpoint_dir=ckpt_dir,
              epoch_end_callback=maybe_crash)

    acc = model.score(X, y=y)
    print(f"crash_resume final accuracy = {acc:.4f} "
          f"(resumed from epoch {model.begin_epoch})")
    assert acc > 0.95, acc


if __name__ == "__main__":
    main()
