#!/usr/bin/env python
"""Distributed data-parallel MLP training (reference:
tests/python/multi-node/dist_sync_mlp.py — each worker trains on its shard,
gradients BSP-synced every batch, final accuracy asserted).

Run under the launcher:
    python tools/launch.py -n 2 python examples/distributed/dist_sync_mlp.py

Each process joins the jax.distributed world (kv.create('dist_sync') wires
it up from the launcher env), the FeedForward trainer builds a data-parallel
mesh over ALL processes' devices, and the per-batch gradient psum rides the
collective backend (Gloo on CPU here; ICI/DCN on a TPU pod).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx


def make_dataset(n=1024, dim=16, seed=42):
    """Deterministic two-class blobs — identical on every worker
    (reference: multi-node/common.py disables iterator randomness)."""
    rng = np.random.RandomState(seed)
    half = n // 2
    X = np.concatenate([rng.randn(half, dim) + 1.5,
                        rng.randn(half, dim) - 1.5]).astype(np.float32)
    y = np.concatenate([np.zeros(half), np.ones(half)]).astype(np.float32)
    perm = rng.permutation(n)
    return X[perm], y[perm]


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    X, y = make_dataset()
    # shard rows by rank (≙ num_parts/part_index sharding in the iterators)
    Xs, ys = X[rank::nworker], y[rank::nworker]

    net = mx.symbol.Variable("data")
    net = mx.symbol.FullyConnected(data=net, num_hidden=32, name="fc1")
    net = mx.symbol.Activation(data=net, act_type="relu", name="relu1")
    net = mx.symbol.FullyConnected(data=net, num_hidden=2, name="fc2")
    net = mx.symbol.SoftmaxOutput(data=net, name="softmax")

    model = mx.model.FeedForward(
        symbol=net, num_epoch=5, learning_rate=0.1, momentum=0.9,
        initializer=mx.init.Xavier())
    model.fit(Xs, ys, batch_size=32, kvstore=kv)

    acc = model.score(X, y=y)
    print(f"worker {rank}/{nworker}: dist_sync_mlp accuracy = {acc:.4f}")
    assert acc > 0.95, f"worker {rank}: accuracy too low: {acc}"
    kv.barrier()


if __name__ == "__main__":
    main()
