"""Shared dataset + runner for the multi-node LeNet tiers (reference:
tests/python/multi-node/common.py — one module the sync and async
conv-net scripts both import, randomness fixed so every worker and every
run sees identical data)."""

import numpy as np


def make_dataset(n=512, seed=42):
    """Deterministic 4-class 28x28 images: a bright square in one of the
    four quadrants identifies the class."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.1
    y = rng.randint(0, 4, (n,)).astype(np.float32)
    corners = {0: (2, 2), 1: (2, 16), 2: (16, 2), 3: (16, 16)}
    for i in range(n):
        r, c = corners[int(y[i])]
        X[i, 0, r:r + 10, c:c + 10] += 1.0
    return X, y


def run_tier(kv_type, lr, tag, threshold=0.9):
    """The whole launched-worker body both tiers share: create the store,
    shard rows by rank, train LeNet, score on the FULL set, assert."""
    import mxnet_tpu as mx
    from mxnet_tpu.models import lenet

    kv = mx.kv.create(kv_type)
    rank, nworker = kv.rank, kv.num_workers
    X, y = make_dataset()
    Xs, ys = X[rank::nworker], y[rank::nworker]

    model = mx.model.FeedForward(
        symbol=lenet(num_classes=4), num_epoch=6,
        learning_rate=lr, momentum=0.9, initializer=mx.init.Xavier())
    model.fit(Xs, ys, batch_size=32, kvstore=kv)

    acc = model.score(X, y=y)
    print(f"worker {rank}/{nworker}: {tag} accuracy = {acc:.4f}")
    assert acc > threshold, f"worker {rank}: accuracy too low: {acc}"
    kv.barrier()
