"""Shared dataset for the multi-node LeNet tiers (reference:
tests/python/multi-node/common.py — one deterministic dataset module the
sync and async conv-net scripts both import, randomness fixed so every
worker and every run sees identical data)."""

import numpy as np


def make_dataset(n=512, seed=42):
    """Deterministic 4-class 28x28 images: a bright square in one of the
    four quadrants identifies the class."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.1
    y = rng.randint(0, 4, (n,)).astype(np.float32)
    corners = {0: (2, 2), 1: (2, 16), 2: (16, 2), 3: (16, 16)}
    for i in range(n):
        r, c = corners[int(y[i])]
        X[i, 0, r:r + 10, c:c + 10] += 1.0
    return X, y
