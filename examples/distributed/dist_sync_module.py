#!/usr/bin/env python
"""Distributed data-parallel training through the MODULE API (the BASELINE
north star's module.fit(), multi-worker): gradients round through a
dist_sync store each step (push/pull -> summed across workers), rank 0's
initialization is broadcast so replicas start identical, and the rescale
folds num_workers — every worker must converge to the same accurate model.

Run under the launcher:
    python tools/launch.py -n 2 python examples/distributed/dist_sync_module.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx


def make_dataset(n=1024, dim=16, seed=42):
    rng = np.random.RandomState(seed)
    half = n // 2
    X = np.concatenate([rng.randn(half, dim) + 1.5,
                        rng.randn(half, dim) - 1.5]).astype(np.float32)
    y = np.concatenate([np.zeros(half), np.ones(half)]).astype(np.float32)
    perm = rng.permutation(n)
    return X[perm], y[perm]


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    X, y = make_dataset()
    Xs, ys = X[rank::nworker], y[rank::nworker]

    net = mx.symbol.Variable("data")
    net = mx.symbol.FullyConnected(data=net, num_hidden=32, name="fc1")
    net = mx.symbol.Activation(data=net, act_type="relu", name="relu1")
    net = mx.symbol.FullyConnected(data=net, num_hidden=2, name="fc2")
    net = mx.symbol.SoftmaxOutput(data=net, name="softmax")

    # per-process RNG seeds differ on purpose: the rank-0 broadcast in
    # fit(kvstore=...) must still produce identical replicas
    np.random.seed(1234 + rank)
    it = mx.io.NDArrayIter(Xs, ys, batch_size=32)
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=5, initializer=mx.init.Xavier(), kvstore=kv,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1 / 32.0})

    name, acc = mod.score(mx.io.NDArrayIter(X, y, batch_size=32))
    # replicas must agree: print a weight digest every rank can compare
    w = mod.get_params()[0]["fc1_weight"].asnumpy()
    print(f"worker {rank}/{nworker}: dist_sync_module accuracy = "
          f"{acc:.4f} wsum = {float(np.abs(w).sum()):.6f}")
    assert acc > 0.95, f"worker {rank}: accuracy too low: {acc}"
    kv.barrier()


if __name__ == "__main__":
    main()
