#!/usr/bin/env python
"""Distributed data-parallel CONV-NET training to asserted accuracy
(reference: tests/python/multi-node/dist_sync_lenet.py — LeNet on MNIST
across launched workers, BSP gradient sync every batch; common.py:2-4 fixes
randomness so every run converges identically).

Run under the launcher:
    python tools/launch.py -n 2 python examples/distributed/dist_sync_lenet.py

Against dist_sync_mlp.py this tier adds what the judge's round-4 review
asked for: the *convolutional* stack (conv/pool/BN-free LeNet, the same
symbol family the reference trains) through the multi-process mesh path —
conv gradients and the im2col-shaped XLA programs are sharded and synced,
not just dense matmuls.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from lenet_dist_common import make_dataset
from mxnet_tpu.models import lenet


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    X, y = make_dataset()
    Xs, ys = X[rank::nworker], y[rank::nworker]

    model = mx.model.FeedForward(
        symbol=lenet(num_classes=4), num_epoch=6,
        learning_rate=0.1, momentum=0.9, initializer=mx.init.Xavier())
    model.fit(Xs, ys, batch_size=32, kvstore=kv)

    acc = model.score(X, y=y)
    print(f"worker {rank}/{nworker}: dist_sync_lenet accuracy = {acc:.4f}")
    assert acc > 0.9, f"worker {rank}: accuracy too low: {acc}"
    kv.barrier()


if __name__ == "__main__":
    main()
