#!/usr/bin/env python
"""Distributed data-parallel CONV-NET training to asserted accuracy
(reference: tests/python/multi-node/dist_sync_lenet.py — LeNet across
launched workers, BSP gradient sync every batch; common.py:2-4 fixes
randomness so every run converges identically).

Run under the launcher:
    python tools/launch.py -n 2 python examples/distributed/dist_sync_lenet.py

Against dist_sync_mlp.py this tier adds the *convolutional* stack through
the multi-process mesh path — conv gradients and the im2col-shaped XLA
programs are sharded and synced, not just dense matmuls. The worker body
lives in lenet_dist_common.run_tier (shared with the async tier).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from lenet_dist_common import run_tier

if __name__ == "__main__":
    run_tier("dist_sync", lr=0.1, tag="dist_sync_lenet")
