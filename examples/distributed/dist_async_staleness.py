#!/usr/bin/env python
"""dist_async staleness characterization: workers at deliberately skewed
speeds (reference semantics: kvstore_dist_server.h:194-202 — update on
arrival, unbounded staleness; consistency table
doc/developer-guide/multi_node.md:21-27).

Run under the launcher:
    python tools/launch.py -n 4 python examples/distributed/dist_async_staleness.py

Each worker trains the same tiny logistic-regression objective but sleeps
rank*SKEW seconds per batch, so fast workers lap slow ones — under BSP this
would stall the fleet at the slowest worker; under dist_async every push is
applied immediately. Asserts:
  * every worker completes all of its batches (no worker gated on another),
  * the server applied exactly sum(batches) update batches (update_count),
  * the final model still converges despite stale gradients.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx

BATCHES = 12
SKEW = 0.05  # seconds of extra per-batch latency per rank


def make_dataset(n=1024, dim=8, seed=7):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim).astype(np.float32)
    X = rng.randn(n, dim).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    return X, y, w


def main():
    kv = mx.kv.create("dist_async")
    rank, nworker = kv.rank, kv.num_workers
    X, y, _ = make_dataset()
    dim = X.shape[1]

    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5))
    # all ranks call init (rank 0 sends the value, init barriers internally)
    kv.init(0, mx.nd.zeros((dim,)))

    batch = 64
    rng = np.random.RandomState(100 + rank)
    t0 = time.monotonic()
    for step in range(BATCHES):
        idx = rng.randint(0, len(X), size=batch)
        xb, yb = X[idx], y[idx]
        w = kv.pull_many([0])[0]
        # logistic-regression gradient on this worker's (stale) weights
        p = 1.0 / (1.0 + np.exp(-(xb @ w)))
        grad = xb.T @ (p - yb) / batch
        kv.push_pull({0: grad.astype(np.float32)})
        time.sleep(rank * SKEW)  # skew: rank 3 runs ~4x slower than rank 0
    elapsed = time.monotonic() - t0
    print(f"worker {rank}/{nworker}: completed {BATCHES} batches "
          f"in {elapsed:.2f}s")

    kv.barrier()
    if rank == 0:
        stats = kv.stats()
        expect = BATCHES * nworker
        assert stats["update_count"] == expect, \
            f"server applied {stats['update_count']} updates, expected {expect}"
        w = kv.pull_many([0])[0]
        p = 1.0 / (1.0 + np.exp(-(X @ w)))
        acc = float(np.mean((p > 0.5) == (y > 0.5)))
        print(f"dist_async_staleness OK: updates={stats['update_count']} "
              f"acc={acc:.4f}")
        assert acc > 0.9, f"stale-gradient training failed to converge: {acc}"
    kv.barrier()


if __name__ == "__main__":
    main()
