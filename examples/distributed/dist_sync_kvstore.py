#!/usr/bin/env python
"""Distributed KVStore semantics test (ported from the reference:
tests/python/multi-node/dist_sync_kvstore.py, launched there as
`dmlc_local.py -n 4 -s 4 ./dist_sync_kvstore.py`).

Two modes:
  - under tools/launch.py (MXTPU_WORKER_RANK set): each process is a worker,
    semantics run over jax.distributed when available.
  - standalone (default): 4 in-process workers on threads against the BSP
    server group — same accumulate-until-N semantics, one command:
      python examples/distributed/dist_sync_kvstore.py
"""

import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx

SHAPE = (4, 4)
KEYS = [3, 5, 7]
BIG_SHAPE = (1200,)  # ≙ the reference's striped "big array" key
BIG_KEY = 99


def check(kv, nworker):
    # init (rank 0) then one BSP push round per key
    kv.init(KEYS, [mx.nd.ones(SHAPE)] * len(KEYS))
    kv.init(BIG_KEY, mx.nd.ones(BIG_SHAPE))
    rank = kv.rank
    kv.push(KEYS, [[mx.nd.ones(SHAPE) * (rank + 1)]] * len(KEYS))
    kv.push(BIG_KEY, [mx.nd.ones(BIG_SHAPE) * (rank + 1)])
    expected = sum(r + 1 for r in range(nworker))
    outs = [mx.nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), np.ones(SHAPE) * expected)
    big = mx.nd.empty(BIG_SHAPE)
    kv.pull(BIG_KEY, out=big)
    np.testing.assert_allclose(big.asnumpy(), np.ones(BIG_SHAPE) * expected)
    kv.barrier()
    print(f"worker {rank}/{nworker}: dist_sync semantics OK "
          f"(reduced value = {expected})")


def main():
    if "MXTPU_WORKER_RANK" in os.environ:
        kv = mx.kv.create("dist_sync")
        check(kv, kv.num_workers)
        return
    n = 4
    stores = mx.kv.create_group(n)
    errors = []

    def worker(rank):
        try:
            check(stores[rank], n)
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    if errors:
        raise SystemExit(f"FAILED: {errors}")
    print("all workers passed")


if __name__ == "__main__":
    main()
