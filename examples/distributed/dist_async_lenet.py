#!/usr/bin/env python
"""Asynchronous distributed CONV-NET training (reference:
tests/python/multi-node/dist_async_lenet.py — LeNet against the async
parameter server, workers at their own pace, accuracy asserted).

Run under the launcher:
    python tools/launch.py -n 2 python examples/distributed/dist_async_lenet.py

Completes the multi-node matrix: {sync, async} x {mlp, lenet}. The async
conv tier exercises what the sync one cannot — conv/pool gradients flowing
through the pickled-tensor wire to the update-on-arrival host (reference:
kvstore_dist_server.h:194-202) rather than through an in-jit collective.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from lenet_dist_common import make_dataset
from mxnet_tpu.models import lenet


def main():
    kv = mx.kv.create("dist_async")
    rank, nworker = kv.rank, kv.num_workers
    X, y = make_dataset()
    Xs, ys = X[rank::nworker], y[rank::nworker]

    model = mx.model.FeedForward(
        symbol=lenet(num_classes=4), num_epoch=6,
        learning_rate=0.05, momentum=0.9, initializer=mx.init.Xavier())
    model.fit(Xs, ys, batch_size=32, kvstore=kv)

    acc = model.score(X, y=y)
    print(f"worker {rank}/{nworker}: dist_async_lenet accuracy = {acc:.4f}")
    assert acc > 0.9, f"worker {rank}: accuracy too low: {acc}"
    kv.barrier()


if __name__ == "__main__":
    main()
