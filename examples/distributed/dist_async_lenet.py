#!/usr/bin/env python
"""Asynchronous distributed CONV-NET training (reference:
tests/python/multi-node/dist_async_lenet.py — LeNet against the async
parameter server, workers at their own pace, accuracy asserted).

Run under the launcher:
    python tools/launch.py -n 2 python examples/distributed/dist_async_lenet.py

Completes the multi-node matrix: {sync, async} x {mlp, lenet}. The async
conv tier exercises what the sync one cannot — conv/pool gradients flowing
through the pickled-tensor wire to the update-on-arrival host (reference:
kvstore_dist_server.h:194-202) rather than through an in-jit collective.
The worker body lives in lenet_dist_common.run_tier (shared with the sync
tier; only kv type and lr differ).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from lenet_dist_common import run_tier

if __name__ == "__main__":
    run_tier("dist_async", lr=0.05, tag="dist_async_lenet")
