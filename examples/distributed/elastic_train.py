#!/usr/bin/env python
"""Elastic training demonstration (ISSUE 10; ROADMAP item 4).

An 8-virtual-device data-parallel run loses 2 workers mid-epoch, keeps
training on the 6 survivors, and re-absorbs the capacity two epochs
later — all in ONE process, no relaunch. The ElasticCoordinator owns
membership; fit() polls it every step and, on a change, quiesces,
re-shards state from the CRC-manifest checkpoints onto the new dp axis,
re-derives the wire plans, AOT re-warms the new axis, and resumes. The
downtime is priced into the per-epoch Goodput line as `resize` badput.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python elastic_train.py /tmp/elastic_ckpt
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.resilience import ElasticCoordinator


def main():
    ckpt_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/elastic_ckpt"

    rng = np.random.RandomState(0)
    X = np.concatenate([rng.randn(240, 8) + 1.0,
                        rng.randn(240, 8) - 1.0]).astype(np.float32)
    y = np.concatenate([np.ones(240), np.zeros(240)]).astype(np.float32)
    order = rng.permutation(480)  # mixed-class batches; the ITERATOR stays
    X, y = X[order], y[order]     # unshuffled so every epoch replays bitwise

    world = 8
    co = ElasticCoordinator(world, min_world=4)

    def churn(param):
        # a real deployment calls kill() from heartbeat expiry or a
        # kvstore MembershipTimeout; here the schedule is scripted
        if param.epoch == 1 and param.nbatch == 3 and co.world_size == 8:
            print(">>> losing ranks", co.kill(), "and", co.kill())
        if param.epoch == 3 and param.nbatch == 2 and co.world_size == 6:
            print(">>> capacity returned:", co.join_all())

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(data=net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(data=net, name="fc2", num_hidden=2)
    net = mx.sym.SoftmaxOutput(data=net, name="softmax")
    model = mx.FeedForward(
        net, ctx=[mx.cpu(i) for i in range(world)],
        num_epoch=5, optimizer="sgd", learning_rate=0.1)
    model.fit(mx.io.NDArrayIter(X, y, batch_size=48, shuffle=False),
              batch_size=48, elastic=co, sharded_checkpoint_dir=ckpt_dir,
              batch_end_callback=churn, compression="int8", overlap=True,
              telemetry=True)

    print("resizes:", co.resizes)
    for h in co.history:
        print(f"  {h['from']} -> {h['to']}  downtime {h['downtime_s']:.2f}s"
              f"  ({h['reason']})")
    print("final accuracy:", model.score(X, y=y))


if __name__ == "__main__":
    main()
