#!/usr/bin/env python
"""ImageNet-scale training (reference: example/imagenet/ — AlexNet and
Inception-BN with ImageRecordIter; here plus ResNet-50, the BASELINE.json
north-star model).

Data: RecordIO shards from tools/im2rec.py (--data-rec), or synthetic
224x224 JPEG records (default). Multi-device data parallelism via
--num-devices (kvstore 'device' ≙ ICI allreduce inside the fused step);
multi-host via --kv-store dist_sync under tools/launch.py.

  python examples/imagenet/train_imagenet.py --network resnet-50 --bf16
"""

import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def make_synthetic_rec(path, n=512, num_classes=100, size=256, seed=0):
    from mxnet_tpu import recordio as rio

    rng = np.random.RandomState(seed)
    # write-then-rename: under tools/launch.py several workers race to
    # create the same shard; os.replace makes the publish atomic so a
    # reader never sees a half-written file
    tmp = f"{path}.w{os.getpid()}"
    w = rio.MXRecordIO(tmp, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i % num_classes), i, 0),
                             img, img_fmt=".jpg", quality=85))
    w.close()
    os.replace(tmp, path)
    return path


NETWORKS = {
    "alexnet": lambda n: __import__("mxnet_tpu.models", fromlist=["alexnet"]).alexnet(n),
    "inception-bn": lambda n: __import__("mxnet_tpu.models", fromlist=["inception_bn"]).inception_bn(n),
    "resnet-50": lambda n: __import__("mxnet_tpu.models", fromlist=["resnet50"]).resnet50(n),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=sorted(NETWORKS), default="resnet-50")
    ap.add_argument("--data-rec", default=None)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="device")
    ap.add_argument("--num-devices", type=int, default=1)
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    import mxnet_tpu as mx

    logging.basicConfig(level=logging.INFO)
    # dist kvstore first: the iterator shards by worker rank (reference:
    # ImageRecordIter num_parts/part_index from kvstore rank, so each
    # worker reads only its slice — multi-node/README.md discipline)
    kv = mx.kv.create(args.kv_store) if "dist" in args.kv_store \
        else args.kv_store
    num_parts, part_index = (kv.num_workers, kv.rank) \
        if "dist" in args.kv_store else (1, 0)

    rec = args.data_rec
    if rec is None:
        args.num_classes = 100
        n_synth = int(os.environ.get("MXTPU_SYNTH_IMAGES", "512"))
        # filename keyed on n: a cached shard from a different size must
        # not be silently reused
        rec = os.path.join(tempfile.gettempdir(),
                           f"imagenet_synth_{n_synth}.rec")
        if not os.path.exists(rec):
            if part_index == 0:
                # rank 0 generates, the rest wait for the atomic publish
                # (same discipline as the iterator's cached mean image,
                # io/__init__.py) — N identical JPEG passes are wasted CPU
                logging.info("generating synthetic ImageNet rec at %s", rec)
                make_synthetic_rec(rec, n=n_synth)
            else:
                import time as _time

                deadline = _time.time() + 600
                while not os.path.exists(rec):
                    if _time.time() > deadline:
                        raise RuntimeError(f"timed out waiting for {rec}")
                    _time.sleep(0.5)

    train = mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 224, 224), batch_size=args.batch_size,
        rand_crop=True, rand_mirror=True, shuffle=True, resize=256,
        mean_r=123.68, mean_g=116.78, mean_b=103.94, scale=1 / 58.8,
        num_parts=num_parts, part_index=part_index)

    net = NETWORKS[args.network](args.num_classes)
    ctx = [mx.tpu(i) for i in range(args.num_devices)]
    model = mx.FeedForward(
        net, ctx=ctx, num_epoch=args.num_epochs,
        initializer=mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                   magnitude=2),
        compute_dtype=jnp.bfloat16 if args.bf16 else None,
        lr=args.lr, momentum=0.9, wd=1e-4)
    # checkpoint from rank 0 only: every rank holds the same BSP-synced
    # weights, and two ranks writing one prefix would race/truncate
    callbacks = mx.callback.do_checkpoint(
        os.path.join(tempfile.gettempdir(), args.network)) \
        if part_index == 0 else None
    model.fit(train, kvstore=kv,
              batch_end_callback=mx.callback.Speedometer(args.batch_size, 10),
              epoch_end_callback=callbacks)


if __name__ == "__main__":
    main()
