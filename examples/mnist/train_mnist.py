#!/usr/bin/env python
"""MNIST training (reference: example/mnist/{mlp.py,lenet.py,train_mnist.py}).

Runs on real MNIST idx files (--data-dir with train-images-idx3-ubyte etc.,
gzip ok) or, by default in this offline environment, on a synthetic
MNIST-shaped dataset that converges the same way.

  python examples/mnist/train_mnist.py --network mlp
  python examples/mnist/train_mnist.py --network lenet --lr 0.05
"""

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_mnist(n=2048, num_classes=10, seed=0):
    """Digit-like data: each class is a fixed random stroke pattern + noise."""
    rng = np.random.RandomState(seed)
    protos = rng.rand(num_classes, 28, 28) > 0.8
    X = np.zeros((n, 28, 28), np.float32)
    y = np.zeros((n,), np.float32)
    for i in range(n):
        cls = i % num_classes
        X[i] = protos[cls] * (0.7 + 0.3 * rng.rand()) + 0.1 * rng.rand(28, 28)
        y[i] = cls
    order = rng.permutation(n)
    return X[order], y[order]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=["mlp", "lenet"], default="mlp")
    ap.add_argument("--data-dir", default=None, help="dir with MNIST idx files")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--num-devices", type=int, default=1)
    ap.add_argument("--cpu", action="store_true", help="force CPU platform")
    ap.add_argument("--api", choices=["feedforward", "module"],
                    default="feedforward",
                    help="estimator API: FeedForward (reference parity) or "
                         "Module (the BASELINE north star's module.fit())")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.models import lenet, mlp

    logging.basicConfig(level=logging.INFO)
    flat = args.network == "mlp"
    net = mlp() if flat else lenet()

    if args.data_dir:
        train = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, "train-images-idx3-ubyte"),
            label=os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True, flat=flat)
        val = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(args.data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size, flat=flat)
    else:
        logging.info("no --data-dir; using synthetic MNIST-shaped data")
        X, y = synthetic_mnist()
        X = X.reshape(len(X), -1) if flat else X[:, None]
        split = int(0.9 * len(X))
        train = mx.io.NDArrayIter(X[:split], y[:split],
                                  batch_size=args.batch_size, shuffle=True)
        val = mx.io.NDArrayIter(X[split:], y[split:], batch_size=args.batch_size)

    if args.api == "module":
        if args.num_devices > 1:
            logging.warning("--api module is single-device; use "
                            "--api feedforward for multi-device dp "
                            "(--num-devices ignored)")
        kv = mx.kv.create(args.kv_store) if "dist" in args.kv_store else None
        mod = mx.mod.Module(net, context=mx.tpu() if not args.cpu
                            else mx.cpu())
        mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
                initializer=mx.init.Xavier(), kvstore=kv,
                optimizer_params={"learning_rate": args.lr,
                                  "momentum": args.momentum,
                                  "rescale_grad": 1.0 / args.batch_size})
        print("final val accuracy:", mod.score(val)[1])
        return

    ctx = [mx.tpu(i) for i in range(args.num_devices)]
    model = mx.FeedForward(net, ctx=ctx, num_epoch=args.num_epochs,
                           initializer=mx.init.Xavier(),
                           lr=args.lr, momentum=args.momentum)
    model.fit(train, eval_data=val, kvstore=args.kv_store,
              batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    print("final val accuracy:", model.score(val))


if __name__ == "__main__":
    main()
