"""Fast-gradient-sign adversarial examples (rewrite of the reference
example/adversary/adversary_generation.ipynb): train a classifier, then
bind an executor with a gradient buffer on the INPUT and perturb images by
the sign of dLoss/dInput.

Demonstrates the raw bind/forward/backward API surface: grad_req on data,
backward() populating input gradients — the same mechanics the reference
notebook uses through simple_bind.

Run: python examples/adversary/fgsm.py
"""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import symbol as sym


def build_mlp(classes):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=64)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=classes)
    return sym.SoftmaxOutput(data=net, name="softmax")


def main(eps=0.15):
    from sklearn.datasets import load_digits

    d = load_digits()
    X = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.float32)
    net = build_mlp(10)
    model = mx.FeedForward(net, ctx=mx.cpu(), num_epoch=20,
                           learning_rate=0.1, momentum=0.9,
                           initializer=mx.init.Xavier())
    model.fit(X, y, batch_size=50)
    clean_acc = (model.predict(X, batch_size=50).argmax(axis=1) == y).mean()

    # bind with a gradient buffer on the input only
    batch = 50
    exe = net.simple_bind(ctx=mx.cpu(), grad_req={"data": "write"},
                          data=(batch, X.shape[1]),
                          softmax_label=(batch,))
    exe.copy_params_from(model.arg_params, model.aux_params)

    adv = np.empty_like(X)
    for i in range(0, len(X) - batch + 1, batch):
        xb, yb = X[i:i + batch], y[i:i + batch]
        exe.forward(is_train=True, data=xb, softmax_label=yb)
        exe.backward()  # loss head injects prob - onehot
        g = exe.grad_dict["data"].asnumpy()
        adv[i:i + batch] = np.clip(xb + eps * np.sign(g), 0.0, 1.0)
    n_done = (len(X) // batch) * batch
    adv[n_done:] = X[n_done:]

    adv_acc = (model.predict(adv, batch_size=50).argmax(axis=1) == y).mean()
    print(f"clean accuracy: {clean_acc:.3f}   "
          f"adversarial (eps={eps}): {adv_acc:.3f}")
    assert clean_acc > 0.95
    assert adv_acc < clean_acc - 0.3, "FGSM should break the classifier"


if __name__ == "__main__":
    main()
