#!/usr/bin/env python
"""Unrolled-LSTM language model (reference: example/rnn/lstm.py + the PTB
bucketing-executor config in BASELINE.json).

Like the reference, this drives the *Executor API directly* (bind once per
sequence length, per-step data variables, forward/backward + manual SGD) —
exercising weight sharing across the unrolled graph. Data is a synthetic
character stream by default (--text for a real corpus file).

The scan-based fast path for the same model lives in
examples/rnn/lstm_scan.py; this script is the API-parity path.
"""

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_text(n_chars=20000, vocab=32, seed=0):
    """A char stream with learnable structure (repeated motifs + noise)."""
    rng = np.random.RandomState(seed)
    motifs = [rng.randint(0, vocab, rng.randint(3, 8)) for _ in range(8)]
    out = []
    while len(out) < n_chars:
        m = motifs[rng.randint(len(motifs))]
        out.extend(m.tolist())
        if rng.rand() < 0.1:
            out.append(rng.randint(vocab))
    return np.array(out[:n_chars], np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", default=None, help="path to a text corpus")
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=128)
    ap.add_argument("--num-embed", type=int, default=64)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.models import lstm_unroll

    logging.basicConfig(level=logging.INFO)
    if args.text:
        with open(args.text, "rb") as f:
            raw = f.read()
        vocab_map = {b: i for i, b in enumerate(sorted(set(raw)))}
        stream = np.array([vocab_map[b] for b in raw], np.float32)
        vocab = len(vocab_map)
    else:
        vocab = 32
        stream = synthetic_text(vocab=vocab)

    seq, bs = args.seq_len, args.batch_size
    sym = lstm_unroll(args.num_layers, seq, vocab, args.num_hidden,
                      args.num_embed, vocab)

    shapes = {}
    for t in range(seq):
        shapes[f"t{t}_data"] = (bs,)
        shapes[f"t{t}_label"] = (bs,)
    for l in range(args.num_layers):
        shapes[f"l{l}_init_c"] = (bs, args.num_hidden)
        shapes[f"l{l}_init_h"] = (bs, args.num_hidden)

    exe = sym.simple_bind(mx.tpu(), **shapes)
    init = mx.init.Xavier()
    mx.random.seed(0)
    for name, arr in exe.arg_dict.items():
        if name in shapes:
            continue
        init(name if name.endswith(("weight", "bias")) else name + "_weight", arr)

    opt = mx.optimizer.create("sgd", lr=args.lr, momentum=0.9,
                              rescale_grad=1.0 / (bs * seq), clip_gradient=5.0)
    updater = mx.optimizer.get_updater(opt)
    param_names = [n for n in exe.arg_dict if n not in shapes]

    # batch the stream: [n_batches, seq, bs]
    usable = (len(stream) - 1) // (seq * bs) * (seq * bs)
    data = stream[:usable].reshape(bs, -1, seq).transpose(1, 2, 0)
    labels = stream[1:usable + 1].reshape(bs, -1, seq).transpose(1, 2, 0)

    for epoch in range(args.num_epochs):
        total_nll, count = 0.0, 0
        tic = time.time()
        for b in range(data.shape[0]):
            kwargs = {}
            for t in range(seq):
                kwargs[f"t{t}_data"] = mx.nd.array(data[b, t])
                kwargs[f"t{t}_label"] = mx.nd.array(labels[b, t])
            outs = exe.forward(is_train=True, **kwargs)
            exe.backward()
            for i, name in enumerate(param_names):
                updater(i, exe.grad_dict[name], exe.arg_dict[name])
            # perplexity from the per-step softmax outputs
            for t in range(seq):
                p = outs[t].asnumpy()
                idx = labels[b, t].astype(int)
                total_nll -= np.log(p[np.arange(bs), idx] + 1e-8).sum()
                count += bs
        ppl = float(np.exp(total_nll / count))
        logging.info("Epoch[%d] perplexity=%.2f (%.1fs) [vocab=%d]",
                     epoch, ppl, time.time() - tic, vocab)


if __name__ == "__main__":
    main()
