"""Bucketed LSTM language model (reference capability: example/rnn/lstm.py's
executor-per-seq-len binding; here one compiled XLA program per bucket over
shared weights — see mxnet_tpu/bucketing.py).

Generates a synthetic corpus of variable-length token sequences, buckets
them, and trains with the per-bucket compile cache. Swap ``_corpus`` for a
PTB loader to reproduce the reference's rnn example end to end.
"""

import logging

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import lstm_unroll

VOCAB = 64
HIDDEN = 64
EMBED = 32
LAYERS = 1
BATCH = 32
BUCKETS = [8, 16, 32]


def _corpus(n=2000, seed=0):
    """Synthetic text: arithmetic token cycles with random stride/length."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        length = int(rng.randint(4, BUCKETS[-1] + 1))
        start = int(rng.randint(1, VOCAB))
        stride = int(rng.choice([1, 2, 3]))
        out.append([(start + i * stride - 1) % (VOCAB - 1) + 1
                    for i in range(length)])
    return out


def main():
    logging.basicConfig(level=logging.INFO)
    init_states = [(f"l{i}_init_{s}", (BATCH, HIDDEN))
                   for i in range(LAYERS) for s in "ch"]
    train = mx.BucketSentenceIter(_corpus(), BUCKETS, BATCH,
                                  init_states=init_states, shuffle=True)

    def sym_gen(seq_len):
        return lstm_unroll(LAYERS, seq_len, VOCAB, HIDDEN, EMBED, VOCAB)

    model = mx.BucketingFeedForward(
        sym_gen, default_bucket_key=train.default_bucket_key,
        num_epoch=5, optimizer="adam", learning_rate=0.01,
        initializer=mx.init.Xavier())
    model.fit(train, batch_size=BATCH, eval_metric="accuracy")


if __name__ == "__main__":
    main()
