#!/usr/bin/env python
"""Scan-based LSTM language model — the TPU fast path for the same model as
lstm_ptb.py (SURVEY.md §5: the reference's only sequence story is full graph
unrolling; `lax.scan` compiles the recurrence once regardless of sequence
length, so there is no per-seq-len bind and no bucketing executor).

  python examples/rnn/lstm_scan.py --seq-len 64 --cpu
"""

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=128)
    ap.add_argument("--num-embed", type=int, default=64)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    from mxnet_tpu.models.lstm_scan import LSTMLM

    sys.path.insert(0, os.path.dirname(__file__))
    from lstm_ptb import synthetic_text

    logging.basicConfig(level=logging.INFO)
    vocab = 32
    stream = synthetic_text(n_chars=100000, vocab=vocab)

    model = LSTMLM(vocab=vocab, num_embed=args.num_embed,
                   num_hidden=args.num_hidden, num_layers=args.num_layers)
    params = model.init_params(jax.random.PRNGKey(0))
    states = model.init_optimizer(params)
    step = model.make_train_step(lr=args.lr, clip=5.0)

    seq, bs = args.seq_len, args.batch_size
    usable = (len(stream) - 1) // (seq * bs) * (seq * bs)
    data = stream[:usable].reshape(bs, -1, seq).transpose(1, 0, 2).astype(np.int32)
    labels = stream[1:usable + 1].reshape(bs, -1, seq).transpose(1, 0, 2).astype(np.int32)

    tic = time.time()
    n = min(args.steps, data.shape[0])
    for i in range(n):
        params, states, loss = step(params, states, data[i], labels[i])
        if i % 20 == 0:
            logging.info("step %d ppl=%.2f", i, float(np.exp(loss)))
    final = float(np.exp(loss))
    dt = time.time() - tic
    logging.info("final perplexity=%.2f  |  %.0f tokens/sec",
                 final, n * bs * seq / dt)


if __name__ == "__main__":
    main()
