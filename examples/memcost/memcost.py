#!/usr/bin/env python
"""Memory-plan introspection (reference: example/memcost — prints 'Total N MB
allocated' for inception-bn b32 under different memory strategies via
GraphExecutor::Print).

On TPU the strategies map to compiler features instead of executor flags:
  no_optimization   -> eval-shape accounting of every intermediate (upper bound)
  inplace+sharing   -> XLA buffer assignment (what actually allocates)
  forward_only      -> inference-only program
  + remat           -> jax.checkpoint on the loss (activation memory traded
                       for recompute; the note_memory.md tradeoff, compiler-made)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.executor import _build_graph_fn
from mxnet_tpu.models import inception_bn_cifar


def mb(x):
    return x / (1 << 20)


def main():
    batch = 32
    sym = inception_bn_cifar()
    shapes = {"data": (batch, 3, 28, 28), "softmax_label": (batch,)}
    arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
    arg_names, aux_names = sym.list_arguments(), sym.list_auxiliary_states()
    args = {n: jnp.zeros(s, jnp.float32) for n, s in zip(arg_names, arg_shapes)}
    aux = {n: jnp.zeros(s, jnp.float32) for n, s in zip(aux_names, aux_shapes)}
    key = jnp.zeros((2,), jnp.uint32)

    # upper bound: every intermediate held live (≙ no_optimization)
    internals = sym.get_internals()
    fn_all = _build_graph_fn(internals, is_train=False)
    outs = jax.eval_shape(lambda a, x: fn_all(a, x, key)[0], args, aux)
    naive = sum(int(np.prod(o.shape)) * 4 for o in outs)
    print(f"no_optimization (sum of all intermediates): {mb(naive):8.2f} MB")

    def report(tag, fn):
        compiled = jax.jit(fn).lower(args, aux).compile()
        try:
            m = compiled.memory_analysis()
            total = m.temp_size_in_bytes + m.output_size_in_bytes
            print(f"{tag:45s}: {mb(total):8.2f} MB "
                  f"(temp {mb(m.temp_size_in_bytes):.2f})")
        except Exception:
            print(f"{tag:45s}: memory analysis unavailable on this backend")

    fwd = _build_graph_fn(sym, is_train=False)
    report("forward_only (XLA buffer assignment)",
           lambda a, x: fwd(a, x, key)[0])

    fwd_t = _build_graph_fn(sym, is_train=True)

    def train_loss(a, x):
        outs, _ = fwd_t(a, x, key)
        return jnp.sum(outs[0])

    report("inplace+sharing train fwd+bwd (jax.grad)",
           lambda a, x: jax.grad(train_loss)(a, x))
    report("train fwd+bwd with remat (jax.checkpoint)",
           lambda a, x: jax.grad(jax.checkpoint(train_loss))(a, x))


if __name__ == "__main__":
    main()
