#!/usr/bin/env python
"""CIFAR-100-style advanced training knobs (notebook-style walkthrough).

Reference counterpart: example/notebooks/cifar-100.ipynb — a sub-Inception
network trained with the knobs that mattered for its state-of-the-art run:
``grad_scale`` on the loss, randomized crop/mirror augmentation, an epoch
learning-rate schedule, and round_batch handling for a dataset that does
not divide evenly by the batch size.

  python examples/notebooks/cifar100_advanced.py [--num-epochs 2]

Data: synthetic 100-class CIFAR-shaped JPEG RecordIO (offline-safe).
"""

import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)


def ConvFactory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0)):
    conv = mx.symbol.Convolution(data=data, num_filter=num_filter,
                                 kernel=kernel, stride=stride, pad=pad)
    bn = mx.symbol.BatchNorm(data=conv)
    return mx.symbol.Activation(data=bn, act_type="relu")


def build_net(num_classes=100, grad_scale=1.0):
    """Small sub-Inception; grad_scale rescales the loss gradient exactly as
    the reference's SoftmaxOutput(grad_scale=...) — used there to balance
    multi-loss setups and larger effective batches."""
    data = mx.symbol.Variable(name="data")
    c1 = ConvFactory(data, 64, (3, 3), pad=(1, 1))
    c2a = ConvFactory(c1, 32, (1, 1))
    c2b = ConvFactory(c1, 32, (3, 3), pad=(1, 1))
    cat = mx.symbol.Concat(c2a, c2b)
    down = mx.symbol.Pooling(data=cat, kernel=(3, 3), stride=(2, 2),
                             pad=(1, 1), pool_type="max")
    c3a = ConvFactory(down, 64, (1, 1))
    pool = mx.symbol.Pooling(data=c3a, kernel=(14, 14), pool_type="avg")
    fc = mx.symbol.FullyConnected(data=mx.symbol.Flatten(data=pool),
                                  num_hidden=num_classes)
    return mx.symbol.SoftmaxOutput(data=fc, name="softmax",
                                   grad_scale=grad_scale)


def make_rec(path, n, num_classes=100, seed=0):
    from mxnet_tpu import recordio as rio

    rng = np.random.RandomState(seed)
    protos = rng.randint(0, 255, (num_classes, 32, 32, 3), np.uint8)
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        cls = i % num_classes
        img = np.clip(protos[cls].astype(np.int16) +
                      rng.randint(-25, 25, (32, 32, 3), np.int16),
                      0, 255).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(cls), i, 0), img,
                             img_fmt=".jpg"))
    w.close()
    return path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="cifar100_")
    # 1500 records / batch 64 does not divide: round_batch wraps the tail
    # (reference BatchLoader semantics) so every batch is full-size —
    # essential for XLA's static shapes.
    train_rec = make_rec(os.path.join(tmp, "train.rec"), 1500, seed=0)
    val_rec = make_rec(os.path.join(tmp, "val.rec"), 500, seed=1)

    # Randomized crop + mirror is the augmentation the reference notebook
    # leaned on; 28x28 crops from 32x32 sources give ±4px translation.
    train_iter = mx.io.ImageRecordIter(
        path_imgrec=train_rec, data_shape=(3, 28, 28),
        batch_size=args.batch_size, rand_crop=True, rand_mirror=True,
        shuffle=True, round_batch=True,
        mean_r=128, mean_g=128, mean_b=128, scale=1.0 / 128)
    val_iter = mx.io.ImageRecordIter(
        path_imgrec=val_rec, data_shape=(3, 28, 28),
        batch_size=args.batch_size,
        mean_r=128, mean_g=128, mean_b=128, scale=1.0 / 128)

    # Epoch-factor learning-rate schedule: lr *= 0.9 each epoch.
    sched = mx.lr_scheduler.FactorScheduler(
        step=max(1, 1500 // args.batch_size), factor=0.9)

    model = mx.model.FeedForward(
        symbol=build_net(grad_scale=1.0), ctx=mx.cpu(),
        num_epoch=args.num_epochs, learning_rate=0.1, momentum=0.9,
        wd=0.0001, initializer=mx.init.Xavier(),
        lr_scheduler=sched)
    model.fit(X=train_iter, eval_data=val_iter, eval_metric="accuracy",
              batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    prob = model.predict(val_iter)
    assert prob.shape[1] == 100
    print("cifar100 advanced walkthrough complete; predicted", prob.shape)


if __name__ == "__main__":
    main()
