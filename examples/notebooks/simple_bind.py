#!/usr/bin/env python
"""Low-level training with ``simple_bind`` (notebook-style walkthrough).

Reference counterpart: example/notebooks/simple_bind.ipynb — bypassing the
FeedForward model wrapper to drive an Executor by hand: bind, initialize
weights directly, write a custom SGD update, and run the train loop
yourself. Useful when you need full control (custom updates, inspection of
every gradient, research schedules).

  python examples/notebooks/simple_bind.py

Data: sklearn's bundled scanned-digit set (offline-safe stand-in for the
notebook's MNIST download).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

# ----------------------------------------------------------------------------
# A one-hidden-layer BatchNorm MLP, exactly as in the notebook.

batch_size = 100

data = mx.sym.Variable("data")
fc1 = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=128)
bn1 = mx.sym.BatchNorm(data=fc1, name="bn1")
act1 = mx.sym.Activation(data=bn1, name="relu1", act_type="relu")
fc2 = mx.sym.FullyConnected(data=act1, name="fc2", num_hidden=10)
softmax = mx.sym.SoftmaxOutput(data=fc2, name="softmax")

# ----------------------------------------------------------------------------
# simple_bind allocates argument/gradient arrays from inferred shapes and
# returns a ready Executor. (FeedForward wraps exactly this machinery, plus
# a kvstore; at this level you own the update rule.)

executor = softmax.simple_bind(ctx=mx.cpu(), data=(batch_size, 64),
                               softmax_label=(batch_size,))

arg_arrays = executor.arg_arrays
grad_arrays = executor.grad_arrays
aux_arrays = executor.aux_arrays

# name -> array maps, in the argument order of the symbol
args = dict(zip(softmax.list_arguments(), arg_arrays))
grads = dict(zip(softmax.list_arguments(), grad_arrays))
print("bound executor:")
print(" args:", list(args))

# ----------------------------------------------------------------------------
# Initialize weights by writing into the bound arrays (the notebook's
# Init helper). NDArray slicing assignment works like numpy.

mx.random.seed(0)
for key, arr in args.items():
    if "weight" in key:
        arr[:] = mx.random.uniform(-0.07, 0.07, arr.shape)
    elif "gamma" in key:
        arr[:] = 1.0
    elif key.endswith(("bias", "beta")):
        arr[:] = 0.0


# ----------------------------------------------------------------------------
# A custom SGD update rule over the raw (weight, grad) pairs.

def SGD(key, weight, grad, lr=0.1, grad_norm=batch_size):
    if key.startswith("data") or key.startswith("softmax"):
        return
    weight[:] = weight - lr * (grad / grad_norm)


# ----------------------------------------------------------------------------
# Data: 8x8 scanned digits, flattened to 64 features, split train/val.

from sklearn.datasets import load_digits  # noqa: E402

digits = load_digits()
X = (digits.data / 16.0).astype(np.float32)
y = digits.target.astype(np.float32)
X_train, y_train = X[:1500], y[:1500]
X_val, y_val = X[1500:], y[1500:]


def Accuracy(label, pred_prob):
    pred = np.argmax(pred_prob, axis=1)
    return float(np.sum(label == pred)) / len(label)


# ----------------------------------------------------------------------------
# The hand-rolled train loop: copy a batch in, forward, backward, update.

num_round = 6
keys = softmax.list_arguments()
for epoch in range(num_round):
    train_acc = []
    for i in range(0, len(X_train) - batch_size + 1, batch_size):
        args["data"][:] = X_train[i:i + batch_size]
        args["softmax_label"][:] = y_train[i:i + batch_size]
        executor.forward(is_train=True)
        pred_prob = executor.outputs[0].asnumpy()
        executor.backward()
        for key in keys:
            SGD(key, args[key], grads[key])
        train_acc.append(Accuracy(y_train[i:i + batch_size], pred_prob))

    # validation: forward-only on the bound executor
    val_acc = []
    for i in range(0, len(X_val) - batch_size + 1, batch_size):
        args["data"][:] = X_val[i:i + batch_size]
        args["softmax_label"][:] = y_val[i:i + batch_size]
        executor.forward(is_train=False)
        val_acc.append(Accuracy(y_val[i:i + batch_size],
                                executor.outputs[0].asnumpy()))
    print("epoch %d: train acc %.3f, val acc %.3f"
          % (epoch, np.mean(train_acc), np.mean(val_acc)))

assert np.mean(val_acc) > 0.85, "low-level training failed to converge"
print("simple_bind training converged.")
