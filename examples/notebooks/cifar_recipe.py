#!/usr/bin/env python
"""CIFAR-10 recipe (notebook-style walkthrough).

Reference counterpart: example/notebooks/cifar-recipe.ipynb — the full
training recipe in one place: component factories, a simplified Inception
net, the augmented RecordIO data pipeline, FeedForward training with
callbacks, save/load (both pickle and the checkpoint format), prediction,
and internal-feature extraction via ``get_internals``.

  python examples/notebooks/cifar_recipe.py [--num-epochs 2]

Data: synthetic CIFAR-shaped JPEG RecordIO shards generated on the fly
(class-coded prototypes + noise; offline-safe), same scheme as
examples/cifar10/train_cifar10.py.
"""

import argparse
import logging
import os
import pickle
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)


# ----------------------------------------------------------------------------
# Component factories (same idea as composite_symbol.py, smaller net).

def ConvFactory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                act_type="relu"):
    conv = mx.symbol.Convolution(data=data, num_filter=num_filter,
                                 kernel=kernel, stride=stride, pad=pad)
    bn = mx.symbol.BatchNorm(data=conv)
    return mx.symbol.Activation(data=bn, act_type=act_type)


def DownsampleFactory(data, ch_3x3):
    conv = ConvFactory(data=data, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       num_filter=ch_3x3)
    pool = mx.symbol.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                             pad=(1, 1), pool_type="max")
    return mx.symbol.Concat(conv, pool)


def SimpleFactory(data, ch_1x1, ch_3x3):
    conv1x1 = ConvFactory(data=data, kernel=(1, 1), pad=(0, 0),
                          num_filter=ch_1x1)
    conv3x3 = ConvFactory(data=data, kernel=(3, 3), pad=(1, 1),
                          num_filter=ch_3x3)
    return mx.symbol.Concat(conv1x1, conv3x3)


def build_net(num_classes=10):
    data = mx.symbol.Variable(name="data")
    conv1 = ConvFactory(data=data, kernel=(3, 3), pad=(1, 1), num_filter=32)
    in3a = SimpleFactory(conv1, 16, 16)
    in3b = SimpleFactory(in3a, 16, 16)
    in3c = DownsampleFactory(in3b, 32)
    in4a = SimpleFactory(in3c, 32, 32)
    in4b = DownsampleFactory(in4a, 32)
    in5a = SimpleFactory(in4b, 32, 32)
    pool = mx.symbol.Pooling(data=in5a, global_pool=True, kernel=(7, 7), pool_type="avg",
                             name="global_avg")
    flatten = mx.symbol.Flatten(data=pool, name="flatten")
    fc = mx.symbol.FullyConnected(data=flatten, num_hidden=num_classes,
                                  name="fc")
    return mx.symbol.SoftmaxOutput(data=fc, name="softmax")


# ----------------------------------------------------------------------------
# Synthetic CIFAR-shaped RecordIO data (no network egress in this sandbox).

def make_synthetic_rec(path, n, num_classes=10, seed=0):
    from mxnet_tpu import recordio as rio

    rng = np.random.RandomState(seed)
    protos = rng.randint(0, 255, (num_classes, 32, 32, 3), np.uint8)
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        cls = i % num_classes
        noise = rng.randint(-30, 30, (32, 32, 3), np.int16)
        img = np.clip(protos[cls].astype(np.int16) + noise, 0,
                      255).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(cls), i, 0), img,
                             img_fmt=".jpg"))
    w.close()
    return path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="cifar_recipe_")
    train_rec = make_synthetic_rec(os.path.join(tmp, "train.rec"), 1536,
                                   seed=0)
    val_rec = make_synthetic_rec(os.path.join(tmp, "val.rec"), 512, seed=1)

    # The augmented train pipeline: random crop + mirror, mean subtraction.
    train_iter = mx.io.ImageRecordIter(
        path_imgrec=train_rec, data_shape=(3, 28, 28),
        batch_size=args.batch_size, rand_crop=True, rand_mirror=True,
        shuffle=True, mean_r=128, mean_g=128, mean_b=128, scale=1.0 / 128)
    val_iter = mx.io.ImageRecordIter(
        path_imgrec=val_rec, data_shape=(3, 28, 28),
        batch_size=args.batch_size, rand_crop=False, rand_mirror=False,
        mean_r=128, mean_g=128, mean_b=128, scale=1.0 / 128)

    softmax = build_net()
    model = mx.model.FeedForward(
        symbol=softmax, ctx=mx.cpu(), num_epoch=args.num_epochs,
        learning_rate=0.05, momentum=0.9, wd=0.0001,
        initializer=mx.init.Uniform(0.07))

    # Speedometer prints samples/sec every 10 batches, as in the notebook.
    model.fit(X=train_iter, eval_data=val_iter, eval_metric="accuracy",
              batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    # ------------------------------------------------------------------
    # Saving and loading. pickle works on the whole model; save/load uses
    # the prefix-symbol.json + prefix-%04d.params checkpoint format (the
    # recommended path — it is readable from any process, S3/FS URI, etc).
    smodel = pickle.dumps(model)
    model2 = pickle.loads(smodel)

    prefix = os.path.join(tmp, "cifar")
    model.save(prefix)
    model3 = mx.model.FeedForward.load(prefix, model.num_epoch)

    # Both restored models predict identically:
    prob2 = model2.predict(val_iter)
    prob3 = model3.predict(val_iter)
    assert np.allclose(prob2, prob3, atol=1e-5)
    pred = np.argmax(prob3, axis=1)
    labels = np.concatenate(
        [b.label[0].asnumpy() for b in iter(val_iter)])[:len(pred)]
    acc = float(np.mean(pred == labels))
    print("restored-model val accuracy: %.3f" % acc)

    # ------------------------------------------------------------------
    # Internal-feature extraction: any internal output is itself a symbol
    # that can head a forward-only model (transfer-learning workflow).
    internals = softmax.get_internals()
    print("some internals:", internals.list_outputs()[-6:])
    fea_symbol = internals["global_avg_output"]
    feature_extractor = mx.model.FeedForward(
        ctx=mx.cpu(), symbol=fea_symbol, arg_params=model.arg_params,
        aux_params=model.aux_params, allow_extra_params=True)
    features = feature_extractor.predict(val_iter)
    print("extracted feature maps:", features.shape)
    assert features.shape[1:] == (64, 1, 1)
    print("cifar recipe complete.")


if __name__ == "__main__":
    main()
