#!/usr/bin/env python
"""Composing symbols into components (notebook-style walkthrough).

Reference counterpart: example/notebooks/composite_symbol.ipynb — building
an Inception network from small reusable symbol factories and visualizing
the pieces. Run it top to bottom:

  python examples/notebooks/composite_symbol.py

Each section below mirrors a notebook cell; print output stands in for
cell output.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx

# ----------------------------------------------------------------------------
# For a complex network such as Inception, composing single symbols by hand
# is painful. Small "component factories" make it mechanical: each factory
# takes the previous symbol and returns a bigger composite.


def ConvFactory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                name=None, suffix=''):
    conv = mx.symbol.Convolution(data=data, num_filter=num_filter,
                                 kernel=kernel, stride=stride, pad=pad,
                                 name='conv_%s%s' % (name, suffix))
    bn = mx.symbol.BatchNorm(data=conv, name='bn_%s%s' % (name, suffix))
    act = mx.symbol.Activation(data=bn, act_type='relu',
                               name='relu_%s%s' % (name, suffix))
    return act


# A factory is itself composable — visualize one in isolation by feeding it
# a free Variable:
prev = mx.symbol.Variable(name="previous_output")
conv_comp = ConvFactory(data=prev, num_filter=64, kernel=(7, 7),
                        stride=(2, 2))
print("one ConvFactory component:")
print(" arguments:", conv_comp.list_arguments())


# ----------------------------------------------------------------------------
# Inception building blocks: factory A (1x1 / 3x3 / double-3x3 / pool
# towers concatenated on channels) and factory B (stride-2 downsampling).

def InceptionFactoryA(data, n1x1, n3x3r, n3x3, nd3x3r, nd3x3, proj, name):
    c1x1 = ConvFactory(data, n1x1, (1, 1), name='%s_1x1' % name)
    c3x3r = ConvFactory(data, n3x3r, (1, 1), name='%s_3x3' % name, suffix='_reduce')
    c3x3 = ConvFactory(c3x3r, n3x3, (3, 3), pad=(1, 1), name='%s_3x3' % name)
    cd3r = ConvFactory(data, nd3x3r, (1, 1), name='%s_d3x3' % name, suffix='_reduce')
    cd3a = ConvFactory(cd3r, nd3x3, (3, 3), pad=(1, 1), name='%s_d3x3_0' % name)
    cd3b = ConvFactory(cd3a, nd3x3, (3, 3), pad=(1, 1), name='%s_d3x3_1' % name)
    pool = mx.symbol.Pooling(data=data, kernel=(3, 3), stride=(1, 1),
                             pad=(1, 1), pool_type='avg',
                             name='avg_pool_%s_pool' % name)
    cproj = ConvFactory(pool, proj, (1, 1), name='%s_proj' % name)
    return mx.symbol.Concat(c1x1, c3x3, cd3b, cproj,
                            name='ch_concat_%s_chconcat' % name)


def InceptionFactoryB(data, n3x3r, n3x3, nd3x3r, nd3x3, name):
    c3x3r = ConvFactory(data, n3x3r, (1, 1), name='%s_3x3' % name, suffix='_reduce')
    c3x3 = ConvFactory(c3x3r, n3x3, (3, 3), pad=(1, 1), stride=(2, 2),
                       name='%s_3x3' % name)
    cd3r = ConvFactory(data, nd3x3r, (1, 1), name='%s_d3x3' % name, suffix='_reduce')
    cd3a = ConvFactory(cd3r, nd3x3, (3, 3), pad=(1, 1), name='%s_d3x3_0' % name)
    cd3b = ConvFactory(cd3a, nd3x3, (3, 3), pad=(1, 1), stride=(2, 2),
                       name='%s_d3x3_1' % name)
    # NOTE: our Pooling uses floor output-shape rounding (XLA reduce_window
    # semantics); the reference's v0.5 pooling rounded up. pad=(1,1) keeps
    # the tower shapes aligned under floor rounding.
    pool = mx.symbol.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                             pad=(1, 1), pool_type='max',
                             name='max_pool_%s_pool' % name)
    return mx.symbol.Concat(c3x3, cd3b, pool,
                            name='ch_concat_%s_chconcat' % name)


# ----------------------------------------------------------------------------
# The full network is now a linear chain of factory calls.

data = mx.symbol.Variable(name="data")
# stage 1
conv1 = ConvFactory(data=data, num_filter=64, kernel=(7, 7), stride=(2, 2),
                    pad=(3, 3), name='conv1')
pool1 = mx.symbol.Pooling(data=conv1, kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type='max', name='pool1')
# stage 2
conv2red = ConvFactory(data=pool1, num_filter=64, kernel=(1, 1), name='conv2red')
conv2 = ConvFactory(data=conv2red, num_filter=192, kernel=(3, 3), pad=(1, 1),
                    name='conv2')
pool2 = mx.symbol.Pooling(data=conv2, kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type='max', name='pool2')
# stage 3
in3a = InceptionFactoryA(pool2, 64, 64, 64, 64, 96, 32, '3a')
in3b = InceptionFactoryA(in3a, 64, 64, 96, 64, 96, 64, '3b')
in3c = InceptionFactoryB(in3b, 128, 160, 64, 96, '3c')
# head
avg = mx.symbol.Pooling(data=in3c, kernel=(14, 14), stride=(1, 1),
                        pool_type='avg', name='global_pool')
flatten = mx.symbol.Flatten(data=avg, name='flatten')
fc1 = mx.symbol.FullyConnected(data=flatten, num_hidden=1000, name='fc1')
softmax = mx.symbol.SoftmaxOutput(data=fc1, name='softmax')

print("\nfull composite network:")
print(" #arguments:", len(softmax.list_arguments()))

# Shape inference flows through the whole composite:
arg_shapes, out_shapes, aux_shapes = softmax.infer_shape(
    data=(2, 3, 224, 224))
print(" output shape for 2x3x224x224 input:", out_shapes[0])

# Graphviz rendering (writes a .dot you can render with `dot -Tpng`):
dot = mx.viz.plot_network(softmax, shape={"data": (2, 3, 224, 224)},
                          save_path="/tmp/composite_symbol.dot")
print(" graphviz dot written to /tmp/composite_symbol.dot")

# A symbol round-trips through JSON (checkpoint format parity):
js = softmax.tojson()
back = mx.symbol.load_json(js)
assert back.list_arguments() == softmax.list_arguments()
print(" JSON round-trip OK (%d bytes)" % len(js))
