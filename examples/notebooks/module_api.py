#!/usr/bin/env python
"""Module API walkthrough (notebook-style; successor-API counterpart of
simple_bind.py — the BASELINE north star's module.fit()).

Three levels of control over one model, all the same machinery:

1. high:   mod.fit(train_iter)
2. middle: bind / init_params / init_optimizer + forward/backward/update
3. low:    simple_bind executors (see simple_bind.py)

  python examples/notebooks/module_api.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx


def dataset(n=512, dim=16, seed=7):
    rng = np.random.RandomState(seed)
    X = np.concatenate([rng.randn(n // 2, dim) + 1.0,
                        rng.randn(n // 2, dim) - 1.0]).astype(np.float32)
    y = np.concatenate([np.ones(n // 2), np.zeros(n // 2)]).astype(np.float32)
    p = rng.permutation(n)
    return X[p], y[p]


def net():
    s = mx.symbol.Variable("data")
    s = mx.symbol.FullyConnected(data=s, num_hidden=32, name="fc1")
    s = mx.symbol.Activation(data=s, act_type="relu", name="relu1")
    s = mx.symbol.FullyConnected(data=s, num_hidden=2, name="fc2")
    return mx.symbol.SoftmaxOutput(data=s, name="softmax")


def main():
    X, y = dataset()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    val = mx.io.NDArrayIter(X, y, batch_size=32)

    # ---- level 1: one call --------------------------------------------------
    mod = mx.mod.Module(net())
    mod.fit(train, eval_data=val, num_epoch=4,
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1 / 32.0})
    name, acc = mod.score(val)
    print(f"fit(): {name}={acc:.3f}")
    assert acc > 0.95

    # ---- level 2: explicit lifecycle ---------------------------------------
    mod2 = mx.mod.Module(net())
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod2.init_params(mx.init.Xavier())
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9,
                                          "rescale_grad": 1 / 32.0})
    metric = mx.metric.create("accuracy")
    for epoch in range(4):
        train.reset()
        metric.reset()
        for batch in train:
            mod2.forward(batch, is_train=True)   # you own the step
            mod2.backward()
            mod2.update()
            mod2.update_metric(metric, batch.label,
                               pad=getattr(batch, "pad", 0))
        print(f"epoch {epoch}: train {metric.get()[1]:.3f}")
    assert metric.get()[1] > 0.95

    # ---- checkpoints interoperate with FeedForward --------------------------
    import tempfile

    prefix = os.path.join(tempfile.mkdtemp(), "mod")
    mod.save_checkpoint(prefix, 4)
    ff = mx.model.FeedForward.load(prefix, 4)
    agree = (ff.predict(X).argmax(1) == mod.predict(val).argmax(1)).mean()
    print(f"FeedForward.load on the Module checkpoint agrees: {agree:.3f}")
    assert agree > 0.99


if __name__ == "__main__":
    main()
