#!/usr/bin/env python
"""Long-context LM training over a sequence-parallel mesh (SURVEY.md §5:
long-context is a first-class capability the reference lacks entirely —
its longest sequences are PTB bucket lengths, example/rnn/lstm_ptb.py).

The sequence axis is sharded over the mesh's ``sp`` dimension: every
attention layer runs RING attention (mxnet_tpu/parallel/sequence.py) —
each device holds seq/sp tokens and rotates K/V blocks around the ring
via collective-permute, so the sp-fold longer context costs sp-fold more
devices, not sp²-fold more memory on one. With ``--flash`` the rank-local
block runs the online-softmax flash kernel (jnp body everywhere; the
pallas TPU kernel powers the same schedule on hardware).

Synthetic copy-task data (target t = token t-1) keeps the example
self-contained: the task is unlearnable without cross-position attention,
so convergence (4.7 at init -> ~1, vs 4.16 for a uniform predictor) proves the ring path trains — the
gradient flows backward through the collective-permute rotations, not
just the forward (tests/test_parallel.py checks forward numerics; this
checks learning). Defaults converge in ~600 steps with the model's plain
SGD-momentum step; longer --seq needs gentler schedules than the fixed-lr
example step provides (measured: seq 128 -> 0.07, seq 256 -> 3.6 slow,
seq 512 stalls — an optimizer property, identical with and without sp).

  # 8 virtual devices: dp=2 x sp=4, each device holds seq/4 tokens
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/long_context/train_long_lm.py --dp 2 --sp 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--remat", action="store_true",
                    help="jax.checkpoint per decoder layer: saved "
                         "activations shrink to the layer boundaries, "
                         "letting --seq scale past the no-remat HBM limit")
    ap.add_argument("--flash", action="store_true",
                    help="flash formulation for the rank-local block")
    ap.add_argument("--cpu", action="store_true", default=True)
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.models.transformer import (TransformerLM,
                                              transformer_lm_config)
    from mxnet_tpu.parallel import make_mesh

    n = args.dp * args.sp
    if len(jax.devices()) < n:
        raise SystemExit(
            f"need {n} devices; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n}")
    mesh = make_mesh(dp=args.dp, sp=args.sp, devices=jax.devices()[:n])

    import jax.numpy as jnp

    cfg = transformer_lm_config(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=4,
        n_layers=args.layers, max_len=args.seq, dtype=jnp.float32,
        attn_impl="flash" if args.flash else "auto", remat=args.remat)
    model = TransformerLM(cfg)
    params, moms = model.init_sharded(mesh, seed=0)
    step = model.make_train_step(mesh, lr=0.1)

    rng = np.random.RandomState(0)
    batch = 2 * args.dp

    def make_batch():
        toks = rng.randint(1, args.vocab, (batch, args.seq)).astype(np.int32)
        # copy task: target t = input t-1 (learnable by attention alone)
        tgt = np.concatenate([toks[:, :1], toks[:, :-1]], axis=1)
        return toks, tgt.astype(np.int32)

    first = last = None
    for i in range(args.steps):
        toks, tgt = make_batch()
        params, moms, loss = step(params, moms, toks, tgt)
        if i == 0:
            first = float(loss)
        if i % 20 == 0:
            print(f"step {i:4d}  loss {float(loss):.4f}  "
                  f"(seq {args.seq} over sp={args.sp}: "
                  f"{args.seq // args.sp} tokens/device)", flush=True)
    last = float(loss)
    print(f"long-context LM: loss {first:.3f} -> {last:.3f} over "
          f"{args.steps} steps, ring attention sp={args.sp}")
    # uniform over the vocab is ln(64)=4.16: well below proves the
    # attention layers learned the one-position shift across shard
    # boundaries (the task is unlearnable without cross-position attention)
    assert last < 1.5, (first, last)


if __name__ == "__main__":
    main()
