#!/usr/bin/env python
"""CIFAR-10 Inception-BN training (reference: example/cifar10/cifar10.py,
the 'local' kvstore baseline config in BASELINE.md).

Data: a RecordIO file packed by tools/im2rec.py (--data-rec), or synthetic
CIFAR-shaped JPEG records generated on the fly (default, offline-safe).

  python examples/cifar10/train_cifar10.py --num-epochs 2
"""

import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def make_synthetic_rec(path, n=2048, num_classes=10, seed=0):
    from mxnet_tpu import recordio as rio

    rng = np.random.RandomState(seed)
    protos = rng.randint(0, 255, (num_classes, 32, 32, 3), np.uint8)
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        cls = i % num_classes
        noise = rng.randint(-30, 30, (32, 32, 3), np.int16)
        img = np.clip(protos[cls].astype(np.int16) + noise, 0, 255).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(cls), i, 0), img,
                             img_fmt=".jpg"))
    w.close()
    return path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-rec", default=None)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--num-devices", type=int, default=1)
    ap.add_argument("--bf16", action="store_true", help="bfloat16 compute")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu.models import inception_bn_cifar

    logging.basicConfig(level=logging.INFO)
    rec = args.data_rec
    if rec is None:
        rec = os.path.join(tempfile.gettempdir(), "cifar_synth.rec")
        if not os.path.exists(rec):
            logging.info("generating synthetic CIFAR rec at %s", rec)
            make_synthetic_rec(rec)

    train = mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 28, 28), batch_size=args.batch_size,
        rand_crop=True, rand_mirror=True, shuffle=True,
        mean_r=128, mean_g=128, mean_b=128, scale=1 / 128.0)

    net = inception_bn_cifar()
    ctx = [mx.tpu(i) for i in range(args.num_devices)]
    model = mx.FeedForward(
        net, ctx=ctx, num_epoch=args.num_epochs,
        initializer=mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                   magnitude=2),
        compute_dtype=jnp.bfloat16 if args.bf16 else None,
        lr=args.lr, momentum=0.9, wd=1e-4)
    model.fit(train, kvstore=args.kv_store,
              batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))


if __name__ == "__main__":
    main()
