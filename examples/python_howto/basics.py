"""Framework walkthrough (rewrite of the reference example/python-howto:
symbol composition, shape inference, binding, the imperative NDArray layer,
and saving/loading — each step printed).

Run: python examples/python_howto/basics.py
"""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import symbol as sym


def main():
    # --- 1. imperative NDArray ------------------------------------------------
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = nd.ones((2, 3))
    c = a * 2 + b
    print("NDArray math:\n", c.asnumpy())

    # --- 2. symbolic composition ---------------------------------------------
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=8)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=3)
    out = sym.SoftmaxOutput(data=net, name="softmax")
    print("arguments:", out.list_arguments())
    print("outputs:", out.list_outputs())

    # --- 3. shape inference ---------------------------------------------------
    arg_shapes, out_shapes, _ = out.infer_shape(data=(4, 10))
    print("inferred arg shapes:", dict(zip(out.list_arguments(), arg_shapes)))
    print("inferred out shapes:", out_shapes)

    # --- 4. bind + forward + backward ----------------------------------------
    exe = out.simple_bind(ctx=mx.cpu(), data=(4, 10), softmax_label=(4,))
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            nd.array(rng.randn(*arr.shape).astype(np.float32) * 0.1).copyto(arr)
    x = rng.randn(4, 10).astype(np.float32)
    y = np.array([0, 1, 2, 0], np.float32)
    probs = exe.forward(is_train=True, data=x, softmax_label=y)[0]
    print("softmax row sums:", probs.asnumpy().sum(axis=1))
    exe.backward()
    print("dL/d(fc1_weight) shape:", exe.grad_dict["fc1_weight"].shape)

    # --- 5. graph introspection + save/load ----------------------------------
    print(out.debug_str().splitlines()[0])
    js = out.tojson()
    out2 = sym.load_json(js)
    assert out2.list_arguments() == out.list_arguments()
    print("symbol JSON roundtrip ok:", len(js), "bytes")


if __name__ == "__main__":
    main()
