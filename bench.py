"""Headline benchmark: ResNet-50 ImageNet training throughput per chip.

Prints ONE JSON line:
  {"metric": "resnet50_imagenet_train_images_per_sec_per_chip",
   "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: the reference (dawdle/mxnet v0.5) publishes no ResNet-50 number
(the model postdates it). The closest published anchor in the same
FLOP class (~4 GFLOPs/image) is Inception-BN at 97 img/s on 1x GTX 980 with
cuDNN v3 (reference example/imagenet/README.md:40, mirrored in BASELINE.md),
so vs_baseline = value / 97.0 — "how much faster than the reference's best
same-class single-device training throughput".

Method: fused train step (forward + backward + SGD-momentum update in one
donated XLA program), NHWC activations (channels on the MXU lane dimension;
weights stay OIHW for checkpoint parity), bf16 compute / f32 master params,
custom-VJP fused BatchNorm(+add)+ReLU kernels (executor fusion passes),
1x1 convs as channel matmuls, synthetic on-device data (the input pipeline
is benchmarked separately; the reference's numbers are likewise decode-bound
only beyond 3000 img/s, README:5). Warmup 2 steps (compile), then timed
steps with a hard device sync at the end.

Perf envelope on the round-2 rig (one v5e-class chip via the axon tunnel,
measured matmul peak ~120-150 TF/s): the 103 ms b256 step profiles as
~50 ms conv+BN-stats fusions (~60 TF/s effective — ResNet's small-channel
conv mix) and ~45 ms backward elementwise / optimizer fusions. Alternatives
measured SLOWER on this backend and reverted (see ops/nn.py notes):
MXU ones-matmul stats (strength-reduced back to reduces; tall-skinny dots
lower to degenerate convs), optimization_barrier splits, flat-buffer
optimizer state, batch 512/1024 (OOM at 1024). A conv-only (no-BN) variant
of the same stack lowers to a 6x SLOWER program — the conv algorithm
choices on this backend are volatile, and the shipped formulation is the
fastest found. ~25x the reference's best same-class published number.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


NOMINAL_BF16_TFLOPS = 197.0  # TPU v5e peak per chip (public spec)


def _data_shape(batch_size, layout):
    return (batch_size, 224, 224, 3) if layout == "NHWC" else \
        (batch_size, 3, 224, 224)


def probe_backend_init(timeout_s=None, tries=3):
    """Init-stage tunnel guard. The r03/r04 driver captures died INSIDE
    make_c_api_client — before any compile — so retrying ops (with_retries)
    or warming the compile cache cannot save a capture whose backend never
    comes up. A wedged in-process init can only be abandoned by killing the
    process, so the probe runs `jax.devices()` in a SUBPROCESS with a hard
    timeout and backs off between attempts; only once a probe succeeds does
    the main process commit to its own (now very likely healthy) init.

    Returns True when a probe succeeded; False when every attempt timed out
    or crashed (callers should exit rc=3 immediately instead of eating the
    driver's whole timeout budget)."""
    import subprocess

    if timeout_s is None:
        timeout_s = int(os.environ.get("MXTPU_INIT_PROBE_TIMEOUT_SEC", "150"))
    code = ("import jax, time; t0=time.time(); d=jax.devices(); "
            "print('probe ok:', d[0].platform, len(d), "
            "'init_s=%.1f' % (time.time()-t0))")
    delays = [30, 90]
    for attempt in range(tries):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               timeout=timeout_s, capture_output=True,
                               text=True)
            if r.returncode == 0:
                print(f"backend init probe: {r.stdout.strip()} "
                      f"(attempt {attempt + 1})", file=sys.stderr)
                return True
            detail = (r.stderr or r.stdout).strip().splitlines()
            detail = detail[-1][:160] if detail else f"rc={r.returncode}"
            print(f"backend init probe failed (attempt {attempt + 1}/"
                  f"{tries}): {detail}", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"backend init probe TIMED OUT after {timeout_s}s "
                  f"(attempt {attempt + 1}/{tries}) — tunnel wedged at "
                  "client init (the r03/r04 failure mode)", file=sys.stderr)
        if attempt < tries - 1:
            delay = delays[min(attempt, len(delays) - 1)]
            print(f"backing off {delay}s before re-probing", file=sys.stderr)
            time.sleep(delay)
    return False


def with_retries(fn, tries=4, what="tpu op"):
    """Retry transient tunnel failures (the round-2 bench died rc=1 on a
    wedged compile service; UNAVAILABLE from the axon backend is retryable)."""
    delays = [20, 60, 120]
    for attempt in range(tries):
        try:
            return fn()
        except RuntimeError as e:  # includes jax.errors.JaxRuntimeError
            msg = str(e)
            retryable = "UNAVAILABLE" in msg or "Unable to initialize" in msg
            if not retryable or attempt == tries - 1:
                raise
            delay = delays[min(attempt, len(delays) - 1)]
            print(f"{what}: transient backend error, retrying in {delay}s "
                  f"({attempt + 1}/{tries - 1}): {msg.splitlines()[0][:120]}",
                  file=sys.stderr)
            time.sleep(delay)


def _publish(result, filename, smoke=False):
    """Single exit for every bench headline (ISSUE 20): the per-bench
    JSON artifact (full runs), a kind="bench" RunRecord in the cross-run
    ledger when MXNET_TPU_LEDGER_DIR is set, and the combined
    BENCH_LEDGER_r20.json trajectory. telemetry.ledger.publish_bench is
    the one writer — no hand-rolled per-bench dumps (mxlint MX316)."""
    from mxnet_tpu.telemetry import ledger

    out = ledger.publish_bench(
        result, filename=filename,
        bench_dir=os.path.dirname(os.path.abspath(__file__)), smoke=smoke)
    if out["bench_path"]:
        print(f"wrote {out['bench_path']}", file=sys.stderr)
    return out


def measured_matmul_peak_tflops(n=8192, iters=16, samples=3):
    """This chip's achievable bf16 matmul rate, measured through the same
    tunnel/timing path as the headline number. Slope method: the loop runs
    in-device via fori_loop and the per-iter cost is the slope between a
    short and a long run, cancelling constant dispatch+fence overhead.
    n=8192 (1.1 TFLOP/iter) keeps the timed region hundreds of ms so
    tunnel-latency jitter (several ms) stays <1%; the median of several
    slope samples guards against one-off network stalls."""
    import jax
    import jax.numpy as jnp

    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)

    @jax.jit
    def run(a, k):
        def body(i, x):
            return (jax.lax.dot_general(
                x, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * 1e-3).astype(jnp.bfloat16)
        return jax.lax.fori_loop(0, k, body, a)

    k1, k2 = iters, iters * 4
    a = run(a, k1)  # compile + warm
    float(jnp.sum(a))
    rates = []
    for _ in range(samples):
        t0 = time.perf_counter()
        a = run(a, k1)
        float(jnp.sum(a))
        t1 = time.perf_counter()
        a = run(a, k2)
        float(jnp.sum(a))
        t2 = time.perf_counter()
        per_iter = ((t2 - t1) - (t1 - t0)) / (k2 - k1)
        rates.append(2 * n ** 3 / per_iter / 1e12)
    rates.sort()
    return rates[len(rates) // 2]


def build_train_step(batch_size, lr=0.1, momentum=0.9, layout="NHWC",
                     model="resnet50"):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.executor import _build_graph_fn
    from mxnet_tpu.models import resnet50
    from mxnet_tpu.models.inception import inception_bn

    if model == "inception_bn":
        # the BASELINE anchor architecture itself (97 img/s, 1x GTX 980,
        # example/imagenet/README.md:40) — same net, our chip
        sym = inception_bn(num_classes=1000, layout=layout)
    else:
        sym = resnet50(num_classes=1000, layout=layout)
    input_shapes = {"data": _data_shape(batch_size, layout),
                    "softmax_label": (batch_size,)}
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()

    rng = np.random.RandomState(0)
    params = {}
    for name, shape in zip(arg_names, arg_shapes):
        if name in input_shapes:
            continue
        scale = 0.1 if name.endswith(("gamma", "bias", "beta")) else \
            float(np.sqrt(2.0 / max(1, int(np.prod(shape[1:])))))
        if name.endswith("gamma"):
            params[name] = jnp.ones(shape, jnp.float32)
        elif name.endswith(("beta", "bias")):
            params[name] = jnp.zeros(shape, jnp.float32)
        else:
            params[name] = jnp.asarray((rng.randn(*shape) * scale).astype(np.float32))
    aux = {name: (jnp.ones(s, jnp.float32) if name.endswith("var")
                  else jnp.zeros(s, jnp.float32))
           for name, s in zip(aux_names, aux_shapes)}
    moms = {k: jnp.zeros_like(v) for k, v in params.items()}

    graph_fn = _build_graph_fn(sym, is_train=True)
    zero_key = jnp.zeros((2,), jnp.uint32)
    rescale = 1.0 / batch_size

    def step(params, moms, aux, data, label):
        def loss_fn(p):
            p_c = {k: v.astype(jnp.bfloat16) for k, v in p.items()}
            outs, new_aux = graph_fn(
                {**p_c, "data": data.astype(jnp.bfloat16), "softmax_label": label},
                aux, zero_key)
            return jnp.sum(outs[0].astype(jnp.float32)), new_aux

        grads, new_aux = jax.grad(loss_fn, has_aux=True)(params)
        new_moms = {k: momentum * moms[k] + grads[k] * rescale for k in params}
        new_params = {k: params[k] - lr * new_moms[k] for k in params}
        return new_params, new_moms, new_aux

    jitted = jax.jit(step, donate_argnums=(0, 1, 2))
    return jitted, params, moms, aux


def build_resnet50_train_step(batch_size, lr=0.1, momentum=0.9,
                              layout="NHWC"):
    """Back-compat alias (tools/bench_roofline.py imports this name)."""
    return build_train_step(batch_size, lr=lr, momentum=momentum,
                            layout=layout, model="resnet50")


def ensure_recordio(path, n=1024, size=256, seed=0):
    """Synthetic ImageNet-like RecordIO shard: n JPEG records of size²
    smooth-gradient images (JPEG-compressible, like the reference's test
    data), cached across runs."""
    import os

    if os.path.exists(path):
        return path
    from mxnet_tpu import recordio as rio

    rng = np.random.RandomState(seed)
    w = rio.MXRecordIO(path + ".tmp", "w")
    yy, xx = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
    for i in range(n):
        f = rng.uniform(0.5, 4.0, 3)
        ph = rng.uniform(0, np.pi, 3)
        img = np.stack([
            127 + 120 * np.sin(2 * np.pi * f[c] * (yy + xx) / size + ph[c])
            for c in range(3)], axis=-1).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i % 1000), i, 0), img,
                             quality=90, img_fmt=".jpg"))
    w.close()
    os.rename(path + ".tmp", path)
    return path


def _make_iter(args, layout, output_dtype="float32"):
    from mxnet_tpu import io as mio

    path = ensure_recordio(args.recordio, n=args.num_images)
    return mio.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 224, 224),
        batch_size=args.batch_size, shuffle=True, rand_crop=True,
        rand_mirror=True, resize=256, layout=layout,
        prefetch_buffer=8, seed=7, output_dtype=output_dtype)


def run_pipeline_bench(args):
    """Input-pipeline-only throughput (no device in the loop): RecordIO read
    -> JPEG decode -> resize-short 256 -> rand-crop 224 -> mirror -> batch.
    Reference anchor: 3000 img/s from HDD on a multicore Xeon
    (example/imagenet/README.md:5); this host has os.cpu_count() cores and
    the native pipeline scales per-core."""
    import os

    it = _make_iter(args, args.layout)
    n_batches = 0
    for _ in it:  # epoch 1: warm page cache / thread spin-up
        n_batches += 1
    t0 = time.perf_counter()
    it.reset()
    for _ in it:
        pass
    dt = time.perf_counter() - t0
    ips = n_batches * args.batch_size / dt
    print(json.dumps({
        "metric": "imagerecorditer_pipeline_images_per_sec",
        "value": round(ips, 2), "unit": "images/sec",
        "host_cores": os.cpu_count(),
        "native": it._native is not None,
        "vs_baseline": round(ips / 3000.0, 3),
    }))


def run_io_bench(args):
    """End-to-end FeedForward.fit fed by ImageRecordIter on the real chip.
    Reports the steady-state epoch throughput (epochs after the first, so
    compile time is excluded). With prefetch overlap this should approach
    min(pipeline img/s, transfer img/s, synthetic train img/s).

    Batches cross to the device as raw uint8 (output_dtype='uint8', the
    standard TPU input path — 4x less wire traffic); FeedForward's
    compute_dtype casts them to bf16 in-graph. Rig context matters when
    reading the number: this benchmark host has a single CPU core (decode
    ~380 img/s/core) and reaches the chip through a ~19 MB/s tunnel
    (~130 img/s for uint8 batches); a real TPU host (dozens of cores, PCIe)
    is bound by neither. The JSON includes both rig limits so the result is
    interpretable."""
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet50

    # feed-only throughput first (drain one pass, no training): the
    # VERDICT-r4 overlap arithmetic needs max(feed, compute) measured in
    # the same process — an overlapped epoch should cost ~max of the two,
    # a serial one their sum (see _AsyncDeviceFeed / tests/test_overlap.py).
    # Iterator construction stays OUTSIDE the clock: _make_iter may
    # synthesize the RecordIO shard on a fresh host (ensure_recordio), and
    # timing that would understate the decode rate by an order of magnitude.
    feed_iter = _make_iter(args, args.layout, output_dtype="uint8")
    t0 = time.perf_counter()
    n_feed = sum(b.data[0].shape[0] for b in feed_iter)
    feed_ips = n_feed / (time.perf_counter() - t0)
    print(f"feed-only: {feed_ips:.0f} img/s", file=sys.stderr)

    it = _make_iter(args, args.layout, output_dtype="uint8")
    model = mx.model.FeedForward(
        resnet50(num_classes=1000, layout=args.layout), ctx=mx.tpu(),
        num_epoch=args.epochs, learning_rate=0.01, momentum=0.9,
        initializer=mx.init.Xavier(), compute_dtype=jnp.bfloat16)
    marks = [time.perf_counter()]

    def at_epoch_end(epoch, symbol, arg_params, aux_params):
        marks.append(time.perf_counter())

    model.fit(it, epoch_end_callback=at_epoch_end,
              batch_size=args.batch_size)
    import os

    n_batches = (args.num_images + args.batch_size - 1) // args.batch_size
    steady = marks[2:]  # skip epoch 1 (compile) boundary
    dt = (steady[-1] - marks[1]) / (len(steady)) if steady else float("nan")
    ips = n_batches * args.batch_size / dt
    print(json.dumps({
        "metric": "resnet50_io_fed_fit_images_per_sec_per_chip",
        "value": round(ips, 2), "unit": "images/sec",
        "epochs_timed": len(steady),
        "host_cores": os.cpu_count(),
        "transfer": "uint8",
        "feed_only_img_s": round(feed_ips, 1),
        "overlap_explained": (
            "overlapped epoch ~= max(feed, compute): io-fed value should "
            "approach min(feed_only_img_s, synthetic train img/s); a "
            "serial loop would sit near their harmonic combination "
            "1/(1/feed + 1/compute)"),
        "vs_baseline": round(ips / 97.0, 3),
    }))


def _compile_bench_symbol():
    """A conv+BN net with a nontrivial XLA compile (the persistent cache's
    win scales with compile time; a bare MLP compiles too fast to measure)."""
    from mxnet_tpu import symbol as sym

    net = sym.Variable("data")
    for i, ch in enumerate((32, 64, 64)):
        net = sym.Convolution(data=net, name=f"conv{i}", num_filter=ch,
                              kernel=(3, 3), pad=(1, 1))
        net = sym.BatchNorm(data=net, name=f"bn{i}")
        net = sym.Activation(data=net, name=f"relu{i}", act_type="relu")
        if i < 2:
            net = sym.Pooling(data=net, name=f"pool{i}", kernel=(2, 2),
                              stride=(2, 2), pool_type="max")
    net = sym.Flatten(data=net, name="flat")
    net = sym.FullyConnected(data=net, name="fc1", num_hidden=64)
    net = sym.Activation(data=net, name="fcrelu", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=10)
    return sym.SoftmaxOutput(data=net, name="softmax")


def run_compile_bench_child(args):
    """One measured process start: import -> build -> (optional AOT
    precompile) -> first train step. Prints one JSON line; the parent
    (run_compile_bench) aggregates cold/warm/AOT runs. The persistent
    cache dir arrives via MXNET_TPU_COMPILE_CACHE (wired by the package
    import, exactly the production path)."""
    t0 = time.perf_counter()
    import mxnet_tpu as mx
    from mxnet_tpu.utils import compile as compile_mod

    import_s = time.perf_counter() - t0
    bs = args.batch_size
    rng = np.random.RandomState(0)
    X = rng.randn(bs, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (bs,)).astype(np.float32)
    model = mx.FeedForward(_compile_bench_symbol(), ctx=mx.cpu()
                           if os.environ.get("JAX_PLATFORMS", "") == "cpu"
                           else None,
                           num_epoch=1, learning_rate=0.1)
    marks = []
    first_step_cb = lambda p: marks.append(time.perf_counter())  # noqa: E731
    precompile_s = None
    if args.compile_bench_child == "aot":
        t_pre = time.perf_counter()
        # batch_end_callback must match fit()'s (it un-fuses the device
        # metric, changing the compiled program — a mismatch orphans the
        # whole warmup; fit warns when that happens)
        model.precompile(
            data_shapes={"data": (bs, 3, 32, 32)},
            label_shapes={"softmax_label": (bs,)},
            batch_end_callback=first_step_cb)
        precompile_s = time.perf_counter() - t_pre
    model.fit(X, y, batch_size=bs, batch_end_callback=first_step_cb)
    stats = compile_mod.compile_stats()
    print(json.dumps({
        "import_s": round(import_s, 3),
        "time_to_first_step_s": round(marks[0] - t0, 3),
        "first_step_after_setup_s": round(
            marks[0] - t0 - import_s - (precompile_s or 0.0), 3),
        "precompile_s": (round(precompile_s, 3)
                         if precompile_s is not None else None),
        "compiles": stats["compiles"],
        "compile_seconds": round(stats["compile_seconds"], 3),
        "persistent_cache_hits": stats["persistent_cache_hits"],
        "persistent_cache_saved_s": round(
            stats["persistent_cache_saved_seconds"], 3),
    }))


def run_compile_bench(args):
    """Cold-start vs warm-start (persistent compilation cache) time-to-
    first-step, plus AOT-warmup wall time — each in a fresh subprocess so
    every run pays real process start. Emits BENCH_COMPILE_r07.json."""
    import shutil
    import subprocess
    import tempfile

    base = tempfile.mkdtemp(prefix="mxtpu_compile_bench_")

    def child(mode, cache_dir):
        env = {**os.environ,
               "MXNET_TPU_COMPILE_CACHE": cache_dir,
               "MXNET_TPU_COMPILE_CACHE_MIN_SEC": "0"}
        cmd = [sys.executable, os.path.abspath(__file__),
               "--compile-bench-child", mode,
               "--batch-size", str(args.batch_size)]
        r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=1200)
        if r.returncode != 0:
            print(r.stdout + r.stderr, file=sys.stderr)
            raise RuntimeError(f"compile-bench child ({mode}) failed")
        return json.loads(r.stdout.strip().splitlines()[-1])

    cache = os.path.join(base, "cache")
    cold = child("plain", cache)          # empty cache: full XLA compiles
    warm = child("plain", cache)          # same cache: deserialize from disk
    aot_cache = os.path.join(base, "aot_cache")
    aot = child("aot", aot_cache)         # fresh cache + AOT warmup up front
    aot_warm = child("aot", aot_cache)    # warm cache + AOT: best case
    entries = len([f for f in os.listdir(cache) if f.endswith("-cache")]) \
        if os.path.isdir(cache) else 0
    result = {
        "metric": "compile_bench_time_to_first_step_sec",
        "unit": "seconds",
        "batch_size": args.batch_size,
        "cold_start_s": cold["time_to_first_step_s"],
        "warm_start_s": warm["time_to_first_step_s"],
        "warm_speedup": round(cold["time_to_first_step_s"]
                              / max(warm["time_to_first_step_s"], 1e-9), 2),
        "warm_persistent_cache_hits": warm["persistent_cache_hits"],
        "warm_compile_saved_s": warm["persistent_cache_saved_s"],
        "aot_precompile_s": aot["precompile_s"],
        "aot_first_step_after_setup_s": aot["first_step_after_setup_s"],
        "aot_warm_precompile_s": aot_warm["precompile_s"],
        "aot_warm_first_step_after_setup_s":
            aot_warm["first_step_after_setup_s"],
        "cold_first_step_after_setup_s": cold["first_step_after_setup_s"],
        "cache_entries": entries,
        "detail": {"cold": cold, "warm": warm, "aot": aot,
                   "aot_warm": aot_warm},
    }
    print(json.dumps(result))
    _publish(result, "BENCH_COMPILE_r07.json")
    shutil.rmtree(base, ignore_errors=True)


def run_comm_bench(args):
    """Gradient-sync wire bytes + step time per compression mode on the
    8-virtual-device CPU mesh (the comm subsystem's acceptance rig: real
    chips aren't needed to measure the collective plan — the compiled
    HLO's collective instructions ARE the wire). For each mode the same
    dp-8 MLP train step is built via parallel.make_data_parallel_step,
    its HLO collective-byte table extracted (comm.hlo_collective_table),
    cross-checked against the closed-form plan (comm.allreduce_plan), and
    timed. Emits one JSON line; full runs write BENCH_COMM_r08.json."""
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import comm
    from mxnet_tpu import parallel as par

    ndev = 8
    devs = jax.devices()
    if len(devs) < ndev:
        print(json.dumps({"metric": "comm_bench_int8_wire_reduction_vs_fp32",
                          "value": 0, "unit": "x", "vs_baseline": 0,
                          "error": f"need {ndev} devices, have {len(devs)}"}))
        return
    mesh = par.make_mesh(dp=ndev, devices=devs[:ndev])
    smoke = args.smoke
    dim, hidden, classes = (64, 64, 8) if smoke else (512, 1024, 64)
    batch = 64 if smoke else 256
    steps = 3 if smoke else 30
    rng = np.random.RandomState(0)
    params0 = {
        "w1": (rng.randn(dim, hidden) * 0.05).astype(np.float32),
        "b1": np.zeros(hidden, np.float32),
        "w2": (rng.randn(hidden, classes) * 0.05).astype(np.float32),
        "b2": np.zeros(classes, np.float32),
    }
    num_elems = sum(v.size for v in params0.values())

    def loss_fn(params, data):
        h = jnp.tanh(data["x"] @ params["w1"] + params["b1"])
        logits = h @ params["w2"] + params["b2"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(
            logp, data["y"][:, None], axis=1))

    lr = 0.1

    def update_fn(params, opt_state, grads):
        return {k: params[k] - lr * grads[k] for k in params}, opt_state

    x = rng.randn(batch, dim).astype(np.float32)
    y = rng.randint(0, classes, (batch,)).astype(np.int32)
    data = par.shard_batch({"x": x, "y": y}, mesh)

    modes = {}
    for mode in (None, "bf16", "int8", "twobit"):
        spec = comm.CompressionSpec.resolve(mode)
        step = par.make_data_parallel_step(loss_fn, update_fn, mesh,
                                           donate=False, compression=mode)
        params = par.replicate_params(
            {k: jnp.asarray(v) for k, v in params0.items()}, mesh)
        call = (params, {}, data)
        if spec is not None and spec.error_feedback:
            resid = jax.device_put(
                comm.init_error_feedback(params, spec, ndev),
                NamedSharding(mesh, P("dp")))
            call += (resid,)
        hlo = step.lower(*call).compile().as_text()
        table = comm.hlo_collective_table(hlo, default_group_size=ndev)
        hlo_wire = sum(r["wire_bytes"] for r in table)
        plan = comm.allreduce_plan(num_elems, ndev, mode)
        res = step(*call)  # warm the dispatch path
        jax.block_until_ready(res[0])
        state = call
        t0 = _time.perf_counter()
        for _ in range(steps):
            res = step(state[0], state[1], data, *state[3:])
            state = (res[0], res[1], data) + tuple(res[3:])
        jax.block_until_ready(res[0])
        dt = (_time.perf_counter() - t0) / steps
        modes[mode or "none"] = {
            "hlo_wire_bytes_per_step": round(hlo_wire, 1),
            "hlo_collectives": table,
            "plan_wire_bytes_per_step": round(plan["wire_bytes"], 1),
            "plan_ratio_vs_fp32": round(plan["ratio"], 2),
            "step_ms": round(dt * 1e3, 3),
            "final_loss": round(float(np.asarray(res[2])), 5),
        }
    fp32_wire = modes["none"]["hlo_wire_bytes_per_step"]
    for m in modes.values():
        m["hlo_ratio_vs_fp32"] = round(
            fp32_wire / m["hlo_wire_bytes_per_step"], 2) \
            if m["hlo_wire_bytes_per_step"] else None
    ratio = modes["int8"]["hlo_ratio_vs_fp32"] or 0.0
    result = {
        "metric": "comm_bench_int8_wire_reduction_vs_fp32",
        "value": ratio,
        "unit": "x",
        # fp32 IS the baseline: vs_baseline == the reduction factor
        "vs_baseline": ratio,
        "axis_size": ndev,
        "param_elements": num_elems,
        "smoke": bool(smoke),
        "modes": modes,
        "notes": (
            "hlo_* numbers are from the compiled CPU-mesh HLO: int8/uint8 "
            "payloads are faithful, but the CPU backend's float "
            "normalization upcasts bf16 collectives to f32, so bf16 (and "
            "twobit's bf16 all-gather stage) read high here — plan_* is "
            "authoritative for those; on TPU bf16 stays bf16. step_ms is "
            "CPU compute-bound (quantization arithmetic costs more than "
            "the loopback 'wire' saves); the wire-byte cut is the number "
            "that transfers to bandwidth-bound pods."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_COMM_r08.json", smoke=smoke)


def run_overlap_bench(args):
    """Comm/compute overlap: fused single-bucket sync vs the overlapped
    per-bucket schedule (comm/overlap.py), measured two ways.

    **Mesh part** (dp-8 CPU mesh, int8): builds the same MLP train step
    with the fused allreduce and with ``overlap=`` bucketing, and proves
    the SCHEDULE — the compiled HLO must contain one independent
    reduce-scatter/all-gather pair per bucket (≥2, not one fused pair)
    and the per-bucket closed-form plans must sum exactly to the fused
    plan. Loopback step times are reported but are NOT the overlap
    headline: the CPU backend lowers collectives as synchronous thunks
    and its 'wire' is memcpy (CPU work), so there is no idle wire
    latency for XLA to hide here — that schedule benefit needs real
    interconnect (same caveat class as BENCH_COMM's bf16 note).

    **Stale-sync part** (the timed headline): single-process dist_async
    with an EMULATED cross-host RTT (an idle sleep on the push_pull
    round trip — loopback TCP has none; real parameter hosts do).
    Serial baseline: compute + push_pull every step. Overlapped:
    ``push_pull_stale`` pipelines the round trip one step behind
    compute. The headline speedup is serial/pipelined step time, and the
    ``comm_overlap_efficiency`` gauge (comm.overlap_efficiency) is
    computed from measured compute / comm / pipelined-step times and
    exported through the telemetry hub. Emits one JSON line; full runs
    write BENCH_OVERLAP_r11.json."""
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu import comm, telemetry
    from mxnet_tpu import parallel as par
    from mxnet_tpu.kvstore_async import AsyncKVStore

    smoke = args.smoke
    ndev = 8
    devs = jax.devices()
    if len(devs) < ndev:
        print(json.dumps({"metric": "overlap_bench_stale_sync_speedup",
                          "value": 0, "unit": "x", "vs_baseline": 0,
                          "error": f"need {ndev} devices, have {len(devs)}"}))
        return

    # -- mesh part: schedule structure + exact plan arithmetic -----------------
    mesh = par.make_mesh(dp=ndev, devices=devs[:ndev])
    layers, dim = (3, 128) if smoke else (4, 512)
    batch = 64 if smoke else 128
    steps = 3 if smoke else 20
    rng = np.random.RandomState(0)
    params0 = {}
    for i in range(layers):
        params0[f"w{i:02d}"] = (rng.randn(dim, dim) * 0.05).astype(np.float32)
        params0[f"b{i:02d}"] = np.zeros(dim, np.float32)
    num_elems = sum(v.size for v in params0.values())
    # cap at ~1/3 of the f32 bytes -> >=3 slabs, >=3 independent pairs
    cap = max(num_elems * 4 // 3, 1 << 14)

    def loss_fn(params, data):
        h = data["x"]
        for i in range(layers):
            h = jnp.tanh(h @ params[f"w{i:02d}"] + params[f"b{i:02d}"])
        return jnp.mean((h - data["y"]) ** 2)

    def update_fn(params, opt_state, grads):
        return {k: params[k] - 0.01 * grads[k] for k in params}, opt_state

    x = rng.randn(batch, dim).astype(np.float32)
    y = rng.randn(batch, dim).astype(np.float32)
    data = par.shard_batch({"x": x, "y": y}, mesh)
    spec = comm.CompressionSpec.resolve("int8")
    params = par.replicate_params(
        {k: jnp.asarray(v) for k, v in params0.items()}, mesh)

    def timed_steps(step, call):
        res = step(*call)
        jax.block_until_ready(res[0])
        state = call
        t0 = _time.perf_counter()
        for _ in range(steps):
            res = step(state[0], state[1], data, *state[3:])
            state = (res[0], res[1], data) + tuple(res[3:])
        jax.block_until_ready(res[0])
        return (_time.perf_counter() - t0) / steps, res

    step_f = par.make_data_parallel_step(loss_fn, update_fn, mesh,
                                         donate=False, compression="int8")
    resid_f = jax.device_put(comm.init_error_feedback(params, spec, ndev),
                             NamedSharding(mesh, P("dp")))
    t_fused, res_f = timed_steps(step_f, (params, {}, data, resid_f))

    hlo_f = step_f.lower(params, {}, data, resid_f).compile().as_text()
    table_f = comm.hlo_collective_table(hlo_f, default_group_size=ndev)

    def _op_counts(table):
        a2a = sum(r["count"] for r in table if "all-to-all" in r["op"])
        ag = sum(r["count"] for r in table if "all-gather" in r["op"])
        return a2a, ag

    f_a2a, f_ag = _op_counts(table_f)

    step_o = par.make_data_parallel_step(loss_fn, update_fn, mesh,
                                         donate=False, compression="int8",
                                         overlap=cap)
    oplan = comm.plan_overlap({k: v.shape for k, v in params0.items()},
                              spec, ndev, max_bytes=cap)
    resid_o = {k: jax.device_put(v, NamedSharding(mesh, P("dp")))
               for k, v in comm.init_overlap_residuals(oplan).items()}
    call_o = (params, {}, data, resid_o)
    hlo = step_o.lower(*call_o).compile().as_text()
    table = comm.hlo_collective_table(hlo, default_group_size=ndev)
    n_a2a, n_ag = _op_counts(table)
    t_over, res_o = timed_steps(step_o, call_o)
    wplan = oplan.wire_plan()

    mesh_part = {
        "num_buckets": oplan.num_buckets,
        # int8 payloads are (values, scales) dicts: 2 wire arrays per
        # collective pair — the split is proven by per-bucket multiplicity
        # over the fused counts, 1 independent pair group per bucket
        "hlo_reduce_scatter_ops": n_a2a,
        "hlo_all_gather_ops": n_ag,
        "hlo_reduce_scatter_ops_fused": f_a2a,
        "hlo_all_gather_ops_fused": f_ag,
        "hlo_independent_pairs": min(n_a2a // max(f_a2a, 1),
                                     n_ag // max(f_ag, 1)),
        "plan_wire_bytes": round(wplan["wire_bytes"], 1),
        "plan_matches_fused": wplan["matches_fused"],
        "fused_wire_bytes": round(wplan["fused_wire_bytes"], 1),
        "step_ms_fused": round(t_fused * 1e3, 3),
        "step_ms_overlapped": round(t_over * 1e3, 3),
        "loss_parity": abs(float(np.asarray(res_f[2]))
                           - float(np.asarray(res_o[2]))) < 1e-5,
    }

    # -- stale-sync part: the timed fused-vs-overlapped headline ---------------
    rtt = 0.040

    class _WireDelayed(AsyncKVStore):
        # emulated cross-host RTT: idle latency on the batch round trip
        # (time.sleep releases the GIL — genuinely hideable, like a NIC)
        def _call(self, *msg, **kw):
            if msg[0] in ("push_pull", "push_pull_enc"):
                _time.sleep(rtt)
            return super()._call(*msg, **kw)

    # sized so compute ~ comm (the regime where pipelining pays most:
    # serial = c + m, pipelined -> max(c, m))
    sdim = 256 if smoke else 512
    sbatch = 2048
    ssteps = 12 if smoke else 30
    W = {f"w{i}": (rng.randn(sdim, sdim) * 0.01).astype(np.float32)
         for i in range(2)}
    kv = _WireDelayed()
    try:
        for k, v in W.items():
            kv.init(k, mx.nd.NDArray(v))
        kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.01,
                                             rescale_grad=1.0))
        kv.set_gradient_compression("int8")

        @jax.jit
        def sstep(p, xb):
            def lf(q):
                h = xb
                for k in sorted(q):
                    h = jnp.tanh(h @ q[k])
                return jnp.mean(h ** 2)
            return jax.value_and_grad(lf)(p)

        xb = jnp.asarray(rng.randn(sbatch, sdim).astype(np.float32))
        ps = {k: jnp.asarray(v) for k, v in W.items()}
        loss, g = sstep(ps, xb)
        jax.block_until_ready(loss)
        t0 = _time.perf_counter()
        for _ in range(ssteps):
            loss, g = sstep(ps, xb)
            jax.block_until_ready(loss)
        t_compute = (_time.perf_counter() - t0) / ssteps
        gh = {k: np.asarray(v) for k, v in g.items()}
        pulled = kv.push_pull(gh)
        t0 = _time.perf_counter()
        for _ in range(ssteps):
            pulled = kv.push_pull(gh)
        t_comm = (_time.perf_counter() - t0) / ssteps

        def loop(pipelined):
            p = {k: jnp.asarray(pulled[k]) for k in W}
            t0 = _time.perf_counter()
            for _ in range(ssteps):
                loss, g = sstep(p, xb)
                jax.block_until_ready(loss)
                gh = {k: np.asarray(v) for k, v in g.items()}
                out = kv.push_pull_stale(gh) if pipelined \
                    else kv.push_pull(gh)
                p = {k: jnp.asarray(out[k]) for k in W}
            if pipelined:
                # drain INSIDE the clock: the last round's tail is part of
                # the pipelined schedule's honest cost
                kv.flush_stale(list(W))
            return (_time.perf_counter() - t0) / ssteps

        t_serial = loop(False)
        t_pipe = loop(True)
    finally:
        del kv
    speedup = t_serial / t_pipe
    # efficiency from self-consistent in-loop numbers: the serial loop IS
    # compute + comm by construction, so its excess over the measured
    # compute step prices the per-step comm the pipeline had to hide
    # (the standalone round_trip_ms microbench is reported for context;
    # back-to-back round trips contend differently than in-loop ones)
    t_comm_inloop = max(t_serial - t_compute, 0.0)
    eff = comm.overlap_efficiency(t_pipe, t_compute, t_comm_inloop)
    telemetry.gauge("comm_overlap_efficiency", eff)

    # telemetry tax of the overlap accounting: push_pull_stale adds two
    # histogram observes + one span sub-record per step
    hub = telemetry.hub()
    reps = 10000
    t0 = _time.perf_counter()
    for _ in range(reps):
        hub.observe("bench_overlap_seconds", 0.001)
    observe_s = (_time.perf_counter() - t0) / reps
    overhead_pct = 3 * observe_s / t_pipe * 100.0

    result = {
        "metric": "overlap_bench_stale_sync_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        # the serial (fused, un-overlapped) schedule IS the baseline
        "vs_baseline": round(speedup, 3),
        "axis_size": ndev,
        "smoke": bool(smoke),
        "mesh": mesh_part,
        "stale_sync": {
            "emulated_rtt_ms": rtt * 1e3,
            "step_ms_compute": round(t_compute * 1e3, 3),
            "round_trip_ms": round(t_comm * 1e3, 3),
            "comm_ms_in_loop": round(t_comm_inloop * 1e3, 3),
            "step_ms_serial": round(t_serial * 1e3, 3),
            "step_ms_pipelined": round(t_pipe * 1e3, 3),
        },
        "overlap_efficiency": round(eff, 4),
        "telemetry_overhead_pct": round(overhead_pct, 4),
        "notes": (
            "stale_sync is the timed headline: push_pull_stale pipelines "
            "the parameter-host round trip (emulated cross-host RTT — "
            "loopback TCP has no idle wire latency; real pods do) one "
            "step behind compute, so the pipelined step approaches "
            "max(compute, comm) instead of their sum. overlap_efficiency "
            "= 1 - (step - max(compute, comm)) / min(compute, comm), "
            "exported as the comm_overlap_efficiency hub gauge. The mesh "
            "part proves the per-bucket schedule structurally (>=2 "
            "independent HLO pairs, per-bucket plans summing exactly to "
            "the fused plan, loss parity); its loopback step times carry "
            "no hideable wire latency (synchronous CPU collectives) and "
            "are reported for completeness only."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_OVERLAP_r11.json", smoke=smoke)


def run_telemetry_bench(args):
    """Telemetry-hub overhead on the 8-virtual-device CPU mesh.

    Three measurements: (1) microbenched hub op cost (emit / observe /
    counter — the operations the train loop performs per step); (2) a
    small dp-8 MLP ``fit()`` WITHOUT telemetry (baseline steps/s); (3) the
    same fit WITH ``telemetry=True`` (timeline + MFU, per-step output
    sync). The headline number is hub overhead as a percentage of the
    baseline step: (hub ops per step) x (measured op cost) / step time —
    the always-on cost of the instrumentation layer. The timeline's
    sync-per-step cost (opt-in, trades pipelining for attribution) is
    reported separately as ``timeline_overhead_pct``. Emits one JSON
    line; full runs write BENCH_TELEMETRY_r09.json."""
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    ndev = 8
    import jax

    if len(jax.devices()) < ndev:
        print(json.dumps({"metric": "telemetry_hub_overhead_pct_of_step",
                          "value": 0, "unit": "%", "vs_baseline": 0,
                          "error": f"need {ndev} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (128, 256, 8) if smoke else (256, 1024, 32)
    batch, n_rows = (128, 1024) if smoke else (256, 4096)
    epochs = 3 if smoke else 6

    # -- (1) hub op microbench -------------------------------------------------
    hub = telemetry.reset()
    reps = 20000
    t0 = _time.perf_counter()
    for i in range(reps):
        hub.emit("bench", i=i)
    emit_ns = (_time.perf_counter() - t0) / reps * 1e9
    t0 = _time.perf_counter()
    for i in range(reps):
        hub.observe("bench_seconds", 0.001)
    observe_ns = (_time.perf_counter() - t0) / reps * 1e9
    t0 = _time.perf_counter()
    for i in range(reps):
        hub.counter("bench_total")
    counter_ns = (_time.perf_counter() - t0) / reps * 1e9

    # -- (2)/(3) fit with and without the timeline -----------------------------
    def build():
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1", act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(ndev)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    telemetry.measured_peak_flops()  # cache the peak probe outside timing

    def timed_fit(tel):
        model = build()
        model.fit(X, y, batch_size=batch, telemetry=tel)  # warm programs
        t0 = _time.perf_counter()
        model.fit(X, y, batch_size=batch, telemetry=tel)
        return _time.perf_counter() - t0

    wall_off = timed_fit(None)
    wall_on = timed_fit(True)
    step_s_off = wall_off / (epochs * steps_per_epoch)
    step_s_on = wall_on / (epochs * steps_per_epoch)

    # per-step hub traffic in the instrumented loop: 1 span emit + ~6
    # histogram observes (phases, step, data-wait) + ~3 counters
    hub_ops_per_step = 10
    op_ns = (emit_ns + observe_ns + counter_ns) / 3.0
    hub_overhead_pct = hub_ops_per_step * op_ns / (step_s_off * 1e9) * 100.0
    timeline_overhead_pct = (wall_on - wall_off) / wall_off * 100.0

    result = {
        "metric": "telemetry_hub_overhead_pct_of_step",
        "value": round(hub_overhead_pct, 4),
        "unit": "%",
        "vs_baseline": round(hub_overhead_pct, 4),
        "emit_ns": round(emit_ns, 1),
        "observe_ns": round(observe_ns, 1),
        "counter_ns": round(counter_ns, 1),
        "hub_ops_per_step": hub_ops_per_step,
        "step_ms_baseline": round(step_s_off * 1e3, 3),
        "step_ms_telemetry": round(step_s_on * 1e3, 3),
        "timeline_overhead_pct": round(timeline_overhead_pct, 2),
        "epochs": epochs, "steps_per_epoch": steps_per_epoch,
        "axis_size": ndev,
        "smoke": bool(smoke),
        "notes": (
            "hub overhead = measured per-op hub cost x ops/step vs the "
            "un-instrumented step (the always-on tax); "
            "timeline_overhead_pct additionally includes the OPT-IN "
            "per-step output sync (exact device-phase attribution trades "
            "feed/compute overlap) and one jaxpr FLOP trace per fit — on "
            "a CPU rig with ~ms steps that sync dominates; on a real pod "
            "with 100ms+ steps it vanishes."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_TELEMETRY_r09.json", smoke=smoke)


def run_trace_bench(args):
    """Flight-recorder + trace-propagation overhead on the dp-8 fused step.

    ISSUE 6 acceptance: the always-on black box (flight ring writes) plus
    the distributed-tracing identity work (rank/world stamping on every
    emit, span-id minting, trace-context capture for kvstore envelopes)
    must cost <2%% of a dp-8 step. Three measurements: (1) microbenched
    per-op costs for the operations tracing adds per step — one
    flight ``note_step`` ring append, one stamped ``emit`` through the
    recorder sink, one ``trace_ctx()`` capture, one span-id mint; (2) a
    dp-8 MLP ``fit()`` without telemetry (baseline steps/s); (3) the same
    fit with the timeline + flight recording on, reported separately
    (includes the opt-in per-step output sync). The headline number is
    (tracing ops per step) x (measured op cost) / baseline step time.
    Emits one JSON line; full runs write BENCH_TRACE_r10.json."""
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    ndev = 8
    import jax

    if len(jax.devices()) < ndev:
        print(json.dumps({"metric": "trace_flight_overhead_pct_of_step",
                          "value": 0, "unit": "%", "vs_baseline": 0,
                          "error": f"need {ndev} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (128, 256, 8) if smoke else (256, 1024, 32)
    batch, n_rows = (128, 1024) if smoke else (256, 4096)
    epochs = 3 if smoke else 6

    # -- (1) tracing-op microbench --------------------------------------------
    telemetry.reset()
    telemetry.flight.reset()
    rec = telemetry.flight.recorder()
    reps = 20000
    t0 = _time.perf_counter()
    for i in range(reps):
        rec.note_step(0, i)
    note_ns = (_time.perf_counter() - t0) / reps * 1e9
    span_event = {"kind": "span", "name": "step", "epoch": 0, "step": 0,
                  "dur_ms": 1.0, "phases": [], "rank": 0}
    t0 = _time.perf_counter()
    for i in range(reps):
        rec.write_event(span_event)
    sink_ns = (_time.perf_counter() - t0) / reps * 1e9
    t0 = _time.perf_counter()
    for i in range(reps):
        telemetry.trace_ctx()
    ctx_ns = (_time.perf_counter() - t0) / reps * 1e9
    t0 = _time.perf_counter()
    for i in range(reps):
        telemetry.mint_span_id(0, 0, i)
    mint_ns = (_time.perf_counter() - t0) / reps * 1e9

    # -- (2)/(3) fit with and without tracing ---------------------------------
    def build():
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1", act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(ndev)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    telemetry.measured_peak_flops()  # cache the peak probe outside timing

    def timed_fit(tel):
        model = build()
        model.fit(X, y, batch_size=batch, telemetry=tel)  # warm programs
        t0 = _time.perf_counter()
        model.fit(X, y, batch_size=batch, telemetry=tel)
        return _time.perf_counter() - t0

    wall_off = timed_fit(None)
    wall_on = timed_fit(True)
    step_s_off = wall_off / (epochs * steps_per_epoch)
    step_s_on = wall_on / (epochs * steps_per_epoch)

    # tracing ops per step: 1 flight ring append (lite mark or span
    # routing) + 1 stamped emit through the recorder sink + 1 trace-ctx
    # capture (kvstore envelope) + 1 span-id mint
    op_ns = note_ns + sink_ns + ctx_ns + mint_ns
    overhead_pct = op_ns / (step_s_off * 1e9) * 100.0
    traced_overhead_pct = (wall_on - wall_off) / wall_off * 100.0

    result = {
        "metric": "trace_flight_overhead_pct_of_step",
        "value": round(overhead_pct, 4),
        "unit": "%",
        "vs_baseline": round(overhead_pct, 4),
        "note_ns": round(note_ns, 1),
        "sink_ns": round(sink_ns, 1),
        "ctx_ns": round(ctx_ns, 1),
        "mint_ns": round(mint_ns, 1),
        "step_ms_baseline": round(step_s_off * 1e3, 3),
        "step_ms_traced": round(step_s_on * 1e3, 3),
        "traced_overhead_pct": round(traced_overhead_pct, 2),
        "flight_steps_recorded": len(rec.snapshot()[0]),
        "epochs": epochs, "steps_per_epoch": steps_per_epoch,
        "axis_size": ndev,
        "smoke": bool(smoke),
        "notes": (
            "headline = measured per-op cost of the tracing additions "
            "(flight ring append + rank-stamped emit through the recorder "
            "sink + trace-context capture + span-id mint) vs the "
            "un-instrumented dp-8 step — the always-on tax of ISSUE 6; "
            "step_ms_traced additionally includes the OPT-IN timeline "
            "with its per-step output sync (PR 5's attribution trade), "
            "dominated by sync on a CPU rig with ~ms steps."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_TRACE_r10.json", smoke=smoke)


def run_mem_bench(args):
    """Memory-observability overhead on the dp-8 fused step (ISSUE 9).

    The acceptance bound: the live-array ledger + phase-boundary sampler
    must cost <2%% of a dp-8 step. Three measurements: (1) microbenched
    per-op costs — one ledger add (weakref + locked dict insert, the
    NDArray-creation hook) and one phase-boundary sample (three gauge
    writes); (2) a dp-8 MLP ``fit()`` with telemetry but memory tracking
    OFF (baseline); (3) the same fit with tracking ON. The headline is
    (ledger+sampler ops per step) x (measured op cost) / baseline step —
    the deterministic always-on tax; the measured wall delta is reported
    separately (``tracked_overhead_pct``, noisy on ~ms CPU steps). Also
    reports the run's watermark and the number of registered program
    plans. Emits one JSON line; full runs write BENCH_MEM_r12.json."""
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import memory as mem_mod

    ndev = 8
    import jax

    if len(jax.devices()) < ndev:
        print(json.dumps({"metric": "memory_ledger_overhead_pct_of_step",
                          "value": 0, "unit": "%", "vs_baseline": 0,
                          "error": f"need {ndev} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (128, 256, 8) if smoke else (256, 1024, 32)
    batch, n_rows = (128, 1024) if smoke else (256, 4096)
    epochs = 2 if smoke else 6

    # -- (1) ledger/sampler op microbench (smoke stays light: this runs
    # inside tier-1 as a CI guard, and suite-cumulative CPU load skews
    # later timing tests) ------------------------------------------------------
    telemetry.reset()
    led = mem_mod.ledger()
    led.clear()
    reps = 5000 if smoke else 20000
    # distinct buffers: the ledger dedups wrappers of one buffer onto a
    # refcount fast path, so measuring the full insert needs fresh arrays
    probes = [mx.nd.zeros((8, 8)) for _ in range(reps)]
    t0 = _time.perf_counter()
    for p in probes:
        led.add(p)
    add_ns = (_time.perf_counter() - t0) / reps * 1e9
    del probes
    led.clear()
    t0 = _time.perf_counter()
    for _ in range(reps):
        mem_mod.sample()
    sample_ns = (_time.perf_counter() - t0) / reps * 1e9

    # -- (2)/(3) fit with tracking off vs on ----------------------------------
    def build():
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1", act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(ndev)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    telemetry.measured_peak_flops()  # cache the peak probe outside timing

    def timed_fit(mem):
        tel = telemetry.TelemetryConfig(memory=mem)
        model = build()
        model.fit(X, y, batch_size=batch, telemetry=tel)  # warm programs
        t0 = _time.perf_counter()
        model.fit(X, y, batch_size=batch, telemetry=tel)
        return _time.perf_counter() - t0

    wall_off = timed_fit(False)
    wall_on = timed_fit(True)
    step_s_off = wall_off / (epochs * steps_per_epoch)
    step_s_on = wall_on / (epochs * steps_per_epoch)
    watermark = led.watermark_bytes

    # register the AOT program's static memory plan so the JSON also
    # reports the plans side of ISSUE 9 (precompile -> memory_analysis)
    build().precompile(data_shapes={"data": (batch, dim)},
                       label_shapes={"softmax_label": (batch,)})

    # ledger traffic per instrumented step: a handful of NDArray creations
    # (device-metric path creates ~2; host-metric paths more) + ~6 phase-
    # boundary samples (one per mark + span finish)
    ledger_ops_per_step = 4
    samples_per_step = 6
    overhead_pct = (ledger_ops_per_step * add_ns
                    + samples_per_step * sample_ns) \
        / (step_s_off * 1e9) * 100.0
    tracked_overhead_pct = (wall_on - wall_off) / wall_off * 100.0

    result = {
        "metric": "memory_ledger_overhead_pct_of_step",
        "value": round(overhead_pct, 4),
        "unit": "%",
        "vs_baseline": round(overhead_pct, 4),
        "add_ns": round(add_ns, 1),
        "sample_ns": round(sample_ns, 1),
        "ledger_ops_per_step": ledger_ops_per_step,
        "samples_per_step": samples_per_step,
        "step_ms_baseline": round(step_s_off * 1e3, 3),
        "step_ms_tracked": round(step_s_on * 1e3, 3),
        "tracked_overhead_pct": round(tracked_overhead_pct, 2),
        "watermark_mb": round(watermark / (1 << 20), 3),
        "memory_plans_registered": len(mem_mod.plans()),
        "epochs": epochs, "steps_per_epoch": steps_per_epoch,
        "axis_size": ndev,
        "smoke": bool(smoke),
        "notes": (
            "headline = measured per-op ledger/sampler cost x ops/step vs "
            "the tracking-off step (the always-on tax of ISSUE 9's "
            "live-array ledger); tracked_overhead_pct is the raw wall "
            "delta of the same fit with tracking on — noisy on a CPU rig "
            "with ~ms steps, representative only on real 100ms+ pod "
            "steps."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_MEM_r12.json", smoke=smoke)


def run_health_bench(args):
    """--health-bench: price the in-graph training-health stats engine
    (ISSUE 14) and measure its detectors.

    Three measurements on the 8-virtual-device CPU mesh:

      (1) **stats overhead** — the headline. Two identical dp-8 MLP fits,
          health off vs on; the deterministic cost model is the jaxpr
          FLOP delta of the two fused-step programs (the stats live in
          the same XLA program, so ``model_flops_per_step`` prices them
          exactly), reported as %% of the baseline step's FLOPs. The raw
          wall delta is reported separately (noisy on ~ms CPU steps).
      (2) **per-layer table** — the health events of the instrumented run
          (what ``telemetry health`` renders), proving the stream.
      (3) **detection latency** — synthetic anomaly streams through the
          EXACT HealthMonitor detectors: a layer's grad norm exploding
          10x over a healthy baseline, a 20x loss spike, and a NaN step;
          reported as steps from injection to the ``health_anomaly``
          event. Acceptance: nonfinite detects in 0 extra steps,
          explosion/spike within 1.

    Emits one JSON line; full runs write BENCH_HEALTH_r17.json."""
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    ndev = 8
    import jax

    if len(jax.devices()) < ndev:
        print(json.dumps({"metric": "health_stats_overhead_pct_of_step",
                          "value": 0, "unit": "%", "vs_baseline": 0,
                          "error": f"need {ndev} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (128, 256, 8) if smoke else (256, 1024, 32)
    batch, n_rows = (128, 1024) if smoke else (256, 4096)
    epochs = 2 if smoke else 6

    def build():
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1", act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(ndev)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    telemetry.measured_peak_flops()  # cache the peak probe outside timing

    def timed_fit(health, jsonl=None):
        telemetry.reset()
        model = build()
        # warm-up fit WITHOUT the jsonl sink: the published event counts
        # and per-layer table must describe the instrumented run only
        model.fit(X, y, batch_size=batch,
                  telemetry=telemetry.TelemetryConfig(memory=False),
                  health=health)
        tel = telemetry.TelemetryConfig(jsonl=jsonl, memory=False)
        t0 = _time.perf_counter()
        model.fit(X, y, batch_size=batch, telemetry=tel, health=health)
        wall = _time.perf_counter() - t0
        flops = telemetry.hub().snapshot()["gauges"].get(
            "model_flops_per_step", 0.0)
        return wall, flops

    import tempfile

    jsonl = os.path.join(tempfile.mkdtemp(prefix="mxtpu_health_bench_"),
                         "run.jsonl")
    wall_off, flops_off = timed_fit(False)
    wall_on, flops_on = timed_fit(True, jsonl=jsonl)
    step_s_off = wall_off / (epochs * steps_per_epoch)
    step_s_on = wall_on / (epochs * steps_per_epoch)
    flop_overhead_pct = (flops_on - flops_off) / flops_off * 100.0 \
        if flops_off else 0.0
    wall_overhead_pct = (wall_on - wall_off) / wall_off * 100.0

    # -- (2) the per-layer table from the instrumented run --------------------
    from mxnet_tpu.telemetry.health import aggregate_events

    rows = telemetry.read_events(jsonl)
    health_events = [e for e in rows if e.get("kind") == "health"]
    run_anomalies = [e for e in rows if e.get("kind") == "health_anomaly"]
    layer_table = [{"layer": k, **v}
                   for k, v in sorted(aggregate_events(rows).items())]

    # -- (3) detection latency on synthetic streams ---------------------------
    def synth(kind):
        """Healthy baseline then one injected anomaly; returns steps from
        injection to detection (None = missed within the horizon)."""
        telemetry.reset()
        mon = telemetry.HealthMonitor(telemetry.HealthConfig())
        srng = np.random.RandomState(7)
        base = 40
        for i in range(base):
            stats = {"fc1": {"grad_norm": 1.0 + 0.05 * srng.randn(),
                             "weight_norm": 1.0, "update_ratio": 1e-3,
                             "nonfinite": 0},
                     "fc2": {"grad_norm": 2.0 + 0.1 * srng.randn(),
                             "weight_norm": 1.0, "update_ratio": 1e-3,
                             "nonfinite": 0}}
            mon.observe({"kind": "health", "epoch": 0, "step": i,
                         "loss": 1.0 + 0.01 * srng.randn(), "finite": True,
                         "stats": stats})
        for k in range(8):
            stats = {"fc1": {"grad_norm": 1.0, "weight_norm": 1.0,
                             "update_ratio": 1e-3, "nonfinite": 0},
                     "fc2": {"grad_norm": 2.0, "weight_norm": 1.0,
                             "update_ratio": 1e-3, "nonfinite": 0}}
            loss = 1.0
            if kind == "grad_explosion":
                stats["fc2"]["grad_norm"] = 20.0 * (k + 1)
            elif kind == "loss_spike":
                loss = 20.0
            elif kind == "nonfinite":
                stats["fc2"]["nonfinite"] = 17
            found = mon.observe({"kind": "health", "epoch": 0,
                                 "step": base + k, "loss": loss,
                                 "finite": kind != "nonfinite",
                                 "stats": stats})
            if any(r[0] == kind for r in found):
                return k
        return None

    latency = {kind: synth(kind)
               for kind in ("nonfinite", "grad_explosion", "loss_spike")}

    result = {
        "metric": "health_stats_overhead_pct_of_step",
        "value": round(flop_overhead_pct, 4),
        "unit": "%",
        "vs_baseline": round(flop_overhead_pct, 4),
        "flops_per_step_baseline": flops_off,
        "flops_per_step_health": flops_on,
        "step_ms_baseline": round(step_s_off * 1e3, 3),
        "step_ms_health": round(step_s_on * 1e3, 3),
        "wall_overhead_pct": round(wall_overhead_pct, 2),
        "health_events": len(health_events),
        "anomalies_in_run": len(run_anomalies),
        "layers": layer_table,
        "detect_latency_steps": latency,
        "epochs": epochs, "steps_per_epoch": steps_per_epoch,
        "axis_size": ndev,
        "smoke": bool(smoke),
        "notes": (
            "headline = jaxpr-audit FLOP delta of the health-instrumented "
            "fused step vs the bare one, as % of baseline FLOPs — the "
            "deterministic on-device cost of the in-graph stats engine "
            "(ISSUE 14); wall_overhead_pct is the raw dp-8 wall delta "
            "(includes the per-step host pull + detector pass; noisy on "
            "~ms CPU steps). detect_latency_steps: steps from synthetic "
            "injection to the health_anomaly event through the exact "
            "HealthMonitor detectors."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_HEALTH_r17.json", smoke=smoke)


def run_profile_bench(args):
    """--profile-bench: the device-time profiler's acceptance numbers
    (ISSUE 15). Three measurements on the 8-virtual-device CPU mesh:

      (1) **attribution coverage** — the headline. A dp-8 MLP fit with a
          bounded capture window (guards + health stacked, the production
          shape): the profiler must attribute >= 80%% of in-window device
          time to named layers/kernels, with the remainder reported as an
          explicit ``unattributed`` row. The top-K hotspot table, the
          per-layer split, and the measured roofline rows
          (``source: "measured"``, joined to the jaxpr-audit FLOP/byte
          models) are published alongside.
      (2) **measured-vs-modeled MFU** — the reconciliation delta between
          the device-clock MFU (measured numerator) and the wall-clock
          MFU the epoch report logs.
      (3) **out-of-window overhead** — once the window closes, the fit
          loop's only profiler cost is one state poll per step; priced
          per-poll (ns, microbenched) against the measured step time —
          acceptance < 0.5%% of a step. The window itself is priced as
          ``profile`` badput (reported, not hidden in throughput).

    Emits one JSON line; full runs write BENCH_PROFILE_r18.json."""
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import profiling

    ndev = 8
    import jax

    if len(jax.devices()) < ndev:
        print(json.dumps({"metric": "profile_attribution_coverage_pct",
                          "value": 0, "unit": "%", "vs_baseline": 80,
                          "error": f"need {ndev} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (64, 128, 8) if smoke else (256, 1024, 32)
    batch, n_rows = (128, 1024) if smoke else (256, 4096)
    epochs = 2 if smoke else 4
    window = 4 if smoke else 8

    def build(ndev=ndev):
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1",
            act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(ndev)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    telemetry.measured_peak_flops()  # cache the probes outside timing
    profiling.measured_peak_bandwidth()

    telemetry.reset()
    model = build()
    t0 = _time.perf_counter()
    model.fit(X, y, batch_size=batch, guards=True, health=True,
              telemetry=telemetry.TelemetryConfig(memory=False),
              profile=telemetry.ProfileConfig(steps=window, warmup=2))
    wall = _time.perf_counter() - t0
    rep = model.profile_report
    assert rep is not None, "profiled fit produced no report"
    summary = rep.to_dict(top_k=10)
    step_ms = wall / (epochs * steps_per_epoch) * 1e3

    # -- (3) out-of-window overhead: the per-step poll of a closed session
    ses = profiling.ProfileSession(telemetry.ProfileConfig(), layers=())
    ses._state = "done"
    reps = 20000 if smoke else 200000
    t0 = _time.perf_counter()
    for _ in range(reps):
        _ = ses.pending
        _ = ses.open
    poll_ns = (_time.perf_counter() - t0) / reps * 1e9
    overhead_pct = poll_ns / (step_ms * 1e6) * 100.0

    badput = sum(float(e.get("seconds", 0.0))
                 for e in telemetry.hub().events(kind="badput")
                 if e.get("reason") == "profile")

    mfu = summary.get("mfu", {})
    result = {
        "metric": "profile_attribution_coverage_pct",
        "value": round(summary["coverage_pct"], 2),
        "unit": "%",
        "vs_baseline": 80.0,
        "window_steps": summary["steps"],
        "device_ms": round(summary["device_ms"], 3),
        "unattributed_ms": round(summary["unattributed_ms"], 3),
        "layers_ms": {k: round(v, 3)
                      for k, v in summary["layers"].items()},
        "top": [{"layer": r.get("layer"), "op": r.get("op"),
                 "ms": round(r.get("us", 0.0) / 1e3, 4),
                 "pct": round(r.get("pct", 0.0), 2)}
                for r in summary["top"]],
        "roofline": summary["roofline"][:10],
        "measured_mfu_pct": mfu.get("measured_mfu_pct"),
        "modeled_mfu_pct": mfu.get("modeled_mfu_pct"),
        "mfu_delta_pct": mfu.get("delta_pct"),
        "profile_badput_s": round(badput, 4),
        "out_of_window_poll_ns": round(poll_ns, 1),
        "out_of_window_overhead_pct": round(overhead_pct, 6),
        "step_ms": round(step_ms, 3),
        "epochs": epochs, "steps_per_epoch": steps_per_epoch,
        "axis_size": ndev,
        "smoke": bool(smoke),
        "notes": (
            "headline = share of in-window device time attributed to "
            "named layers/kernels through the named-scope HLO metadata "
            "join (>= 80% acceptance; the remainder is the explicit "
            "unattributed row). roofline rows are source=measured: "
            "measured per-op seconds against the jaxpr-audit/kernel-"
            "registry models — on this CPU rig the rates are rig-"
            "relative (measured matmul peak), the row schema is the TPU "
            "contract. out_of_window = the closed session's per-step "
            "state poll, priced per-poll x 1 poll/step against the "
            "measured step (<0.5% acceptance); the window itself is "
            "priced as `profile` badput, never as throughput."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_PROFILE_r18.json", smoke=smoke)


def run_elastic_bench(args):
    """--elastic-bench: price a mid-run world resize (ISSUE 10).

    On the 8-virtual-device CPU mesh, an elastic fit loses 2 of 8 workers
    mid-epoch, continues on 6, and regrows to 8 — the bench measures the
    quiesce->reshard->replan->rewarm downtime of each resize, the per-step
    time at every world size, and the post-resize goodput (the `resize`
    badput bucket priced by the epoch report). Emits one JSON line; full
    runs write BENCH_ELASTIC_r13.json."""
    import tempfile
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.resilience import ElasticCoordinator

    import jax

    world = 8
    if len(jax.devices()) < world:
        print(json.dumps({"metric": "elastic_resize_downtime_seconds",
                          "value": 0, "unit": "s", "vs_baseline": 0,
                          "error": f"need {world} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (32, 64, 4) if smoke else (256, 1024, 32)
    batch, n_rows = (48, 480) if smoke else (192, 3840)  # 48,192 % 6 == 0
    epochs = 4 if smoke else 6

    def build():
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1",
            act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(world)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    telemetry.reset()
    telemetry.measured_peak_flops()  # cache the peak probe outside timing

    co = ElasticCoordinator(world)

    def drive(param):
        # kill 2 of 8 mid-epoch-1; regrow mid-epoch-2 — both resizes land
        # mid-epoch so the redo + downtime are fully priced
        if param.epoch == 1 and param.nbatch == 2 and co.world_size == 8:
            co.kill()
            co.kill()
        if param.epoch == 2 and param.nbatch == 2 and co.world_size == 6:
            co.join_all()

    tmp = tempfile.mkdtemp(prefix="mxtpu_elastic_bench_")
    jsonl = os.path.join(tmp, "events.jsonl")
    model = build()
    t0 = _time.perf_counter()
    model.fit(X, y, batch_size=batch, elastic=co,
              sharded_checkpoint_dir=os.path.join(tmp, "ckpt"),
              batch_end_callback=drive,
              telemetry=telemetry.TelemetryConfig(jsonl=jsonl))
    wall = _time.perf_counter() - t0

    downs = [h["downtime_s"] for h in co.history]
    # per-world step times from the timeline: an epoch interrupted by a
    # resize leaves the ABORTED attempt's old-world spans under the same
    # epoch number, so take only the trailing steps_per_epoch spans of
    # each epoch — the completed attempt at that epoch's final world size
    spans = model.telemetry.steps()
    step_ms = {}
    for world_size, epoch in (("8_pre", 0), ("6", 1), ("8_post", 3)):
        tail = [s.duration for s in spans
                if s.epoch == epoch][-steps_per_epoch:]
        if tail:
            tail.sort()
            step_ms[world_size] = tail[len(tail) // 2] * 1e3
    events = telemetry.read_events(jsonl)
    goodput = {int(e["epoch"]): e.get("goodput_pct")
               for e in events if e.get("kind") == "epoch_summary"}
    resize_badput = sum(float(e.get("seconds", 0.0)) for e in events
                        if e.get("kind") == "badput"
                        and e.get("reason") == "resize")
    resizes = [e for e in events if e.get("kind") == "resize"]

    result = {
        "metric": "elastic_resize_downtime_seconds",
        "value": round(downs[0], 4) if downs else None,
        "unit": "s",
        "vs_baseline": round(downs[0], 4) if downs else None,
        "shrink_downtime_s": round(downs[0], 4) if downs else None,
        "grow_downtime_s": round(downs[1], 4) if len(downs) > 1 else None,
        "resizes": co.resizes,
        "resize_events": len(resizes),
        "worlds": [h["to"] for h in co.history],
        "step_ms_by_world": {k: round(v, 3) for k, v in step_ms.items()},
        "goodput_pct_by_epoch": {k: round(v, 2)
                                 for k, v in sorted(goodput.items())
                                 if v is not None},
        "resize_badput_s": round(resize_badput, 4),
        "wall_s": round(wall, 3),
        "epochs": epochs, "steps_per_epoch": steps_per_epoch,
        "batch": batch, "full_world": world,
        "smoke": bool(smoke),
        "notes": (
            "headline = shrink (8->6) downtime: quiesce + checkpoint "
            "reshard + plan re-derivation + AOT re-warmup for the new "
            "axis, measured on the CPU rig (pod-scale compiles dominate "
            "on real hardware; the persistent compile cache and warm-"
            "program reuse on regrow are what bound it). resize badput "
            "additionally prices the redone partial epoch."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_ELASTIC_r13.json", smoke=smoke)


def run_controller_bench(args):
    """--controller-bench: goodput recovered by the fleet controller
    under an injected persistent straggler + a flaky rank (ISSUE 12).

    Three dp-8 fits on the CPU mesh, same model/data/steps:

      clean    no fault injected, no controller — the ceiling;
      static   rank 7 drags every collective by a fixed stall (injected
               as a real per-step sleep + per-rank telemetry spans that
               blame it) and rank 6 goes heartbeat-silent mid-run; no
               controller, so the fleet pays the stall forever;
      armed    same faults, fit(controller=...): the controller blames
               rank 7 over K-of-N windows, evicts it, backfills the
               flaky rank when it beats again, and auto-picks a
               compression tier from the (bandwidth-scaled) comm:compute
               ratio.

    Headline: goodput_recovered_frac = (tpc_armed - tpc_static) /
    (tpc_clean - tpc_static) on per-chip throughput over the post-
    warmup epochs — 1.0 means the autopilot bought back everything the
    straggler cost. Emits one JSON line; full runs write
    BENCH_CONTROLLER_r15.json."""
    import tempfile
    import threading
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.resilience import ElasticCoordinator, FleetController

    import jax

    world = 8
    if len(jax.devices()) < world:
        print(json.dumps({"metric": "controller_goodput_recovered_frac",
                          "value": 0, "unit": "frac", "vs_baseline": 0,
                          "error": f"need {world} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (32, 64, 4) if smoke else (128, 512, 16)
    # batch % 6, 7, 8 == 0: every world this fleet can pass through
    # (evict the straggler -> 7, flaky death -> 6, backfill -> 7/8)
    batch, n_rows = (168, 840) if smoke else (168, 3360)
    epochs = 3 if smoke else 5
    stall_s = 0.03 if smoke else 0.05
    straggler, flaky = 7, 6
    steps_per_epoch = n_rows // batch

    def build():
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1",
            act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(world)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    telemetry.measured_peak_flops()  # cache the probe outside timing

    class FaultHarness:
        """The injected fleet pathology: a persistent straggler (real
        per-step sleep, charged to whoever keeps rank 7 in the world)
        plus per-rank telemetry spans blaming it, and a flaky rank whose
        out-of-band heartbeats stop for a while mid-run and resume.
        Heartbeats come from their own thread (like a real fleet's —
        and so a long AOT re-warm gap can never read as a mass death).
        With ``inject=False`` it is the clean harness: same bookkeeping
        (per-step wall clocks), no faults."""

        def __init__(self, co=None, inject=True):
            self.co = co
            self.inject = inject
            self.step = 0
            self.times = []  # monotonic at every batch callback
            self._stop = threading.Event()
            self._silent_at = None  # wall start of the flaky outage
            if co is not None and co.heartbeat_timeout:
                self._silence = 6.0 * co.heartbeat_timeout
                threading.Thread(target=self._beat, daemon=True,
                                 name="mx-bench-beater").start()

        def alive(self):
            return self.co.alive if self.co is not None \
                else tuple(range(world))

        def _beat(self):
            # every rank beats (departed ones too: a recovered host
            # heartbeats before readmission) except the flaky one
            # during its outage window
            while not self._stop.wait(0.05):
                now = _time.monotonic()
                out = self._silent_at is not None and \
                    now - self._silent_at < self._silence
                for r in range(world):
                    if r == flaky and out:
                        continue
                    self.co.heartbeat(r)

        def close(self):
            self._stop.set()

        def final_epoch_step_s(self):
            """Median wall per step over the run's final epoch — the
            steady state each fleet settled into (run-order XLA-cache
            effects and mid-run re-warms excluded by construction)."""
            tail = self.times[-(steps_per_epoch + 1):]
            diffs = sorted(b - a for a, b in zip(tail, tail[1:]))
            return diffs[len(diffs) // 2] if diffs else None

        def __call__(self, param):
            del param
            self.times.append(_time.monotonic())
            s = self.step
            self.step += 1
            if not self.inject:
                return
            if self.co is not None and self._silent_at is None and \
                    s >= steps_per_epoch:
                self._silent_at = _time.monotonic()  # outage starts
            alive = self.alive()
            if straggler in alive:
                _time.sleep(stall_s)  # the whole collective waits
            for r in alive:
                dur_ms = (stall_s * 1e3 + 2.0) if r == straggler else 2.0
                telemetry.emit(
                    "span", rank=r, name="step", epoch=0, step=s,
                    dur_ms=dur_ms,
                    phases=[{"name": "device", "dur_ms": dur_ms}])

    def run_fit(name, faults, controller=None, co=None):
        telemetry.reset()
        model = build()
        tmp = tempfile.mkdtemp(prefix=f"mxtpu_ctl_bench_{name}_")
        t0 = _time.perf_counter()
        try:
            model.fit(X, y, batch_size=batch,
                      # False, not None: a user's MXNET_TPU_ELASTIC /
                      # MXNET_TPU_CONTROLLER env gates must not arm the
                      # clean/static baselines
                      elastic=co if co is not None else False,
                      controller=controller if controller is not None
                      else False,
                      sharded_checkpoint_dir=os.path.join(tmp, "ckpt")
                      if co is not None else None,
                      batch_end_callback=faults,
                      telemetry=telemetry.TelemetryConfig(
                          timeline=False, memory=False))
        finally:
            if hasattr(faults, "close"):
                faults.close()
        wall = _time.perf_counter() - t0
        return model, wall

    clean = FaultHarness(inject=False)   # the no-fault ceiling
    _, wall_clean = run_fit("clean", clean)
    static = FaultHarness()
    _, wall_static = run_fit("static", static)

    co = ElasticCoordinator(world, heartbeat_timeout=0.5)
    ctl = FleetController(
        interval=0.0, window=24, min_report_steps=24, evict_k=3,
        evict_n=5, max_evictions=1, rejoin_after=1.0, evaluate_after=1.0,
        cooldowns={"evict": 0.5, "backfill": 0.2, "retier": 0.5},
        wire_gbps=0.01)  # scaled bandwidth: the tiny CPU model reads as
    #                      comm-bound, so the tier policy has a real
    #                      choice to make on this rig
    harness = FaultHarness(co)
    model, wall_ctl = run_fit("armed", harness, controller=ctl, co=co)

    # per-chip throughput in each run's FINAL-epoch steady state
    # (steps/sec/chip, global batch fixed): the static fleet is still
    # paying the straggler there; the armed fleet has evicted it and
    # settled on its chosen world/tier. Whole-run walls are reported
    # too, but run-order XLA-executable-cache effects make them
    # incomparable as the headline.
    worlds = [h["to"] for h in co.history]
    step_clean = clean.final_epoch_step_s()
    step_static = static.final_epoch_step_s()
    step_ctl = harness.final_epoch_step_s()
    tpc_clean = 1.0 / (step_clean * world) if step_clean else None
    tpc_static = 1.0 / (step_static * world) if step_static else None
    tpc_ctl = 1.0 / (step_ctl * co.world_size) if step_ctl else None
    recovered = None
    if None not in (tpc_clean, tpc_static, tpc_ctl) and \
            tpc_clean > tpc_static:
        recovered = (tpc_ctl - tpc_static) / (tpc_clean - tpc_static)

    evicts = [d for d in ctl.decisions
              if d["lever"] == "evict" and d["outcome"] == "actuated"]
    backfills = [d for d in ctl.decisions
                 if d["lever"] == "backfill" and d["outcome"] == "actuated"]
    retiers = [d for d in ctl.decisions
               if d["lever"] == "retier" and d["outcome"] == "actuated"]

    result = {
        "metric": "controller_goodput_recovered_frac",
        "value": round(recovered, 4) if recovered is not None else None,
        "unit": "frac",
        "vs_baseline": round(tpc_ctl / tpc_static, 4)
        if tpc_ctl and tpc_static else None,
        "tpc_clean": round(tpc_clean, 4) if tpc_clean else None,
        "tpc_static": round(tpc_static, 4) if tpc_static else None,
        "tpc_controller": round(tpc_ctl, 4) if tpc_ctl else None,
        "final_step_ms": {
            "clean": round(step_clean * 1e3, 3) if step_clean else None,
            "static": round(step_static * 1e3, 3) if step_static else None,
            "controller": round(step_ctl * 1e3, 3) if step_ctl else None},
        "wall_clean_s": round(wall_clean, 3),
        "wall_static_s": round(wall_static, 3),
        "wall_controller_s": round(wall_ctl, 3),
        "stall_ms": stall_s * 1e3,
        "evicted": [d.get("rank") for d in evicts],
        "backfilled": [d.get("rank") for d in backfills],
        "tier_chosen": ctl._comm_mode,
        "retier_actions": [d["action"] for d in retiers],
        "resizes": co.resizes,
        "worlds": worlds,
        "breaker_state": ctl.breaker.state,
        "decisions_total": len(ctl.decisions),
        "epochs": epochs, "steps_per_epoch": steps_per_epoch,
        "batch": batch, "full_world": world, "smoke": bool(smoke),
        "notes": (
            "headline = fraction of straggler-lost per-chip throughput "
            "the armed controller bought back, measured in each run's "
            "final-epoch steady state (the static fleet still pays the "
            "stall there; the armed fleet has evicted the straggler and "
            "settled on its chosen world/tier). Whole-run walls carry "
            "the autopilot's own costs (resize + retier re-warms) and "
            "run-order XLA-cache effects — reported, not the headline. "
            "CPU-rig caveat: stall_ms dominates the tiny step, so "
            "fractions exaggerate what a pod would see; the shape of "
            "the loop (blame -> evict -> backfill -> retier) is the "
            "measured artifact."),
    }
    print(json.dumps(result))
    if not smoke:
        assert recovered is not None and recovered >= 0.3, result
        assert [d.get("rank") for d in evicts] == [straggler], result
    _publish(result, "BENCH_CONTROLLER_r15.json", smoke=smoke)


def run_kernel_bench(args):
    """--kernel-bench: the Pallas kernel layer's roofline accounting
    (ISSUE 13). Three measurements, one JSON line (full runs write
    BENCH_KERNELS_r16.json):

    (a) a roofline row per registered kernel — registry FLOP/byte model
        vs measured interpret-mode wall time on this rig (CPU numbers:
        the interpreter prices correctness, not Mosaic speed; the row
        SCHEMA is the TPU contract, and flash's on-chip numbers live in
        FLASH_r05.json / the kernel catalog);
    (b) the fused-vs-unfused HLO delta on the dp-8 compressed allreduce:
        full-slab quantize-shaped elementwise passes (the encode/decode
        cost the comm kernels remove) and collective wire bytes (which
        must NOT change — same bits on the wire);
    (c) the fused-Adam step-time delta vs the per-leaf optimizer tree,
        parity-checked bitwise on the same inputs.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import mxnet_tpu.optimizer as opt_mod
    from mxnet_tpu import comm
    from mxnet_tpu import parallel as par
    from mxnet_tpu.analysis import jaxpr_audit
    from mxnet_tpu.compat import shard_map
    from mxnet_tpu.ops import pallas as pk
    from mxnet_tpu.telemetry.mfu import measured_peak_flops

    smoke = args.smoke
    rng = np.random.RandomState(0)
    peak = measured_peak_flops()

    def time_fn(fn, *a, iters=None, warmup=2):
        iters = iters or (3 if smoke else 20)
        for _ in range(warmup):
            out = fn(*a)
        jax.block_until_ready(out)
        t0 = _time.perf_counter()
        for _ in range(iters):
            out = fn(*a)
        jax.block_until_ready(out)
        return (_time.perf_counter() - t0) / iters

    def roofline_row(label, fn, *a):
        """One kernel invocation: registry-priced cost + measured time.

        Every roofline row carries ``source`` (ISSUE 15 satellite):
        ``interpret`` when the Pallas interpreter ran (CPU rig — prices
        the interpreter, not Mosaic), ``measured`` on real hardware;
        device-profiler rows (telemetry/profiling.py) are always
        ``measured``, and rows priced purely from cost models say
        ``model`` — so a CPU estimate can never be read as a device
        measurement."""
        jitted = jax.jit(fn)
        rows, totals = jaxpr_audit.cost_rows(fn, *a)
        krows = [r for r in rows if r["primitive"].startswith("pallas::")]
        flops = sum(r["flops"] for r in krows)
        bytes_ = sum(r["bytes"] for r in krows)
        dt = time_fn(jitted, *a)
        return {
            "kernel": label,
            "source": "interpret" if pk.use_interpret() else "measured",
            "kernels_in_program": [r["primitive"] for r in krows],
            "model_flops": flops,
            "model_bytes": bytes_,
            "intensity_flops_per_byte": round(flops / bytes_, 3)
            if bytes_ else None,
            "ms": round(dt * 1e3, 4),
            "achieved_gflops_s": round(flops / dt / 1e9, 3),
            "achieved_gbytes_s": round(bytes_ / dt / 1e9, 3),
            "pct_of_measured_peak": round(100.0 * flops / dt / peak, 3),
        }

    # -- (a) per-kernel roofline rows (interpret mode on this rig) ---------
    b, h, s, d = (1, 2, 128, 32) if smoke else (2, 4, 512, 64)
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    slab_r, slab_l = (8, 4096) if smoke else (8, 65536)
    rows_in = jnp.asarray(rng.randn(slab_r, slab_l).astype(np.float32))
    spec8 = comm.CompressionSpec("int8", chunk=256)
    spec2 = comm.CompressionSpec("twobit", threshold=0.5, chunk=256)
    m_mm, k_mm, n_mm = (64, 128, 64) if smoke else (512, 1024, 512)
    x_mm = jnp.asarray(rng.randn(m_mm, k_mm).astype(np.float32))
    w_mm = jnp.asarray(rng.randn(n_mm, k_mm).astype(np.float32))

    names = ["p0", "p1", "p2"]
    shapes = [(256, 64), (64,), (64, 32)] if smoke else \
        [(1024, 512), (512,), (512, 256)]
    params = {n: jnp.asarray(rng.randn(*sh).astype(np.float32))
              for n, sh in zip(names, shapes)}
    grads = {n: jnp.asarray(rng.randn(*sh).astype(np.float32))
             for n, sh in zip(names, shapes)}
    adam_f = opt_mod.Adam(lr=1e-3, fused=True)
    adam_u = opt_mod.Adam(lr=1e-3, fused=False)
    states = adam_f.init_state_tree(params)
    lr = jnp.float32(1e-3)

    kernels = [
        roofline_row("flash_attention_fwd",
                     lambda x: pk.flash_attention(x, x, x, causal=True), q),
        roofline_row(
            "flash_attention_fwd_bwd",
            lambda x: jax.grad(lambda y: jnp.sum(
                pk.flash_attention(y, y, y, causal=True)))(x), q),
        roofline_row(
            "quant_int8",
            lambda r: pk.fused_quantize(spec8, r, want_dequant=True)[0]["q"],
            rows_in),
        roofline_row(
            "quant_twobit",
            lambda r: pk.fused_quantize(spec2, r, want_dequant=True)[0]["q"],
            rows_in),
        # payload built OUTSIDE the measured fn: the row prices the
        # dequant-sum kernel alone, not a quantize+dequant pair
        roofline_row(
            "dequant_sum_int8",
            lambda p: pk.fused_dequant_sum(spec8, p),
            jax.jit(lambda r: pk.fused_quantize(spec8, r)[0])(rows_in)),
        roofline_row("fused_adam",
                     lambda p, g, st: pk.fused_adam_apply(
                         adam_f, p, g, st, lr)[0]["p0"],
                     params, grads, states),
        roofline_row("int8_matmul",
                     lambda a, w: pk.int8_matmul(a, w), x_mm, w_mm),
    ]

    # -- (b) fused-vs-unfused HLO delta on the dp-8 exchange ---------------
    ndev = 8
    mesh = par.make_mesh(dp=ndev, devices=jax.devices()[:ndev])
    L = ndev * (2048 if smoke else 16384)
    tree = {"g": jnp.asarray(rng.randn(L).astype(np.float32))}
    resid = jnp.zeros((ndev, L), jnp.float32)

    def build_exchange(kern_cfg):
        def body(t, r):
            return comm.error_feedback_allreduce(
                t, r, spec8, axis_name="dp", axis_size=ndev,
                kernels=kern_cfg)
        return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P("dp")),
                                 out_specs=(P(), P("dp")), check_vma=False))

    hlo_delta = {}
    for label, cfg in (("codec", False),
                       ("kernels", comm.CommKernelConfig())):
        f = build_exchange(cfg)
        hlo = f.lower(tree, resid).compile().as_text()
        hlo_delta[label] = {
            "full_slab_quantize_passes":
                comm.hlo_quantize_pass_count(hlo, min_elements=L),
            "collective_wire_bytes": round(sum(
                r["wire_bytes"] for r in comm.hlo_collective_table(
                    hlo, default_group_size=ndev)), 1),
            "step_ms": round(time_fn(f, tree, resid) * 1e3, 3),
        }
    passes_cut = (hlo_delta["codec"]["full_slab_quantize_passes"]
                  - hlo_delta["kernels"]["full_slab_quantize_passes"])

    # -- (c) fused-Adam step-time delta + parity ---------------------------
    apply_f = jax.jit(lambda p, g, st: adam_f.apply(p, g, st, lr))
    apply_u = jax.jit(lambda p, g, st: adam_u.apply(p, g, st, lr))
    pf, sf = apply_f(params, grads, states)
    pu, su = apply_u(params, grads, states)
    adam_parity = all(
        bool(jnp.all(pf[n] == pu[n])) for n in names) and all(
        bool(jnp.all(sf[n][i] == su[n][i]))
        for n in names for i in range(3))
    adam_row = {
        "fused_ms": round(time_fn(apply_f, params, grads, states) * 1e3, 4),
        "per_leaf_ms": round(
            time_fn(apply_u, params, grads, states) * 1e3, 4),
        "bitwise_parity": bool(adam_parity),
        "param_elements": int(sum(int(np.prod(sh)) for sh in shapes)),
    }

    y_ref = x_mm @ w_mm.T
    y_q = pk.int8_matmul(x_mm, w_mm)
    mm_err = float(jnp.linalg.norm(y_q - y_ref) / jnp.linalg.norm(y_ref))

    result = {
        "metric": "kernel_bench_full_slab_quantize_passes_removed",
        "value": passes_cut,
        "unit": "hlo_passes",
        "vs_baseline": hlo_delta["codec"]["full_slab_quantize_passes"],
        "smoke": bool(smoke),
        "interpret_mode": bool(pk.use_interpret()),
        "measured_peak_gflops_s": round(peak / 1e9, 2),
        "kernels": kernels,
        "hlo_fused_vs_unfused": hlo_delta,
        "wire_bytes_identical": (
            hlo_delta["codec"]["collective_wire_bytes"]
            == hlo_delta["kernels"]["collective_wire_bytes"]),
        "fused_adam": adam_row,
        "int8_matmul_rel_error": round(mm_err, 6),
        "catalog": pk.catalog(),
        "notes": (
            "CPU rig: kernels run under the Pallas interpreter, so ms/"
            "achieved-rate columns price the interpreter, not Mosaic — "
            "the registry flops/bytes and the HLO pass/wire deltas are "
            "the numbers that transfer to TPU (schema ready; flash's "
            "on-chip rates are in FLASH_r05.json). wire bytes must be "
            "identical between codec and kernel paths: same bits, fewer "
            "passes."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_KERNELS_r16.json", smoke=smoke)


def run_lockwatch_bench(args):
    """--lockwatch-bench: price the runtime lock-order watchdog (ISSUE 11).

    Two soaks under MXNET_TPU_LOCKWATCH semantics (watchdog armed
    in-process): (a) a 4-rank group-kvstore push/pull/barrier soak with a
    mid-soak membership churn (deregister a rank inside an open
    accumulate round, then re-register it), and (b) an elastic fit on a
    dp-4 CPU mesh that shrinks to 3 mid-epoch and regrows — the two most
    lock-entangled paths in the stack. Acceptance: ZERO lock-order cycles
    across both, and watchdog overhead <2% of a step (priced robustly:
    per acquire/release-pair microbench delta x measured acquisitions per
    step / measured step time — two full timed runs would drown the
    number in shared-box noise). Emits one JSON line; full runs write
    BENCH_LOCKWATCH_r14.json."""
    import tempfile
    import threading
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.analysis import lockwatch
    from mxnet_tpu.ndarray import NDArray
    from mxnet_tpu.resilience import ElasticCoordinator

    import jax

    world = 4
    if len(jax.devices()) < world:
        print(json.dumps({"metric": "lockwatch_overhead_pct_of_step",
                          "value": 0, "unit": "%", "vs_baseline": 0,
                          "error": f"need {world} devices"}))
        return
    smoke = args.smoke

    # -- (1) per-pair microbench: watched lock, watchdog off vs on ------------
    reps = 20000 if smoke else 200000
    lk = lockwatch.named_lock("bench.probe")

    def pairs_ns(n):
        t0 = _time.perf_counter()
        for _ in range(n):
            lk.acquire()
            lk.release()
        return (_time.perf_counter() - t0) / n * 1e9

    lockwatch.disable()
    pairs_ns(reps // 10)  # warm
    pair_ns_off = min(pairs_ns(reps) for _ in range(3))
    lockwatch.enable()
    lockwatch.reset()
    pairs_ns(reps // 10)
    pair_ns_on = min(pairs_ns(reps) for _ in range(3))
    pair_delta_ns = max(pair_ns_on - pair_ns_off, 0.0)

    # -- (2) group-kvstore soak with membership churn -------------------------
    from mxnet_tpu import kvstore as kv_mod

    lockwatch.reset()
    rounds = 30 if smoke else 200
    churn_at = rounds // 3
    workers = kv_mod.create_group(4, op_timeout=120.0)
    server = workers[0]._server
    server.init("k", np.zeros((256,), np.float32))
    soak_rounds = {0: rounds, 1: rounds, 2: rounds, 3: churn_at}

    def run_worker(rank):
        w = workers[rank]
        for _ in range(soak_rounds[rank]):
            w.push("k", NDArray(np.ones((256,), np.float32)))

    ts = [threading.Thread(target=run_worker, args=(r,), daemon=True)
          for r in range(4)]
    for t in ts:
        t.start()
    ts[3].join(timeout=300)           # rank 3 dies after churn_at rounds
    _time.sleep(0.05)                 # survivors block in the open round
    server.deregister_worker(3)       # churn inside the open round
    for t in ts[:3]:
        t.join(timeout=300)
    server.register_worker(3)         # rejoin between rounds (idempotent)
    kv_hung = any(t.is_alive() for t in ts)
    kv_cycles = len(lockwatch.report()["cycles"])

    # -- (3) elastic fit soak: dp-4 -> 3 -> 4 under the watchdog --------------
    # full-size layer dims in BOTH modes: the overhead ratio's denominator
    # must be a realistic step, not a toy one (smoke only trims rows/epochs)
    lockwatch.reset()
    dim, hidden, classes = 256, 1024, 16
    batch, n_rows = 96, 960 if smoke else 3840   # 96 % 12 == 0: 4 and 3
    epochs = 4 if smoke else 6

    data = mx.sym.Variable("data")
    h1 = mx.sym.Activation(mx.sym.FullyConnected(
        data, name="fc1", num_hidden=hidden), name="a1", act_type="tanh")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        h1, name="fc2", num_hidden=classes), name="softmax")
    model = mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(world)],
                           num_epoch=epochs, optimizer="sgd",
                           learning_rate=0.05)
    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    telemetry.reset()
    telemetry.measured_peak_flops()

    co = ElasticCoordinator(world)

    def drive(param):
        if param.epoch == 1 and param.nbatch == 2 and co.world_size == 4:
            co.kill()
        if param.epoch == 2 and param.nbatch == 2 and co.world_size == 3:
            co.join_all()

    tmp = tempfile.mkdtemp(prefix="mxtpu_lockwatch_bench_")
    acq0 = lockwatch.watcher().acquires
    model.fit(X, y, batch_size=batch, elastic=co,
              sharded_checkpoint_dir=os.path.join(tmp, "ckpt"),
              batch_end_callback=drive, telemetry=True)
    acq1 = lockwatch.watcher().acquires
    rep = lockwatch.report()
    fit_cycles = len(rep["cycles"])
    lockwatch.publish()

    spans = model.telemetry.steps()
    durs = sorted(s.duration for s in spans)
    step_ms = durs[len(durs) // 2] * 1e3 if durs else 0.0
    total_steps = max(len(spans), 1)
    acquires_per_step = (acq1 - acq0) / total_steps
    overhead_pct = (acquires_per_step * pair_delta_ns) / (step_ms * 1e6) \
        * 100.0 if step_ms else 0.0
    lockwatch.disable()

    result = {
        "metric": "lockwatch_overhead_pct_of_step",
        "value": round(overhead_pct, 4),
        "unit": "%",
        "vs_baseline": round(overhead_pct, 4),
        "pair_ns_off": round(pair_ns_off, 1),
        "pair_ns_on": round(pair_ns_on, 1),
        "pair_delta_ns": round(pair_delta_ns, 1),
        "acquires_per_step": round(acquires_per_step, 1),
        "step_ms": round(step_ms, 3),
        "steps": total_steps,
        "cycles": fit_cycles,
        "max_hold_ms": rep["max_hold_ms"],
        "stalls": len(rep["stalls"]),
        "kv_soak": {"workers": 4, "rounds": rounds,
                    "churn_resizes": 2, "cycles": kv_cycles,
                    "hung": bool(kv_hung)},
        "resizes": co.resizes,
        "worlds": [h["to"] for h in co.history],
        "smoke": bool(smoke),
        "notes": (
            "overhead priced as pair-microbench delta x acquisitions/"
            "step / step time (robust to shared-box noise; two timed "
            "full runs swing +-17% for identical binaries, "
            "BENCH_NOTES_r06). acceptance: zero lock-order cycles "
            "across the group-kvstore churn soak AND the elastic "
            "resize fit, overhead <2% of a dp-4 step."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_LOCKWATCH_r14.json", smoke=smoke)


def run_ckpt_bench(args):
    """--ckpt-bench: price the async multi-tier checkpoint plane
    (ISSUE 17) on the dp-8 CPU mesh. Three measurements:

      1. the step-loop stall per checkpoint — the T0 capture+submit wall
         (one blocking device->host copy, writer thread owns the rest)
         vs the synchronous durable save wall on the same training state.
         Acceptance: async stall < 10% of the sync wall.
      2. the recovery wall on an 8 -> 6 elastic resize: peer (T1, RAM)
         restore vs a chaos-forced disk (T2) restore of the same run.
      3. checkpoint badput per epoch at three cadences (every 1/4/16
         steps), as priced by the epoch goodput report.

    Emits one JSON line; full runs write BENCH_CKPT_r19.json."""
    import statistics
    import tempfile
    import time as _time

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.resilience import (ElasticCoordinator, chaos_scope,
                                      ckpt_async)
    from mxnet_tpu.utils import checkpoint as ckpt_mod

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    world = 8
    if len(jax.devices()) < world:
        print(json.dumps({"metric": "ckpt_async_stall_pct_of_sync",
                          "value": 0, "unit": "%", "vs_baseline": 0,
                          "error": f"need {world} devices"}))
        return
    smoke = args.smoke
    dim, hidden, classes = (32, 64, 4) if smoke else (256, 1024, 32)
    batch, n_rows = (48, 480) if smoke else (192, 3840)
    reps = 5 if smoke else 20

    def build(epochs):
        data = mx.sym.Variable("data")
        h1 = mx.sym.Activation(mx.sym.FullyConnected(
            data, name="fc1", num_hidden=hidden), name="a1",
            act_type="tanh")
        out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
            h1, name="fc2", num_hidden=classes), name="softmax")
        return mx.FeedForward(out, ctx=[mx.cpu(i) for i in range(world)],
                              num_epoch=epochs, optimizer="sgd",
                              learning_rate=0.05)

    rng = np.random.RandomState(0)
    X = rng.randn(n_rows, dim).astype(np.float32)
    y = rng.randint(0, classes, (n_rows,)).astype(np.float32)
    steps_per_epoch = n_rows // batch
    os.environ.setdefault("MXNET_TPU_CKPT_KEEP", "0")  # GC out of the timing

    # -- 1. per-checkpoint step stall: T0 capture+submit vs sync save ------
    tmp = tempfile.mkdtemp(prefix="mxtpu_ckpt_bench_")
    d_state = os.path.join(tmp, "state")
    model = build(1)
    model.fit(X, y, batch_size=batch, sharded_checkpoint_dir=d_state)
    loaded, laux, _, _, opt_leaves = ckpt_mod.load_sharded(d_state)
    mesh = make_mesh(dp=world)
    repl = NamedSharding(mesh, P())
    params = {k: jax.device_put(np.asarray(v), repl)
              for k, v in loaded.items()}
    opt = None if opt_leaves is None else \
        [jax.device_put(np.asarray(l), repl) for l in opt_leaves]

    d_async = os.path.join(tmp, "async")
    writer = ckpt_async.AsyncCheckpointWriter(d_async, queue_depth=2,
                                              keep_last_k=0)
    async_ms, step_id = [], 0
    try:
        for _ in range(reps):
            step_id += 1
            t0 = _time.perf_counter()
            snap = ckpt_async.capture_snapshot(
                step_id, params, opt_state=opt,
                meta={"num_update": step_id})
            writer.submit(snap)
            async_ms.append((_time.perf_counter() - t0) * 1e3)
            writer.flush(timeout=120)  # drain OUTSIDE the stall timer
    finally:
        writer.close()
    d_sync = os.path.join(tmp, "sync")
    sync_ms = []
    for _ in range(reps):
        step_id += 1
        t0 = _time.perf_counter()
        ckpt_async.save_now(d_sync, step_id, params, opt_state=opt,
                            extra_meta={"num_update": step_id})
        sync_ms.append((_time.perf_counter() - t0) * 1e3)
    async_stall = statistics.median(async_ms)
    sync_wall = statistics.median(sync_ms)
    stall_pct = 100.0 * async_stall / sync_wall if sync_wall else None

    # -- 2. resize recovery wall: peer (T1) vs chaos-forced disk (T2) ------
    def resize_run(chaos_rules=None):
        telemetry.reset()
        co = ElasticCoordinator(world)

        def drive(param):
            if param.epoch == 1 and param.nbatch == 2 and \
                    co.world_size == world:
                co.kill()
                co.kill()

        m = build(3)
        d = tempfile.mkdtemp(prefix="mxtpu_ckpt_bench_el_")
        kw = dict(batch_size=batch, elastic=co, sharded_checkpoint_dir=d,
                  checkpoint_every_n_steps=2, batch_end_callback=drive)
        it = mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False)
        if chaos_rules:
            with chaos_scope(seed=0, rules=chaos_rules):
                m.fit(it, **kw)
        else:
            m.fit(it, **kw)
        assert co.resizes == 1
        events = telemetry.hub().events("checkpoint")
        tier = "t1" if any(e.get("tier") == "t1" for e in events) else "t2"
        return co.history[0]["downtime_s"], tier

    peer_recovery_s, peer_tier = resize_run()
    disk_recovery_s, disk_tier = resize_run({"ckpt.replica": 1.0})

    # -- 3. checkpoint badput per epoch at three cadences ------------------
    badput_by_cadence = {}
    for every in (1, 4, 16):
        telemetry.reset()
        jsonl = os.path.join(tmp, f"events_{every}.jsonl")
        m = build(2)
        m.fit(mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False),
              batch_size=batch,
              sharded_checkpoint_dir=os.path.join(tmp, f"cad{every}"),
              checkpoint_every_n_steps=every,
              telemetry=telemetry.TelemetryConfig(jsonl=jsonl))
        events = telemetry.read_events(jsonl)
        ckpt_s = [float(e.get("seconds", 0.0)) for e in events
                  if e.get("kind") == "badput"
                  and e.get("reason") == "checkpoint"]
        walls = [float(e.get("seconds", 0.0)) for e in events
                 if e.get("kind") == "epoch_summary"]
        per_epoch = sum(ckpt_s) / max(1, len(walls))
        badput_by_cadence[str(every)] = {
            "badput_s_per_epoch": round(per_epoch, 4),
            "badput_pct_of_wall": round(
                100.0 * sum(ckpt_s) / sum(walls), 2) if sum(walls) else None,
        }

    result = {
        "metric": "ckpt_async_stall_pct_of_sync",
        "value": round(stall_pct, 2) if stall_pct is not None else None,
        "unit": "%",
        "vs_baseline": round(sync_wall, 3),
        "async_stall_ms": round(async_stall, 3),
        "sync_save_ms": round(sync_wall, 3),
        "peer_recovery_s": round(peer_recovery_s, 4),
        "disk_recovery_s": round(disk_recovery_s, 4),
        "peer_recovery_tier": peer_tier,
        "disk_recovery_tier": disk_tier,
        "badput_by_cadence": badput_by_cadence,
        "reps": reps, "steps_per_epoch": steps_per_epoch,
        "batch": batch, "world": world,
        "smoke": bool(smoke),
        "notes": (
            "headline = the step-loop stall per checkpoint (T0 capture+"
            "submit) as % of the synchronous durable save wall on the "
            "same state; acceptance <10%. peer vs disk recovery is the "
            "8->6 resize downtime with the T1 RAM tier live vs chaos-"
            "killed (ckpt.replica) forcing the T2 disk read. badput rows "
            "are the epoch goodput report's `checkpoint` bucket."),
    }
    print(json.dumps(result))
    _publish(result, "BENCH_CKPT_r19.json", smoke=smoke)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--layout", choices=("NCHW", "NHWC"), default="NHWC")
    ap.add_argument("--mode", choices=("train", "pipeline", "io"),
                    default="train",
                    help="train: synthetic-fed fused step (headline); "
                         "pipeline: input pipeline only; io: fit() fed by "
                         "ImageRecordIter end-to-end")
    ap.add_argument("--recordio", default="/tmp/mxtpu_bench_imagenet.rec")
    ap.add_argument("--num-images", type=int, default=1024)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--model", choices=("resnet50", "inception_bn"),
                    default="resnet50",
                    help="resnet50: headline; inception_bn: the BASELINE "
                         "anchor architecture itself (97 img/s on GTX 980) "
                         "for a same-architecture comparison")
    ap.add_argument("--comm-bench", action="store_true",
                    help="gradient-sync wire bytes + step time per "
                         "compression mode (none/bf16/int8/twobit) on the "
                         "8-virtual-device CPU mesh; emits "
                         "BENCH_COMM_r08.json (full run)")
    ap.add_argument("--overlap-bench", action="store_true",
                    help="comm/compute overlap: per-bucket schedule "
                         "structure on the dp-8 mesh (HLO pair count, "
                         "exact plan sums) + stale-sync pipelined vs "
                         "serial kvstore step time; emits "
                         "BENCH_OVERLAP_r11.json (full run)")
    ap.add_argument("--smoke", action="store_true",
                    help="with --comm-bench/--telemetry-bench/"
                         "--overlap-bench: tiny shapes, no file written "
                         "(the CI guards in tests/test_bench_entry.py)")
    ap.add_argument("--telemetry-bench", action="store_true",
                    help="telemetry-hub overhead (emit/observe/counter "
                         "cost, fit with vs without the step timeline) on "
                         "the 8-virtual-device CPU mesh; emits "
                         "BENCH_TELEMETRY_r09.json (full run)")
    ap.add_argument("--elastic-bench", action="store_true",
                    help="measure elastic-resize downtime (kill 2 of 8 "
                         "virtual workers mid-epoch, continue on 6, regrow "
                         "to 8) and post-resize goodput on the CPU mesh; "
                         "emits one JSON line, full runs write "
                         "BENCH_ELASTIC_r13.json")
    ap.add_argument("--ckpt-bench", action="store_true",
                    help="async multi-tier checkpoint plane (ISSUE 17): "
                         "T0 capture+submit stall vs sync save wall "
                         "(acceptance <10%%), peer (RAM) vs disk recovery "
                         "on a dp-8 resize, checkpoint badput at 3 "
                         "cadences -> BENCH_CKPT_r19.json (one JSON line "
                         "with --smoke)")
    ap.add_argument("--controller-bench", action="store_true",
                    help="fleet-controller acceptance (ISSUE 12): inject "
                         "a persistent straggler + flaky rank into dp-8 "
                         "fits with and without the armed controller; "
                         "headline = fraction of per-chip goodput "
                         "recovered -> BENCH_CONTROLLER_r15.json (one "
                         "JSON line with --smoke)")
    ap.add_argument("--kernel-bench", action="store_true",
                    help="Pallas kernel layer (ISSUE 13): per-kernel "
                         "roofline rows (registry FLOP/byte models vs "
                         "measured time), fused-vs-unfused quantize HLO "
                         "pass counts on the dp-8 exchange, fused-Adam "
                         "step-time delta -> BENCH_KERNELS_r16.json (one "
                         "JSON line with --smoke)")
    ap.add_argument("--lockwatch-bench", action="store_true",
                    help="price the runtime lock-order watchdog (ISSUE "
                         "11): group-kvstore churn + elastic-resize fit "
                         "soaks under the watchdog, zero-cycle + <2%% "
                         "overhead acceptance -> BENCH_LOCKWATCH_r14."
                         "json (one JSON line with --smoke)")
    ap.add_argument("--mem-bench", action="store_true",
                    help="measure memory-observability overhead (live-"
                         "array ledger + phase-boundary sampler) on the "
                         "8-virtual-device CPU mesh; emits one JSON line, "
                         "full runs write BENCH_MEM_r12.json")
    ap.add_argument("--profile-bench", action="store_true",
                    help="device-time profiler acceptance (ISSUE 15): "
                         "attribution coverage of a profiled dp-8 fit "
                         "window (>=80%%), top-K hotspot table, measured "
                         "roofline rows, measured-vs-modeled MFU delta, "
                         "out-of-window overhead (<0.5%%) -> "
                         "BENCH_PROFILE_r18.json (one JSON line with "
                         "--smoke)")
    ap.add_argument("--health-bench", action="store_true",
                    help="price the in-graph training-health stats engine "
                         "on the dp-8 CPU mesh (FLOP-model overhead, "
                         "per-layer table, injected-anomaly detection "
                         "latency) -> BENCH_HEALTH_r17.json (full run)")
    ap.add_argument("--trace-bench", action="store_true",
                    help="flight-recorder + distributed-trace propagation "
                         "overhead on the dp-8 fused step (the ISSUE 6 "
                         "<2%% acceptance bound); emits "
                         "BENCH_TRACE_r10.json (full run)")
    ap.add_argument("--compile-bench", action="store_true",
                    help="cold vs warm (persistent compilation cache) "
                         "time-to-first-step + AOT warmup wall time; "
                         "emits BENCH_COMPILE_r07.json")
    ap.add_argument("--compile-bench-child",
                    choices=("plain", "aot"), default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--remat", nargs="?", const=r"unit\d+_out$", default="",
                    help="rematerialize activations per residual unit "
                         "(MXNET_TPU_REMAT boundary regex; bare --remat "
                         "uses the ResNet unit boundaries) — trades MXU "
                         "recompute for HBM traffic on the bandwidth-bound "
                         "step")
    args = ap.parse_args()
    if args.remat:
        os.environ["MXNET_TPU_REMAT"] = args.remat

    if args.comm_bench:
        # CPU-mesh bench by design (see run_comm_bench): force the cpu
        # platform + 8 virtual devices BEFORE the first jax import so the
        # collective plan is inspectable without hardware
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_comm_bench(args)
        return

    if args.overlap_bench:
        # same CPU-mesh rig as --comm-bench: schedule structure and the
        # stale-sync pipeline are measurable without hardware
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_overlap_bench(args)
        return

    if args.kernel_bench:
        # same CPU-mesh rig: interpret-mode kernels + HLO structure are
        # measurable without hardware (the roofline row schema is the
        # TPU contract; on-chip rates come from the tunnel runs)
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_kernel_bench(args)
        return

    if args.telemetry_bench:
        # same CPU-mesh rig as --comm-bench: the hub/timeline tax is a
        # host-side number, measurable without hardware
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_telemetry_bench(args)
        return

    if args.trace_bench:
        # same CPU-mesh rig: the flight/trace tax is host-side
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_trace_bench(args)
        return

    if args.mem_bench:
        # same CPU-mesh rig: ledger/sampler tax is host-side bookkeeping
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_mem_bench(args)
        return

    if args.profile_bench:
        # same CPU-mesh rig: the capture/attribution machinery is
        # backend-agnostic (the trace parser reads the CPU backend's
        # instruction lanes; a TPU xplane dump feeds the same tables)
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_profile_bench(args)
        return

    if args.health_bench:
        # same CPU-mesh rig: the stats live inside the fused step, so the
        # FLOP-model overhead and the detector latency are measurable
        # without hardware
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_health_bench(args)
        return

    if args.lockwatch_bench:
        # same CPU-mesh rig: lock bookkeeping is host-side, and the two
        # soaked paths (group kvstore, elastic resize) run without hardware
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_lockwatch_bench(args)
        return

    if args.ckpt_bench:
        # same CPU-mesh rig: the snapshot stall, writer drain and both
        # recovery tiers are host+virtual-world paths, no hardware needed
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_ckpt_bench(args)
        return

    if args.elastic_bench:
        # same CPU-mesh rig: the resize protocol (quiesce/reshard/replan/
        # rewarm) is fully exercisable on the 8-virtual-device world
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_elastic_bench(args)
        return

    if args.controller_bench:
        # same CPU-mesh rig: the sense->decide->actuate loop (blame,
        # evict, backfill, retier) runs end-to-end on the virtual world
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        run_controller_bench(args)
        return

    if args.compile_bench_child:
        # measured subprocess of --compile-bench: no watchdog/probe — the
        # parent bounds each child's runtime
        if args.batch_size > 64:
            args.batch_size = 64
        run_compile_bench_child(args)
        return
    if args.compile_bench:
        if args.batch_size > 64:
            args.batch_size = 64  # compile cost, not throughput, is measured
        run_compile_bench(args)
        return

    # Watchdog first: EVERY mode that can touch the tunnel must fail fast
    # when it wedges (see the note below) instead of eating the driver's
    # timeout budget. Installed before mode dispatch.
    import faulthandler
    import threading

    deadline = int(os.environ.get("MXTPU_BENCH_DEADLINE_SEC", "1500"))

    def _watchdog():
        print(f"bench watchdog: no result within {deadline}s — the TPU "
              "tunnel is likely wedged (see BENCH_NOTES_r03.md section 6); "
              "dumping stacks and exiting", file=sys.stderr)
        faulthandler.dump_traceback(file=sys.stderr)
        os._exit(3)

    timer = threading.Timer(deadline, _watchdog)
    timer.daemon = True
    timer.start()

    if args.mode == "pipeline":
        # host-only benchmark: force the cpu platform so NDArray creation
        # never initializes the (possibly wedged) remote backend
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        run_pipeline_bench(args)
        timer.cancel()
        return
    # Init-stage guard BEFORE any remaining mode touches jax.devices():
    # a wedged client init is unrecoverable in-process (r03/r04
    # post-mortem). pipeline mode returned above — it forces CPU.
    if not probe_backend_init():
        print("bench: backend init unreachable after retries; exiting rc=3 "
              "(tunnel wedged at client init)", file=sys.stderr)
        os._exit(3)

    if args.mode == "io":
        run_io_bench(args)
        return

    import jax

    # (watchdog active from mode dispatch above)
    # Persistent compilation cache: the tunnel's compile service degrades
    # unpredictably (round 2's capture died on it; this session saw ResNet
    # compiles go from ~40 s to >25 min). A warm on-disk cache makes the
    # bench independent of compile-service health.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("MXTPU_JAX_CACHE_DIR",
                                         "/tmp/mxtpu_jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception as e:  # pragma: no cover - older jax
        print(f"compilation cache unavailable: {e}", file=sys.stderr)

    dev = with_retries(lambda: jax.devices()[0], what="device init")
    print(f"bench device: {dev}", file=sys.stderr)

    step, params, moms, aux = build_train_step(
        args.batch_size, layout=args.layout, model=args.model)
    rng = np.random.RandomState(0)
    data = jax.device_put(
        rng.randn(*_data_shape(args.batch_size, args.layout)).astype(np.float32))
    label = jax.device_put(
        rng.randint(0, 1000, (args.batch_size,)).astype(np.float32))

    import jax.numpy as jnp

    # Self-accounting FLOPs: XLA's cost analysis of the exact compiled step.
    step_gflops = None
    try:
        compiled = step.lower(params, moms, aux, data, label).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        if ca and "flops" in ca:
            step_gflops = float(ca["flops"]) / 1e9
    except Exception as e:  # noqa: BLE001 - cost analysis is best-effort
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)

    def fence():
        # Through the remote-TPU tunnel, block_until_ready acks before the
        # device queue drains; a scalar readback is the only honest sync.
        return float(jnp.sum(params["fc1_bias"]))

    # Timed region runs ON DEVICE (fori_loop, dynamic trip count) and the
    # per-step cost is the slope between a short and a long run — each
    # Python-level dispatch through the tunnel costs ~5-10 ms, which at
    # ~100 ms steps would shave ~7% off the reported number.
    def loop_step(s):
        p, m, a = step(s[0], s[1], s[2], data, label)
        return (p, m, a)

    @jax.jit
    def run(s, k):
        return jax.lax.fori_loop(0, k, lambda i, t: loop_step(t), s)

    if args.steps < 8:
        print(f"--steps {args.steps} too small for slope timing "
              "(need >=8); raising to 8", file=sys.stderr)
        args.steps = 8
    k1 = max(2, args.steps // 4)
    k2 = args.steps

    def timed_run():
        nonlocal params, moms, aux
        state = (params, moms, aux)
        state = run(state, k1)  # compile + warm
        float(jnp.sum(state[0]["fc1_bias"]))
        t0 = time.perf_counter()
        state = run(state, k1)
        float(jnp.sum(state[0]["fc1_bias"]))
        t1 = time.perf_counter()
        state = run(state, k2)
        float(jnp.sum(state[0]["fc1_bias"]))
        t2 = time.perf_counter()
        params, moms, aux = state
        return ((t2 - t1) - (t1 - t0)) / (k2 - k1)

    try:
        step_time = with_retries(timed_run, what="train step")
        timing = "device_loop_slope"
    except Exception as e:  # e.g. loop-carry OOM: fall back to host loop
        print(f"device-loop timing failed ({e}); host loop", file=sys.stderr)
        for _ in range(args.warmup):
            params, moms, aux = step(params, moms, aux, data, label)
        fence()
        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, moms, aux = step(params, moms, aux, data, label)
        fence()
        step_time = (time.perf_counter() - t0) / args.steps
        timing = "host_loop"

    images_per_sec = args.batch_size / step_time

    # Honest MFU accounting (VERDICT r2 items 1-2). MFU uses the STANDARD
    # model-FLOP count (ResNet-50/224 fwd = 4.09 GFLOP at 2 FLOP/MAC,
    # train = 3x -> 12.27) so the figure is comparable across frameworks;
    # XLA's cost-analysis count of the actual compiled step (which includes
    # BN stats, recompute, optimizer arithmetic) is reported alongside.
    # Inception-BN has no standard published count at this input config, so
    # its achieved-TFLOPs derive from the XLA count (marked accordingly).
    gflop_xla = step_gflops / args.batch_size if step_gflops else None
    if args.model == "inception_bn":
        gflop_analytic = gflop_xla  # XLA-counted; no standard figure
    else:
        gflop_analytic = 12.27
    achieved_tflops = (images_per_sec * gflop_analytic / 1e3
                       if gflop_analytic else 0.0)
    try:
        peak = with_retries(measured_matmul_peak_tflops, what="peak matmul")
    except Exception:
        peak = None

    timer.cancel()
    baseline = 97.0  # Inception-BN img/s, 1x GTX 980 cuDNN v3 (BASELINE.md)
    # resnet50: same-FLOP-class comparison; inception_bn: SAME ARCHITECTURE
    # as the anchor — the apples-to-apples number
    print(json.dumps({
        "metric": f"{args.model}_imagenet_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / baseline, 3),
        "baseline_comparison": ("same_architecture"
                                if args.model == "inception_bn"
                                else "same_flop_class"),
        "step_ms": round(step_time * 1e3, 2),
        "batch_size": args.batch_size,
        "gflop_per_image": gflop_analytic,
        "gflop_per_image_xla_cost_model": (round(gflop_xla, 2)
                                           if gflop_xla else None),
        "achieved_model_tflops": (round(achieved_tflops, 1)
                                  if gflop_analytic else None),
        "measured_matmul_peak_tflops": round(peak, 1) if peak else None,
        "mfu_vs_measured_peak": (round(achieved_tflops / peak, 3)
                                 if peak and gflop_analytic else None),
        "mfu_vs_nominal": (round(achieved_tflops / NOMINAL_BF16_TFLOPS, 3)
                           if gflop_analytic else None),
        "timing": timing,
    }))


if __name__ == "__main__":
    main()
