"""Headline benchmark: ResNet-50 ImageNet training throughput per chip.

Prints ONE JSON line:
  {"metric": "resnet50_imagenet_train_images_per_sec_per_chip",
   "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: the reference (dawdle/mxnet v0.5) publishes no ResNet-50 number
(the model postdates it). The closest published anchor in the same
FLOP class (~4 GFLOPs/image) is Inception-BN at 97 img/s on 1x GTX 980 with
cuDNN v3 (reference example/imagenet/README.md:40, mirrored in BASELINE.md),
so vs_baseline = value / 97.0 — "how much faster than the reference's best
same-class single-device training throughput".

Method: fused train step (forward + backward + SGD-momentum update in one
donated XLA program), NHWC activations (channels on the MXU lane dimension;
weights stay OIHW for checkpoint parity), bf16 compute / f32 master params,
one-pass-statistics BatchNorm, synthetic on-device data (the input pipeline
is benchmarked separately; the reference's numbers are likewise decode-bound
only beyond 3000 img/s, README:5). Warmup 2 steps (compile), then timed
steps with a hard device sync at the end.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _data_shape(batch_size, layout):
    return (batch_size, 224, 224, 3) if layout == "NHWC" else \
        (batch_size, 3, 224, 224)


def build_resnet50_train_step(batch_size, lr=0.1, momentum=0.9, layout="NHWC"):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.executor import _build_graph_fn
    from mxnet_tpu.models import resnet50

    sym = resnet50(num_classes=1000, layout=layout)
    input_shapes = {"data": _data_shape(batch_size, layout),
                    "softmax_label": (batch_size,)}
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()

    rng = np.random.RandomState(0)
    params = {}
    for name, shape in zip(arg_names, arg_shapes):
        if name in input_shapes:
            continue
        scale = 0.1 if name.endswith(("gamma", "bias", "beta")) else \
            float(np.sqrt(2.0 / max(1, int(np.prod(shape[1:])))))
        if name.endswith("gamma"):
            params[name] = jnp.ones(shape, jnp.float32)
        elif name.endswith(("beta", "bias")):
            params[name] = jnp.zeros(shape, jnp.float32)
        else:
            params[name] = jnp.asarray((rng.randn(*shape) * scale).astype(np.float32))
    aux = {name: (jnp.ones(s, jnp.float32) if name.endswith("var")
                  else jnp.zeros(s, jnp.float32))
           for name, s in zip(aux_names, aux_shapes)}
    moms = {k: jnp.zeros_like(v) for k, v in params.items()}

    graph_fn = _build_graph_fn(sym, is_train=True)
    zero_key = jnp.zeros((2,), jnp.uint32)
    rescale = 1.0 / batch_size

    def step(params, moms, aux, data, label):
        def loss_fn(p):
            p_c = {k: v.astype(jnp.bfloat16) for k, v in p.items()}
            outs, new_aux = graph_fn(
                {**p_c, "data": data.astype(jnp.bfloat16), "softmax_label": label},
                aux, zero_key)
            return jnp.sum(outs[0].astype(jnp.float32)), new_aux

        grads, new_aux = jax.grad(loss_fn, has_aux=True)(params)
        new_moms = {k: momentum * moms[k] + grads[k] * rescale for k in params}
        new_params = {k: params[k] - lr * new_moms[k] for k in params}
        return new_params, new_moms, new_aux

    jitted = jax.jit(step, donate_argnums=(0, 1, 2))
    return jitted, params, moms, aux


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--layout", choices=("NCHW", "NHWC"), default="NHWC")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    step, params, moms, aux = build_resnet50_train_step(
        args.batch_size, layout=args.layout)
    rng = np.random.RandomState(0)
    data = jax.device_put(
        rng.randn(*_data_shape(args.batch_size, args.layout)).astype(np.float32))
    label = jax.device_put(
        rng.randint(0, 1000, (args.batch_size,)).astype(np.float32))

    import jax.numpy as jnp

    def fence():
        # Through the remote-TPU tunnel, block_until_ready acks before the
        # device queue drains; a scalar readback is the only honest sync.
        return float(jnp.sum(params["fc1_bias"]))

    for _ in range(args.warmup):
        params, moms, aux = step(params, moms, aux, data, label)
    fence()

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, moms, aux = step(params, moms, aux, data, label)
    fence()
    dt = time.perf_counter() - t0

    images_per_sec = args.batch_size * args.steps / dt
    baseline = 97.0  # Inception-BN img/s, 1x GTX 980 cuDNN v3 (BASELINE.md)
    print(json.dumps({
        "metric": "resnet50_imagenet_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / baseline, 3),
    }))


if __name__ == "__main__":
    main()
